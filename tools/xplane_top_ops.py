"""Minimal XSpace (jax.profiler xplane.pb) parser: prints top TPU ops by
self-time. No tensorflow/tensorboard dependency — raw protobuf wire decode.

Usage: python tools/xplane_top_ops.py /tmp/jaxtrace [N]
"""
import glob
import sys


def _varint(buf, i):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def fields(buf):
    """Yield (field_no, wire_type, value) over a protobuf message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"wire type {wt}")
        yield fno, wt, v


def parse(path, topn=20):
    xs = open(path, "rb").read()
    for fno, _wt, plane in fields(xs):
        if fno != 1:
            continue
        name = b""
        lines = []
        emeta = {}
        for pf, _, pv in fields(plane):
            if pf == 2:
                name = pv
            elif pf == 3:
                lines.append(pv)
            elif pf == 4:   # map entry: key=1 varint, value=2 XEventMetadata
                k = None
                v = b""
                for mf, _, mv in fields(pv):
                    if mf == 1:
                        k = mv
                    elif mf == 2:
                        v = mv
                mname = b""
                for ef, _, ev in fields(v):
                    if ef == 2:
                        mname = ev
                emeta[k] = mname.decode(errors="replace")
        nm = name.decode(errors="replace")
        if "TPU" not in nm and "/device" not in nm:
            continue
        agg = {}
        total = 0
        for line in lines:
            lname = b""
            events = []
            for lf, _, lv in fields(line):
                if lf == 2:
                    lname = lv
                elif lf == 6:
                    events.append(lv)
            if b"XLA Ops" not in lname:
                continue
            for ev in events:
                mid = dur = occ = 0
                for ef, _, evv in fields(ev):
                    if ef == 1:
                        mid = evv
                    elif ef == 3:
                        dur = evv
                    elif ef == 5:
                        occ = evv
                d = dur * max(occ, 1)
                agg[emeta.get(mid, str(mid))] = \
                    agg.get(emeta.get(mid, str(mid)), 0) + d
                total += d
        if not agg:
            continue
        print(f"== plane {nm}  total {total/1e9:.1f} ms (XLA Ops self-time)")
        for op, t in sorted(agg.items(), key=lambda kv: -kv[1])[:topn]:
            print(f"  {t/total*100:5.1f}%  {t/1e9:9.2f}ms  {op[:95]}")


if __name__ == "__main__":
    root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/jaxtrace"
    topn = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    paths = sorted(glob.glob(root + "/plugins/profile/*/*.xplane.pb"))
    if not paths:
        sys.exit(f"no xplane.pb under {root}")
    parse(paths[-1], topn)
