"""Minimal XSpace (jax.profiler xplane.pb) parser: prints top TPU ops by
self-time. No tensorflow/tensorboard dependency — raw protobuf wire decode.

Usage: python tools/xplane_top_ops.py /tmp/jaxtrace [N]
"""
import glob
import sys


def _varint(buf, i):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def fields(buf):
    """Yield (field_no, wire_type, value) over a protobuf message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"wire type {wt}")
        yield fno, wt, v


def device_op_times(path, window_ps=None):
    """Per-device-plane XLA op times from one xplane.pb.

    Returns ``[{"plane", "busy_ps", "sum_ps", "ops"}]`` for TPU/device
    planes (durations are picoseconds in XSpace):

    * ``busy_ps`` — the interval UNION of all op events: true device-busy
      time (the "XLA Ops" line nests control-flow parents with their body
      ops, so plain summation double-counts);
    * ``sum_ps`` / ``ops`` — per-op INCLUSIVE durations (a while loop
      carries its body's time), the ranking signal for "where does device
      time go".

    ``window_ps`` keeps only events in the last ``window_ps`` before the
    latest event end (some libtpu builds dump ops beyond the capture
    window).
    """
    xs = open(path, "rb").read()
    out = []
    for fno, _wt, plane in fields(xs):
        if fno != 1:
            continue
        name = b""
        lines = []
        emeta = {}
        for pf, _, pv in fields(plane):
            if pf == 2:
                name = pv
            elif pf == 3:
                lines.append(pv)
            elif pf == 4:   # map entry: key=1 varint, value=2 XEventMetadata
                k = None
                v = b""
                for mf, _, mv in fields(pv):
                    if mf == 1:
                        k = mv
                    elif mf == 2:
                        v = mv
                mname = b""
                for ef, _, ev in fields(v):
                    if ef == 2:
                        mname = ev
                emeta[k] = mname.decode(errors="replace")
        nm = name.decode(errors="replace")
        if "TPU" not in nm and "/device" not in nm:
            continue
        parsed = []       # (metadata_id, offset_ps, dur_ps, occurrences)
        for line in lines:
            lname = b""
            events = []
            for lf, _, lv in fields(line):
                if lf == 2:
                    lname = lv
                elif lf in (4, 6):
                    # XLine.events: field 4 in current libtpu XSpace
                    # builds, 6 in older ones
                    events.append(lv)
            if lname != b"XLA Ops":     # NOT "Async XLA Ops": async copy
                continue                # events overlap compute self-time
            for ev in events:
                mid = off = dur = occ = 0
                for ef, _, evv in fields(ev):
                    if ef == 1:
                        mid = evv
                    elif ef == 2:
                        off = evv
                    elif ef == 3:
                        dur = evv
                    elif ef == 5:
                        occ = evv
                parsed.append((mid, off, dur, occ))
        if not parsed:
            continue
        if window_ps is not None:
            end = max(off + dur for _, off, dur, _ in parsed)
            parsed = [p for p in parsed if p[1] >= end - window_ps]
        agg = {}
        total = 0
        for mid, _off, dur, occ in parsed:
            d = dur * max(occ, 1)
            key = emeta.get(mid, str(mid))
            agg[key] = agg.get(key, 0) + d
            total += d
        # interval union over (offset, offset+dur): true busy time
        busy = 0
        cur_end = -1
        for _mid, off, dur, _occ in sorted(parsed, key=lambda p: p[1]):
            s, e = off, off + dur
            if s > cur_end:
                busy += e - s
                cur_end = e
            elif e > cur_end:
                busy += e - cur_end
                cur_end = e
        if agg:
            out.append({"plane": nm, "busy_ps": busy, "sum_ps": total,
                        "ops": agg})
    return out


def latest_xplane(root):
    paths = sorted(glob.glob(root + "/plugins/profile/*/*.xplane.pb"))
    return paths[-1] if paths else None


def parse(path, topn=20):
    for p in device_op_times(path):
        total = p["sum_ps"]
        print(f"== plane {p['plane']}  busy {p['busy_ps']/1e9:.1f} ms "
              f"(inclusive sum {total/1e9:.1f} ms)")
        for op, t in sorted(p["ops"].items(), key=lambda kv: -kv[1])[:topn]:
            print(f"  {t/total*100:5.1f}%  {t/1e9:9.2f}ms  {op[:95]}")


if __name__ == "__main__":
    root = sys.argv[1] if len(sys.argv) > 1 else "/tmp/jaxtrace"
    topn = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    paths = sorted(glob.glob(root + "/plugins/profile/*/*.xplane.pb"))
    if not paths:
        sys.exit(f"no xplane.pb under {root}")
    parse(paths[-1], topn)
