"""Out-of-core training leg for bench.py's ``out_of_core`` config.

Runs ONE leg per interpreter (``ru_maxrss`` is a per-process high-water
mark that never resets, so honest peak-RSS accounting needs a fresh
process per leg) and prints a single JSON line:

    python tools/bench_ooc.py <data_dir> <holdout.avro> \
        stream|materialize <cap_mb> <sample_rows>

``stream`` forces the streamed ingest (``streamFit`` on, two directory
passes, ``sample_rows`` bounded working set) and — when ``cap_mb`` > 0 —
first arms a HARD heap ceiling via ``resource.setrlimit(RLIMIT_DATA)``:
on Linux >= 4.7 the data limit covers private anonymous mmaps too, so
any allocation past the cap raises MemoryError and kills the leg. A
streamed fit that secretly materialized the event log could not survive
the cap. The cap is armed AFTER backend init and the warm-up jit (the
interpreter + compiler baseline is environment, not workload) and
BEFORE the first byte of the event log is read.

``materialize`` forces the in-memory path on the same directory with no
cap: its peak RSS is the denominator proving the event log exceeds the
budget, and its holdout metric is the parity reference.
"""
import json
import os
import sys
import time


def main() -> None:
    data_dir, holdout_fp, mode = sys.argv[1], sys.argv[2], sys.argv[3]
    cap_mb = float(sys.argv[4])
    sample_rows = int(sys.argv[5])

    import jax
    # host-memory property under test — pin the portable backend (and
    # beat any axon sitecustomize platform pin, per tools/bench_cpu.py)
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), os.pardir))
    import jax.numpy as jnp
    import numpy as np

    from transmogrifai_tpu import FeatureBuilder, Workflow, telemetry
    from transmogrifai_tpu import workflow as wfmod
    from transmogrifai_tpu.columns import PredictionColumn
    from transmogrifai_tpu.evaluators import metrics as M
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import (
        BinaryClassificationModelSelector)
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.readers.avro import read_avro_records
    from transmogrifai_tpu.readers.streaming import DirectoryStreamReader

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
             for j in range(6)]
    vec = transmogrify(feats)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily()],
        splitter=None, seed=16)
    pred = label.transform_with(selector, vec)

    # warm the backend before arming the cap: one tiny dispatch forces
    # the CPU client + compiler arenas into the baseline
    _ = jax.jit(lambda a: a + 1)(jnp.zeros((8,), jnp.float32))

    if cap_mb > 0:
        import resource
        cap = int(cap_mb) << 20
        resource.setrlimit(resource.RLIMIT_DATA, (cap, cap))

    wfmod.set_stream_fit(stream=(mode == "stream"), passes=2,
                         sample_rows=sample_rows,
                         rss_cap_mb=(cap_mb if cap_mb > 0 else None))
    wf = Workflow().set_result_features(pred)
    wf.set_reader(DirectoryStreamReader(data_dir, pattern="*.avro",
                                        settle_s=0.0))
    t0 = time.perf_counter()
    model = wf.train()
    train_s = time.perf_counter() - t0

    ho = read_avro_records(holdout_fp)
    y = np.array([r["label"] for r in ho], dtype=np.float64)
    store = model.score(ho)
    pcol = next(store[nm] for nm in store.names()
                if isinstance(store[nm], PredictionColumn))
    m = M.binary_metrics(y, pcol.prediction, pcol.probability[:, 1])

    print(json.dumps({
        "mode": mode, "cap_mb": cap_mb,
        "rows_trained": model.train_rows,
        "sample_rows": sample_rows,
        "stream_stat_columns": len(getattr(wf, "_stream_state", None)
                                   or ()),
        "train_s": round(train_s, 2),
        "holdout_AuPR": round(float(m["AuPR"]), 4),
        "peak_rss_mb": telemetry.peak_rss_mb(),
    }), flush=True)


if __name__ == "__main__":
    main()
