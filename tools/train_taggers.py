"""Offline trainer for the vendored POS / NER / sentence taggers.

The reference ships pretrained OpenNLP binaries as package resources
(``/root/reference/models/README.md:1-5``); this repo vendors its own
learned weights instead, produced by THIS script (reproducible, seeded).
There is no network egress in the build image, so no external treebank:
the supervision comes from a template-grammar corpus generator over
curated lexicons (names / organizations / locations / vocabulary with
authored POS tags). The taggers are averaged perceptrons
(``transmogrifai_tpu/utils/taggers.py``) — the same model family NLTK's
default English POS tagger uses.

Run from the repo root:  python tools/train_taggers.py
Writes transmogrifai_tpu/resources/taggers/{pos,ner,sent}.json.gz
"""
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), os.pardir))

from transmogrifai_tpu.utils.taggers import (AveragedPerceptron, NERTagger,
                                             POSTagger, SentenceSplitter,
                                             resource_dir)

FIRST_NAMES = """james mary john patricia robert jennifer michael linda
william elizabeth david barbara richard susan joseph jessica thomas sarah
charles karen christopher nancy daniel lisa matthew betty anthony helen
mark sandra donald ashley steven kimberly paul donna andrew carol joshua
michelle kenneth emily kevin amanda brian melissa george deborah timothy
stephanie ronald rebecca edward laura jason sharon jeffrey cynthia ryan
kathleen jacob amy gary angela nicholas anna eric ruth jonathan brenda
stephen pamela larry nicole justin katherine scott samantha brandon
christine benjamin catherine samuel virginia gregory rachel frank carolyn
alexander janet raymond maria patrick heather jack diane dennis julie
jerry joyce tyler victoria aaron kelly jose christina adam joan henry
evelyn nathan judith douglas megan zachary cheryl peter andrea kyle hannah
walter jacqueline ethan martha jeremy gloria harold teresa keith ann roger
madison noah olivia carl sophia arthur isabella terry emma sean ava austin
mia wei li ming chen yuki hiroshi keiko ravi priya arjun ananya omar fatima
ahmed layla carlos sofia diego valentina pierre claire luca giulia""".split()

SURNAMES = """smith johnson williams brown jones garcia miller davis
rodriguez martinez hernandez lopez gonzalez wilson anderson thomas taylor
moore jackson martin lee perez thompson white harris sanchez clark ramirez
lewis robinson walker young allen king wright scott torres nguyen hill
flores green adams nelson baker hall rivera campbell mitchell carter
roberts gomez phillips evans turner diaz parker cruz edwards collins
reyes stewart morris morales murphy cook rogers gutierrez ortiz morgan
cooper peterson bailey reed kelly howard ramos kim cox ward richardson
watson brooks chavez wood james bennett gray mendoza ruiz hughes price
alvarez castillo sanders patel myers long ross foster jimenez tanaka sato
suzuki wang zhang liu singh kumar khan ali hassan silva santos rossi
ferrari mueller schmidt fischer weber dubois laurent moreau""".split()

ORG_BASES = """acme globex initech umbrella stark wayne cyberdyne tyrell
wonka oscorp aperture vandelay hooli gringotts monarch pinnacle vertex
quantum nimbus zenith apex titan orion atlas nova polaris summit cascade
horizon beacon crescent sterling granite cobalt ember harbor meridian
catalyst fusion vector helix """.split()

ORG_SUFFIXES = ["inc", "corp", "ltd", "llc", "group", "labs",
                "industries", "systems", "holdings", "partners",
                "technologies", "bank", "university", "institute"]

LOCATIONS = """london paris tokyo berlin madrid rome moscow beijing
shanghai mumbai delhi cairo lagos nairobi sydney melbourne toronto
vancouver chicago boston seattle austin denver atlanta miami dallas
houston phoenix portland detroit memphis nashville oakland sacramento
brazil france germany spain italy russia china india egypt nigeria kenya
australia canada mexico argentina chile peru japan korea vietnam thailand
singapore malaysia indonesia texas california florida ohio georgia
washington oregon arizona colorado utah nevada montana idaho maine
amsterdam brussels vienna prague budapest warsaw lisbon dublin oslo
stockholm helsinki copenhagen zurich geneva munich hamburg lyon
barcelona seville naples milان""".replace("milان", "milan").split()

MULTI_LOCS = ["new york", "san francisco", "los angeles", "hong kong",
              "new delhi", "cape town", "buenos aires", "mexico city",
              "new orleans", "san diego", "las vegas", "kuala lumpur",
              "tel aviv", "abu dhabi", "new jersey", "south africa",
              "new zealand", "costa rica", "sri lanka", "saudi arabia"]

#: (word, PTB-ish tag) vocabulary for template slots
NOUNS = """report meeting contract budget project team engineer manager
customer product market quarter revenue profit system network model data
analysis review plan strategy launch deadline office warehouse factory
shipment invoice order payment account balance survey result study
platform service feature release update issue ticket request response
pipeline cluster server database index query table schema record""".split()
VERBS_PAST = """announced approved reviewed signed shipped launched
delivered acquired visited joined left opened closed moved hired promoted
presented finished started completed submitted rejected audited merged
deployed migrated benchmarked profiled optimized""".split()
VERBS_PRES = """announces approves reviews signs ships launches delivers
acquires visits joins opens closes moves hires promotes presents finishes
starts completes submits rejects audits merges deploys migrates""".split()
ADJECTIVES = """new big small quarterly annual final initial major minor
strategic critical strong weak early late global local technical detailed
preliminary responsive efficient reliable scalable robust""".split()
ADVERBS = """quickly slowly carefully recently finally early late soon
yesterday today tomorrow internally externally formally jointly""".split()
PREPS = "in at on for with from to of by near under over after before".split()
DETS = "the a this that each every its their our".split()
MONTHS = """january february march april may june july august september
october november december""".split()

ABBREVS = ["Dr.", "Mr.", "Mrs.", "Ms.", "Prof.", "Jr.", "Sr.", "St.",
           "Jan.", "Feb.", "Mar.", "Apr.", "Jun.", "Jul.", "Aug.", "Sep.",
           "Oct.", "Nov.", "Dec.", "U.S.", "U.K.", "Inc.", "Corp.", "Ltd.",
           "Co.", "vs.", "etc.", "e.g.", "i.e.", "No.", "Dept.", "Ave.",
           "Blvd.", "Rd."]


def _cap(w: str) -> str:
    return w[:1].upper() + w[1:]


def gen_sentence(rng: random.Random):
    """One synthetic sentence → (tokens, pos tags, ner BIO tags)."""
    toks, pos, ner = [], [], []

    def add(ts, ps, ns="O"):
        for j, t in enumerate(ts):
            toks.append(t)
            pos.append(ps[j] if isinstance(ps, list) else ps)
            if ns == "O":
                ner.append("O")
            else:
                ner.append(("B-" if j == 0 else "I-") + ns)

    def person():
        if rng.random() < 0.15:
            # honorific titles precede the name and are NOT part of it.
            # Emitted as TWO tokens ("Dr" ".") — the production
            # tokenizer (_ner_tokenize) splits trailing periods, and the
            # model must train on the token shapes it will see
            add([rng.choice(["Dr", "Mr", "Mrs", "Ms", "Prof"])], "NNP")
            add(["."], ".")
            add([_cap(rng.choice(SURNAMES))], "NNP", "PER")
            return
        parts = [_cap(rng.choice(FIRST_NAMES))]
        if rng.random() < 0.7:
            parts.append(_cap(rng.choice(SURNAMES)))
        add(parts, "NNP", "PER")

    def org():
        parts = [_cap(rng.choice(ORG_BASES))]
        if rng.random() < 0.8:
            parts.append(_cap(rng.choice(ORG_SUFFIXES)))
        add(parts, "NNP", "ORG")

    def loc():
        if rng.random() < 0.25:
            parts = [_cap(p) for p in rng.choice(MULTI_LOCS).split()]
            add(parts, "NNP", "LOC")
        else:
            add([_cap(rng.choice(LOCATIONS))], "NNP", "LOC")

    def np():
        if rng.random() < 0.6:
            add([rng.choice(DETS)], "DT")
        if rng.random() < 0.5:
            add([rng.choice(ADJECTIVES)], "JJ")
        add([rng.choice(NOUNS)], "NN")

    def date():
        add([_cap(rng.choice(MONTHS))], "NNP")
        if rng.random() < 0.6:          # standalone "in March" is common
            add([str(rng.randint(1, 28))], "CD")

    def pp(inner):
        add([rng.choice(PREPS)], "IN")
        inner()

    if rng.random() < 0.2:
        # sentence-initial adverb: capitalized non-entities must appear
        # at position 0 in training or the NER reads them as names
        add([rng.choice(ADVERBS)], "RB")
        if rng.random() < 0.5:
            add([","], ",")
    def pronoun():
        add([rng.choice(["he", "she", "they", "we", "it"])], "PRP")

    subj = rng.choice([person, org, np, np, pronoun])
    subj()
    if rng.random() < 0.25:
        add([rng.choice(ADVERBS)], "RB")
    if rng.random() < 0.7:
        add([rng.choice(VERBS_PAST)], "VBD")
    else:
        add([rng.choice(VERBS_PRES)], "VBZ")
    obj = rng.choice([np, person, org])
    obj()
    for extra in (loc, np, date):
        if rng.random() < 0.4:
            pp(extra if extra is not loc else rng.choice([loc, org, person]))
    end = rng.choice([".", ".", ".", "?", "!"])
    add([end], ".")
    # real text capitalizes sentence starts: without this the taggers
    # read ANY sentence-initial capital as a proper noun / entity
    if toks and toks[0][:1].isalpha():
        toks[0] = _cap(toks[0])
    return toks, pos, ner


def main(seed: int = 7, n_sents: int = 6000, epochs: int = 6) -> None:
    rng = random.Random(seed)
    corpus = [gen_sentence(rng) for _ in range(n_sents)]
    os.makedirs(resource_dir(), exist_ok=True)

    # -- POS --------------------------------------------------------------
    pos_classes = {t for _, ps, _ in corpus for t in ps}
    model = AveragedPerceptron(classes=sorted(pos_classes))
    data = list(corpus)
    for _ in range(epochs):
        rng.shuffle(data)
        for toks, tags, _ in data:
            prev, prev2 = POSTagger.START[1], POSTagger.START[0]
            for i in range(len(toks)):
                feats = POSTagger.features(toks, i, prev, prev2)
                guess = model.predict(feats)
                model.update(tags[i], guess, feats)
                prev2, prev = prev, tags[i]   # gold history (teacher forcing)
    model.average()
    model.save(os.path.join(resource_dir(), "pos.json.gz"))
    print("pos tagger:", len(model.weights), "features")

    # -- NER --------------------------------------------------------------
    loc_words = LOCATIONS + [w for m in MULTI_LOCS for w in m.split()]
    lexicons = {"first": FIRST_NAMES, "last": SURNAMES,
                "orgsfx": ORG_SUFFIXES, "loc": loc_words,
                "month": MONTHS}
    ner_stub = NERTagger(AveragedPerceptron(), lexicons)
    ner_classes = {t for _, _, ns in corpus for t in ns}
    model = AveragedPerceptron(classes=sorted(ner_classes))
    for _ in range(epochs):
        rng.shuffle(data)
        for toks, tags, bio in data:
            prev = "O"
            for i in range(len(toks)):
                feats = ner_stub.features(toks, i, prev, tags)
                guess = model.predict(feats)
                model.update(bio[i], guess, feats)
                prev = bio[i]
    model.average()
    model.save(os.path.join(resource_dir(), "ner.json.gz"),
               extra={"lexicons": lexicons})
    print("ner tagger:", len(model.weights), "features")

    # -- sentence splitter ------------------------------------------------
    # documents: sentences joined, with abbreviation/decimal/initials noise
    docs = []
    for _ in range(2500):
        n = rng.randint(2, 5)
        parts, bounds = [], []
        for _ in range(n):
            toks, _, _ = gen_sentence(rng)
            body = toks[:-1]
            if rng.random() < 0.5:
                pos_j = rng.randint(0, max(len(body) - 1, 0))
                body.insert(pos_j, rng.choice(ABBREVS))
            if rng.random() < 0.3:
                body.insert(rng.randint(0, max(len(body) - 1, 0)),
                            f"{rng.randint(1, 99)}.{rng.randint(0, 99)}")
            sent = " ".join(body) + toks[-1]
            parts.append(sent)
        text = " ".join(parts)
        # boundary positions = ends of each part
        off, marks = 0, set()
        for p in parts:
            off += len(p)
            marks.add(off - 1)
            off += 1
        docs.append((text, marks))
    model = AveragedPerceptron(classes=["0", "1"])
    for _ in range(epochs):
        rng.shuffle(docs)
        for text, marks in docs:
            for i, ch in enumerate(text):
                if ch not in SentenceSplitter.CANDIDATES:
                    continue
                if i + 1 < len(text) and not text[i + 1].isspace():
                    continue
                feats = SentenceSplitter.features(text, i)
                truth = "1" if i in marks else "0"
                guess = model.predict(feats)
                model.update(truth, guess, feats)
    model.average()
    model.save(os.path.join(resource_dir(), "sent.json.gz"))
    print("sentence splitter:", len(model.weights), "features")


if __name__ == "__main__":
    main()
