#!/usr/bin/env python
"""tmoglint — AST-based repo self-lint enforcing project invariants.

The runtime invariants PRs 1-4 introduced by convention are enforced
here as rules (the TMG3xx family of the catalog in
``transmogrifai_tpu/lint.py`` / docs/static-analysis.md):

* **TMG301** — monotonic timing must use ``time.perf_counter()``, never
  ``time.time()`` (the PR-2 rule: an NTP step mid-run corrupts every
  ``time.time()`` duration). Legitimate wall-clock uses — mtime
  comparisons, epoch timestamps written to sinks — carry a
  ``# lint: wall-clock`` marker on the offending line.
* **TMG302** — ``except Exception`` (or ``BaseException``) appears only
  at allowlisted breaker/fallback/quarantine sites marked
  ``# lint: broad-except`` (ideally with a dash-reason). Everything
  else must catch the specific exceptions it can actually handle.
* **TMG303** — every ``resilience.inject(site)`` marker names a site
  registered in ``resilience.FAULT_SITES``: a typo'd site is a chaos
  test that silently never fires.
* **TMG304** — telemetry spans open via context managers
  (``with telemetry.span(...)``): a bare ``span(...)`` call is an
  unpaired begin/end that never records and silently corrupts the
  per-thread span stack.
* **TMG306** — runtime code must not call ``make_mesh()`` directly:
  the PR-6 one-process-mesh invariant routes every consumer through
  ``process_default_mesh()``/``set_process_mesh`` (a throwaway mesh per
  pass is the regression ``mesh_constructions`` exists to catch).
  ``parallel/`` itself and tests are exempt; a deliberate explicit
  construction carries ``# lint: explicit-mesh — reason``.
* **TMG307** — ``threading.Thread(...)`` must pass ``name=`` and
  ``daemon=`` explicitly (the PR-8 model-server rule: the telemetry
  tracer keys trace tracks by thread name, so an unnamed worker renders
  as ``Thread-7`` and an implicit daemon flag hides whether shutdown
  waits for it). A deliberate default carries
  ``# lint: thread — reason``.
* **TMG308** — ``queue.Queue()`` must pass an explicit ``maxsize=``
  (the input-pipeline rule: an unbounded queue between pipeline stages
  hides backpressure — a stalled consumer lets the producer eat the
  heap instead of slowing down; the staged pipeline's whole contract
  is bounded queues with explicit backpressure). A deliberate
  unbounded queue carries ``# lint: unbounded-queue — reason``.
* **TMG309** — product-code ``subprocess.Popen(...)`` must pass
  explicit ``stdout=`` and ``stderr=`` (the fleet-supervisor rule: an
  inherited stdout ties a long-lived child's output to whatever
  terminal started the parent, and a ``PIPE`` nobody drains deadlocks
  the child once the OS buffer fills — a supervisor must own its
  workers' streams). A deliberate inherit carries
  ``# lint: popen — reason``.
* **TMG310** — a function used as a ``threading.Thread`` ``target=``
  must not contain a ``while`` loop with no ``try`` anywhere in its
  body (the continual-tier rule: an uncaught exception kills the
  thread SILENTLY — the drift sentinel, a fleet monitor or a retrain
  supervisor keeps "running" with nobody home while its queue fills
  and its subsystem rots; long-lived loop bodies must catch-and-tally).
  A deliberately bare loop carries ``# lint: thread-loop — reason`` on
  the ``while`` line or the ``def`` line.
* **TMG311** — ``np.argsort(...)`` must pass an explicit ``kind=`` and
  ``np.searchsorted(...)`` an explicit ``side=`` (the temporal-tier
  rule: the columnar aggregation engine groups by key with a STABLE
  argsort precisely because order-dependent monoid folds — float sums,
  concat, first/last — silently change value under unstable sort ties,
  and an implicit ``side=`` hides which boundary of a cutoff window is
  inclusive). A deliberate default carries ``# lint: sort — reason``.
  Only calls attributable to numpy (``import numpy as np`` aliases /
  ``from numpy import argsort``) are checked; ``jnp`` is exempt (jax
  sorts are stable by construction).
* **TMG312** — ``pl.pallas_call(...)`` appears only in
  ``models/_pallas_hist.py`` (the tree-engine rule: every kernel lives
  behind that module's one-time compile probe and
  ``with_pallas_fallback`` retrace-onto-XLA discipline — a kernel
  elsewhere has NO fallback, so a Mosaic rejection at production shapes
  fails an hours-long fit instead of degrading). Tests are exempt; a
  deliberately un-gated kernel carries ``# lint: pallas — reason``.
* **TMG313** — ``telemetry.counter/gauge/histogram(...)`` must pass a
  LITERAL metric name outside ``telemetry.py`` (the observability-plane
  rule: a dynamic name is unbounded registry AND ``/metrics``
  exposition cardinality — every distinct runtime value becomes a new
  instrument held for the process lifetime and a new family in every
  scrape; a per-entity name interpolated from unbounded input can eat
  the heap and flood the scrape surface). Tests are exempt; a
  deliberately dynamic name whose domain is provably bounded (a fixed
  tally catalog, the registered tenant roster) carries
  ``# lint: metric-name — reason``.
* **TMG314** — raw ``customParams`` READS (a Load-context subscript or
  ``.get()`` on a receiver named/ending ``custom_params``/
  ``customParams``) appear only in ``config.py`` (the PR-18 declared-
  config rule: the knob registry owns types, bounds and error wording —
  a raw read elsewhere bypasses validation, drifts from the declared
  default, and is invisible to ``cli check``/the tuner's search space;
  route through ``config.numeric_param``/``bool_param``/
  ``string_param`` or the runner wrappers). Writes are exempt (the CLI
  legitimately ASSEMBLES customParams dicts); tests are exempt; a
  deliberate raw passthrough (a path/dict handed verbatim to its owner)
  carries ``# lint: knob — reason``.

Runs as a CLI over one or more paths (default: the ``transmogrifai_tpu``
package next to this script) and as a tier-1 pytest
(``tests/test_lint.py`` asserts the repo itself is clean), so invariant
regressions fail CI::

    python tools/tmoglint.py                    # lint the package
    python tools/tmoglint.py path/ --fail-on warning
"""
from __future__ import annotations

import argparse
import ast
import io
import os
import re
import sys
import tokenize
from typing import Dict, List, Optional, Sequence, Set, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
if _REPO not in sys.path:                       # direct script execution
    sys.path.insert(0, _REPO)

from transmogrifai_tpu.lint import Finding, Severity, enforce  # noqa: E402

__all__ = ["lint_source", "lint_file", "lint_paths", "main",
           "MARKER_RULES",
           "ALLOW_WALLCLOCK", "ALLOW_BROAD_EXCEPT", "ALLOW_EXPLICIT_MESH",
           "ALLOW_THREAD", "ALLOW_UNBOUNDED_QUEUE", "ALLOW_POPEN",
           "ALLOW_THREAD_LOOP", "ALLOW_SORT", "ALLOW_PALLAS",
           "ALLOW_METRIC_NAME", "ALLOW_KNOB"]

#: suppression markers, checked on the finding's own source line
ALLOW_WALLCLOCK = "lint: wall-clock"
ALLOW_BROAD_EXCEPT = "lint: broad-except"
ALLOW_EXPLICIT_MESH = "lint: explicit-mesh"
ALLOW_THREAD = "lint: thread"
ALLOW_UNBOUNDED_QUEUE = "lint: unbounded-queue"
ALLOW_POPEN = "lint: popen"
ALLOW_THREAD_LOOP = "lint: thread-loop"
ALLOW_SORT = "lint: sort"
ALLOW_PALLAS = "lint: pallas"
ALLOW_METRIC_NAME = "lint: metric-name"
ALLOW_KNOB = "lint: knob"

#: marker word → the ONE rule it silences. The stale-marker pass
#: (TMG399) flags any marker comment whose rule did not actually fire
#: on that line — suppressions must not outlive their findings. Only
#: THIS tool's vocabulary is checked here; the TMG8xx markers
#: (lock-order, thread-escape, lock-blocking, atomic-write) belong to
#: tools/concurrency_lint.py, which runs its own stale pass.
MARKER_RULES: Dict[str, str] = {
    "wall-clock": "TMG301",
    "broad-except": "TMG302",
    "explicit-mesh": "TMG306",
    "thread": "TMG307",
    "unbounded-queue": "TMG308",
    "popen": "TMG309",
    "thread-loop": "TMG310",
    "sort": "TMG311",
    "pallas": "TMG312",
    "metric-name": "TMG313",
    "knob": "TMG314",
}

#: matches the marker word in a real COMMENT token ("# lint: knob — …")
_MARKER_RE = re.compile(r"lint:\s*([a-z][a-z-]*)")

#: the ONE module sanctioned to build instrument names dynamically
#: (TMG313): the registry itself owns cardinality
METRICS_HOME = "telemetry.py"

#: the ONE module sanctioned to read customParams raw (TMG314): the
#: knob registry owns types, bounds, defaults and error wording
CONFIG_HOME = "config.py"

#: the ONE module sanctioned to host pl.pallas_call sites (TMG312): its
#: probe/fallback gate is what makes a Mosaic rejection survivable
PALLAS_HOME = "_pallas_hist.py"


def _fault_sites() -> frozenset:
    from transmogrifai_tpu.resilience import FAULT_SITES
    return FAULT_SITES


class _Visitor(ast.NodeVisitor):
    """One file's AST walk. Collects import aliases first (so ``import
    time as _time`` still triggers TMG301) and the set of Call nodes
    used as ``with``-item context expressions (TMG304)."""

    def __init__(self, path: str, lines: Sequence[str]):
        self.path = path
        self.lines = lines
        self.findings: List[Finding] = []
        #: local names bound to the time module / telemetry module /
        #: resilience module / mesh module / their relevant functions
        self.time_modules: Set[str] = set()
        self.time_funcs: Set[str] = set()       # from time import time [as x]
        self.telemetry_modules: Set[str] = set()
        self.span_funcs: Set[str] = set()
        self.resilience_modules: Set[str] = set()
        self.inject_funcs: Set[str] = set()
        self.mesh_modules: Set[str] = set()
        self.make_mesh_funcs: Set[str] = set()
        self.threading_modules: Set[str] = set()
        self.thread_funcs: Set[str] = set()      # from threading import Thread
        self.queue_modules: Set[str] = set()
        self.queue_funcs: Set[str] = set()       # from queue import Queue
        self.subprocess_modules: Set[str] = set()
        self.popen_funcs: Set[str] = set()       # from subprocess import Popen
        self.numpy_modules: Set[str] = set()
        self.np_sort_funcs: Dict[str, str] = {}  # from numpy import argsort
        self.pallas_modules: Set[str] = set()
        self.pallas_call_funcs: Set[str] = set()
        self.instrument_funcs: Dict[str, str] = {}  # from telemetry import counter
        self.with_contexts: Set[int] = set()
        #: TMG310 bookkeeping: names used as Thread(target=...) and the
        #: module's function defs by name (methods included; resolved in
        #: a post-pass so definition order never matters)
        self.thread_targets: Set[str] = set()
        self.func_defs: Dict[str, ast.AST] = {}
        #: TMG399 bookkeeping: line → rules a marker on that line
        #: actually silenced during this walk
        self.used_markers: Dict[int, Set[str]] = {}
        #: parallel/ owns mesh construction, tests may build explicit
        #: topologies — TMG306 exempts both by path
        parts = os.path.normpath(path).split(os.sep)
        self.mesh_exempt = ("parallel" in parts or "tests" in parts
                            or os.path.basename(path).startswith("test_"))
        #: _pallas_hist.py owns kernel construction (its probe/fallback
        #: gate is the rule's point); tests may build throwaway kernels
        self.pallas_exempt = (os.path.basename(path) == PALLAS_HOME
                              or "tests" in parts
                              or os.path.basename(path).startswith("test_"))
        #: telemetry.py owns the registry (its factories RECEIVE the
        #: names); tests may build throwaway instruments — TMG313
        self.metric_exempt = (os.path.basename(path) == METRICS_HOME
                              or "tests" in parts
                              or os.path.basename(path).startswith("test_"))
        #: config.py owns raw customParams access (its registry
        #: accessors ARE the sanctioned read path); tests may poke raw
        #: dicts freely — TMG314
        self.knob_exempt = (os.path.basename(path) == CONFIG_HOME
                            or "tests" in parts
                            or os.path.basename(path).startswith("test_"))

    # -- helpers -----------------------------------------------------------
    def _marked(self, lineno: int, marker: str) -> bool:
        if 1 <= lineno <= len(self.lines):
            return marker in self.lines[lineno - 1]
        return False

    def _add(self, rule: str, lineno: int, message: str,
             severity: Optional[str] = None) -> None:
        self.findings.append(Finding(
            rule, message, severity=severity or "",
            location=f"{self.path}:{lineno}"))

    def _suppressible(self, rule: str, marker: str, lineno: int,
                      message: str,
                      lines: Optional[Sequence[int]] = None,
                      severity: Optional[str] = None) -> None:
        """Emit ``rule`` at ``lineno`` unless a ``marker`` on one of
        ``lines`` (default: the finding line) silences it. A silencing
        marker is recorded as USED so the stale-marker pass (TMG399)
        can flag the ones that no longer silence anything."""
        for ln in (lines or (lineno,)):
            if self._marked(ln, marker):
                self.used_markers.setdefault(ln, set()).add(rule)
                return
        self._add(rule, lineno, message, severity)

    # -- imports -----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            if alias.name == "time":
                self.time_modules.add(local)
            if alias.name.endswith("telemetry"):
                self.telemetry_modules.add(local)
            if alias.name.endswith("resilience"):
                self.resilience_modules.add(local)
            if alias.name.endswith("mesh"):
                self.mesh_modules.add(local)
            if alias.name == "threading":
                self.threading_modules.add(local)
            if alias.name == "queue":
                self.queue_modules.add(local)
            if alias.name == "subprocess":
                self.subprocess_modules.add(local)
            if alias.name == "numpy":
                self.numpy_modules.add(local)
            if alias.name == "jax.experimental.pallas" and alias.asname:
                # no-asname dotted imports bind only "jax" locally; the
                # call form jax.experimental.pallas.pallas_call(...) is
                # matched as a dotted chain in _is_pallas_call instead
                self.pallas_modules.add(local)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        for alias in node.names:
            local = alias.asname or alias.name
            if mod == "time" and alias.name == "time":
                self.time_funcs.add(local)
            if alias.name == "telemetry":
                self.telemetry_modules.add(local)
            if alias.name == "resilience":
                self.resilience_modules.add(local)
            if alias.name == "mesh":
                self.mesh_modules.add(local)
            if mod.endswith("telemetry") and alias.name == "span":
                self.span_funcs.add(local)
            if mod.endswith("resilience") and alias.name == "inject":
                self.inject_funcs.add(local)
            if mod.endswith("mesh") and alias.name == "make_mesh":
                self.make_mesh_funcs.add(local)
            if mod == "threading" and alias.name == "Thread":
                self.thread_funcs.add(local)
            if mod == "queue" and alias.name == "Queue":
                self.queue_funcs.add(local)
            if mod == "subprocess" and alias.name == "Popen":
                self.popen_funcs.add(local)
            if mod == "numpy" and alias.name in ("argsort",
                                                 "searchsorted"):
                self.np_sort_funcs[local] = alias.name
            if mod == "jax.experimental" and alias.name == "pallas":
                self.pallas_modules.add(local)
            if mod.endswith("pallas") and alias.name == "pallas_call":
                self.pallas_call_funcs.add(local)
            if mod.endswith("telemetry") and alias.name in (
                    "counter", "gauge", "histogram"):
                self.instrument_funcs[local] = alias.name
        self.generic_visit(node)

    # -- function defs: TMG310 target resolution ---------------------------
    def visit_FunctionDef(self, node) -> None:
        self.func_defs.setdefault(node.name, node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- with: remember sanctioned context-manager calls -------------------
    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                self.with_contexts.add(id(item.context_expr))
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    # -- except Exception --------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        names = []
        t = node.type
        if isinstance(t, ast.Tuple):
            names = [getattr(e, "id", getattr(e, "attr", "")) for e
                     in t.elts]
        elif t is not None:
            names = [getattr(t, "id", getattr(t, "attr", ""))]
        if any(n in ("Exception", "BaseException") for n in names):
            self._suppressible(
                "TMG302", ALLOW_BROAD_EXCEPT, node.lineno,
                "broad 'except Exception' outside the allowlist — catch "
                "the specific exceptions or mark the line "
                f"'# {ALLOW_BROAD_EXCEPT} — <reason>' if this is a "
                "deliberate breaker/fallback/quarantine site")
        self.generic_visit(node)

    # -- calls: time.time / inject / span ----------------------------------
    def _is_time_time(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "time" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.time_modules:
            return True
        return isinstance(f, ast.Name) and f.id in self.time_funcs

    def _is_inject(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "inject" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.resilience_modules:
            return True
        return isinstance(f, ast.Name) and f.id in self.inject_funcs

    def _is_span(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "span" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.telemetry_modules:
            return True
        return isinstance(f, ast.Name) and f.id in self.span_funcs

    def _is_make_mesh(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "make_mesh" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.mesh_modules:
            return True
        return isinstance(f, ast.Name) and f.id in self.make_mesh_funcs

    def _is_thread(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "Thread" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.threading_modules:
            return True
        return isinstance(f, ast.Name) and f.id in self.thread_funcs

    def _is_queue(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "Queue" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.queue_modules:
            return True
        return isinstance(f, ast.Name) and f.id in self.queue_funcs

    def _is_popen(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "Popen" \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.subprocess_modules:
            return True
        return isinstance(f, ast.Name) and f.id in self.popen_funcs

    @staticmethod
    def _dotted(node) -> Optional[str]:
        """'a.b.c' for a Name/Attribute chain, else None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    def _is_pallas_call(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "pallas_call":
            if isinstance(f.value, ast.Name) \
                    and f.value.id in self.pallas_modules:
                return True
            # the unaliased dotted form: jax.experimental.pallas.pallas_call
            return self._dotted(f.value) == "jax.experimental.pallas"
        return isinstance(f, ast.Name) and f.id in self.pallas_call_funcs

    def _instrument_kind(self, node: ast.Call) -> Optional[str]:
        """\"counter\"/\"gauge\"/\"histogram\" when the call is
        attributable to the telemetry module (module alias or
        from-import), else None."""
        f = node.func
        if isinstance(f, ast.Attribute) \
                and f.attr in ("counter", "gauge", "histogram") \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.telemetry_modules:
            return f.attr
        if isinstance(f, ast.Name):
            return self.instrument_funcs.get(f.id)
        return None

    def _np_sort_kind(self, node: ast.Call) -> Optional[str]:
        """\"argsort\"/\"searchsorted\" when the call is attributable to
        numpy (module alias or from-import), else None — method-form
        ``x.argsort()`` and jax's ``jnp`` are out of scope (jax sorts
        are stable by construction)."""
        f = node.func
        if isinstance(f, ast.Attribute) \
                and f.attr in ("argsort", "searchsorted") \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.numpy_modules:
            return f.attr
        if isinstance(f, ast.Name):
            return self.np_sort_funcs.get(f.id)
        return None

    # -- TMG314: raw customParams reads outside config.py ------------------
    @staticmethod
    def _is_knob_receiver(expr) -> bool:
        """True when ``expr`` names a customParams mapping: a bare
        ``custom_params``/``customParams`` Name or any Attribute chain
        ending in one (``params.custom_params``, ``self.customParams``)."""
        name = None
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute):
            name = expr.attr
        if name is None:
            return False
        return name.endswith("custom_params") or name.endswith(
            "customParams")

    @staticmethod
    def _knob_lines(node) -> Tuple[int, int]:
        """The ``# lint: knob`` marker may sit on the read's FIRST or
        LAST physical line (a wrapped ``.get(...)`` continuation puts
        the comment after the closing paren, a line below where the
        expression starts)."""
        return (node.lineno,
                getattr(node, "end_lineno", node.lineno) or node.lineno)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # Load-context only: the CLI legitimately ASSEMBLES customParams
        # dicts (Store/Del writes stay clean); reads must route through
        # the registry accessors
        if isinstance(node.ctx, ast.Load) \
                and self._is_knob_receiver(node.value) \
                and not self.knob_exempt:
            self._suppressible(
                "TMG314", ALLOW_KNOB, node.lineno,
                "raw customParams subscript read outside config.py — "
                "the knob registry owns types, bounds, defaults and "
                "error wording; route through config.numeric_param/"
                "bool_param/string_param (or the runner wrappers), or "
                "mark a deliberate passthrough "
                f"'# {ALLOW_KNOB} — <reason>'",
                lines=self._knob_lines(node))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "get" \
                and self._is_knob_receiver(f.value) \
                and not self.knob_exempt:
            self._suppressible(
                "TMG314", ALLOW_KNOB, node.lineno,
                "raw customParams .get() outside config.py — the knob "
                "registry owns types, bounds, defaults and error "
                "wording (a raw .get() silently drifts from the "
                "declared default and skips validation); route through "
                "config.numeric_param/bool_param/string_param (or the "
                "runner wrappers), or mark a deliberate passthrough "
                f"'# {ALLOW_KNOB} — <reason>'",
                lines=self._knob_lines(node))
        if self._is_thread(node):
            # TMG310: remember the target's name whatever the TMG307
            # outcome — `target=self._loop` and `target=loop` both
            # resolve against the module's function defs in a post-pass
            for kw in node.keywords:
                if kw.arg == "target":
                    v = kw.value
                    if isinstance(v, ast.Name):
                        self.thread_targets.add(v.id)
                    elif isinstance(v, ast.Attribute):
                        self.thread_targets.add(v.attr)
        if self._is_time_time(node):
            self._suppressible(
                "TMG301", ALLOW_WALLCLOCK, node.lineno,
                "time.time() — durations must use time.perf_counter() "
                "(NTP steps corrupt wall-clock deltas); true wall-clock "
                "uses (mtime comparisons, sink timestamps) carry "
                f"'# {ALLOW_WALLCLOCK}'")
        elif self._is_inject(node):
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value
                if site not in _fault_sites():
                    self._add(
                        "TMG303", node.lineno,
                        f"inject site {site!r} is not registered in "
                        "resilience.FAULT_SITES — a typo'd site is a "
                        "chaos test that never fires; register it (and "
                        "document it in docs/robustness.md)")
            elif node.args:
                self._add(
                    "TMG303", node.lineno,
                    "inject() must name its site as a string literal so "
                    "the catalog check (and grep) can see it",
                    severity=Severity.WARNING)
        elif self._is_span(node) and id(node) not in self.with_contexts:
            self._add(
                "TMG304", node.lineno,
                "telemetry span opened outside a 'with' statement — a "
                "span only records on __exit__, so an unpaired call "
                "never lands in the trace and corrupts the per-thread "
                "span stack")
        elif self._is_make_mesh(node) and not self.mesh_exempt:
            self._suppressible(
                "TMG306", ALLOW_EXPLICIT_MESH, node.lineno,
                "direct make_mesh() outside parallel/ — runtime code "
                "shares the ONE process mesh via process_default_mesh()"
                "/set_process_mesh (a throwaway mesh per pass is the "
                "mesh_constructions regression); mark a deliberate "
                f"explicit topology '# {ALLOW_EXPLICIT_MESH} — <reason>'")
        elif self._is_thread(node):
            kws = {kw.arg for kw in node.keywords}
            missing = [f"{k}=" for k in ("name", "daemon")
                       if k not in kws]
            if missing:
                self._suppressible(
                    "TMG307", ALLOW_THREAD, node.lineno,
                    f"threading.Thread() without explicit "
                    f"{' and '.join(missing)} — telemetry trace tracks "
                    "are keyed by thread name and implicit daemonness "
                    "hides shutdown semantics; pass name= and daemon= "
                    "(or mark a deliberate default "
                    f"'# {ALLOW_THREAD} — <reason>')")
        elif self._is_queue(node):
            size = None
            for kw in node.keywords:
                if kw.arg == "maxsize":
                    size = kw.value
            if size is None and node.args:
                size = node.args[0]
            # a LITERAL maxsize <= 0 (incl. -1 spelled as UnaryOp) is
            # unbounded in queue semantics — same defect as omitting it
            literal_unbounded = (
                isinstance(size, ast.Constant)
                and isinstance(size.value, int) and size.value <= 0) \
                or (isinstance(size, ast.UnaryOp)
                    and isinstance(size.op, ast.USub)
                    and isinstance(size.operand, ast.Constant))
            if size is None or literal_unbounded:
                self._suppressible(
                    "TMG308", ALLOW_UNBOUNDED_QUEUE, node.lineno,
                    "queue.Queue() without an explicit positive "
                    "maxsize= (maxsize<=0 means UNBOUNDED) — an "
                    "unbounded queue between pipeline stages hides "
                    "backpressure (a stalled consumer lets the producer "
                    "eat the heap instead of slowing down); pass "
                    "maxsize= (or mark a deliberate unbounded queue "
                    f"'# {ALLOW_UNBOUNDED_QUEUE} — <reason>')")
        elif self._is_popen(node):
            kws = {kw.arg for kw in node.keywords}
            # a **kwargs splat may well carry stdout/stderr — the
            # static check cannot see inside it, so don't false-ERROR a
            # dynamically configured Popen
            missing = [] if None in kws else \
                [f"{k}=" for k in ("stdout", "stderr") if k not in kws]
            if missing:
                self._suppressible(
                    "TMG309", ALLOW_POPEN, node.lineno,
                    f"subprocess.Popen() without explicit "
                    f"{' and '.join(missing)} — an inherited stdout "
                    "ties a long-lived child's output to whatever "
                    "terminal started the parent, and a PIPE nobody "
                    "drains deadlocks the child once the OS buffer "
                    "fills; a supervisor must own its workers' "
                    "streams (or mark a deliberate inherit "
                    f"'# {ALLOW_POPEN} — <reason>')")
        elif self._is_pallas_call(node) and not self.pallas_exempt:
            self._suppressible(
                "TMG312", ALLOW_PALLAS, node.lineno,
                "pl.pallas_call() outside models/_pallas_hist.py — "
                "kernels live behind that module's probe/fallback gate "
                "(pallas_histograms_enabled / with_pallas_fallback): a "
                "kernel elsewhere has no retrace-onto-XLA fallback, so "
                "a Mosaic rejection at production shapes fails the fit "
                "instead of degrading; move it (or mark a deliberately "
                f"un-gated kernel '# {ALLOW_PALLAS} — <reason>')")
        elif self._instrument_kind(node) is not None \
                and not self.metric_exempt:
            inst_kind = self._instrument_kind(node)
            name_arg = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "name":
                    name_arg = kw.value
            if not (isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)):
                self._suppressible(
                    "TMG313", ALLOW_METRIC_NAME, node.lineno,
                    f"telemetry.{inst_kind}() with a non-literal metric "
                    "name outside telemetry.py — a dynamic name is "
                    "unbounded registry/exposition cardinality (every "
                    "distinct runtime value is a new instrument held "
                    "for the process lifetime and a new /metrics "
                    "family); use a literal name, or mark a "
                    "deliberately dynamic-but-BOUNDED name "
                    f"'# {ALLOW_METRIC_NAME} — <reason>'")
        else:
            sort_kind = self._np_sort_kind(node)
            if sort_kind is not None:
                need = "kind" if sort_kind == "argsort" else "side"
                kws = {kw.arg for kw in node.keywords}
                if need not in kws and None not in kws:
                    self._suppressible(
                        "TMG311", ALLOW_SORT, node.lineno,
                        f"np.{sort_kind}() without explicit {need}= — "
                        "order-dependent monoid folds (float sums, "
                        "concat, first/last) silently change value "
                        "under unstable sort ties, and an implicit "
                        "side= hides which window boundary is "
                        f"inclusive; pass {need}= explicitly (or mark "
                        "a deliberate default "
                        f"'# {ALLOW_SORT} — <reason>')")
        self.generic_visit(node)


def _check_thread_loops(v: _Visitor) -> None:
    """TMG310 post-pass: every function the module hands to
    ``threading.Thread(target=...)`` is a long-lived loop body — each of
    its ``while`` loops must contain a ``try`` somewhere (catch-and-
    tally), or the first uncaught exception kills the thread silently
    while its subsystem keeps 'running' with nobody home."""
    for name in sorted(v.thread_targets):
        fn = v.func_defs.get(name)
        if fn is None:
            continue                # library callable (serve_forever, …)
        for node in ast.walk(fn):
            if not isinstance(node, ast.While):
                continue
            if any(isinstance(x, ast.Try) for x in ast.walk(node)):
                continue
            v._suppressible(
                "TMG310", ALLOW_THREAD_LOOP, node.lineno,
                f"'while' loop in thread target {name!r} has no "
                "try/except anywhere in its body — an uncaught "
                "exception kills the thread SILENTLY and the subsystem "
                "it drives keeps 'running' with nobody home; "
                "catch-and-tally in the loop body (or mark a "
                "deliberately bare loop "
                f"'# {ALLOW_THREAD_LOOP} — <reason>')",
                lines=(node.lineno, fn.lineno))


def _stale_marker_findings(src: str, path: str,
                           v: _Visitor) -> List[Finding]:
    """TMG399: every real COMMENT carrying a ``lint: <marker>`` from
    THIS tool's vocabulary must have silenced its rule on that line
    during the walk — a leftover marker is camouflage for the next
    real finding there. Rules path-exempt in this file (TMG306/312/
    313/314 homes, tests) are skipped: their markers are inert, not
    stale. Marker text inside string literals never counts (the
    catalog and fixtures SPELL markers without placing them)."""
    exempt: Set[str] = set()
    if v.mesh_exempt:
        exempt.add("TMG306")
    if v.pallas_exempt:
        exempt.add("TMG312")
    if v.metric_exempt:
        exempt.add("TMG313")
    if v.knob_exempt:
        exempt.add("TMG314")
    findings: List[Finding] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return findings                 # parse-adjacent breakage → TMG305's job
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _MARKER_RE.search(tok.string)
        if m is None:
            continue
        rule = MARKER_RULES.get(m.group(1))
        if rule is None or rule in exempt:
            continue                    # foreign vocabulary (TMG8xx) / inert
        lineno = tok.start[0]
        if rule in v.used_markers.get(lineno, ()):
            continue
        findings.append(Finding(
            "TMG399",
            f"stale suppression: 'lint: {m.group(1)}' silences {rule} "
            "but nothing on this line triggers that rule anymore — "
            "delete the marker (or fix it if it names the wrong rule)",
            location=f"{path}:{lineno}"))
    return findings


def lint_source(src: str, path: str = "<string>",
                stale_markers: bool = True) -> List[Finding]:
    """Lint one module's source text; returns TMG3xx findings (plus
    TMG399 stale-suppression warnings unless ``stale_markers=False``)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("TMG305", f"file does not parse: {e}",
                        location=f"{path}:{e.lineno or 0}")]
    v = _Visitor(path, src.splitlines())
    v.visit(tree)
    _check_thread_loops(v)
    findings = v.findings
    if stale_markers:
        findings = findings + _stale_marker_findings(src, path, v)
    return sorted(findings, key=lambda f: f.location or "")


def lint_file(path: str, stale_markers: bool = True) -> List[Finding]:
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), path, stale_markers=stale_markers)


def lint_paths(paths: Sequence[str],
               stale_markers: bool = True) -> List[Finding]:
    """Lint every ``.py`` file under the given files/directories
    (``__pycache__`` skipped), findings sorted by location."""
    findings: List[Finding] = []
    for p in paths:
        if os.path.isfile(p):
            findings.extend(lint_file(p, stale_markers=stale_markers))
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for fn in sorted(files):
                if fn.endswith(".py"):
                    findings.extend(lint_file(
                        os.path.join(root, fn),
                        stale_markers=stale_markers))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmoglint",
        description="AST self-lint for project invariants (TMG3xx)")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_REPO, "transmogrifai_tpu")],
                    help="files/directories to lint (default: the "
                         "transmogrifai_tpu package)")
    ap.add_argument("--fail-on", choices=("error", "warning"),
                    default="error",
                    help="exit non-zero when findings reach this "
                         "severity (default: error)")
    ap.add_argument("--no-stale-markers", action="store_true",
                    help="skip the TMG399 stale-suppression pass")
    args = ap.parse_args(argv)
    findings = lint_paths(args.paths,
                          stale_markers=not args.no_stale_markers)
    for f in findings:
        print(f.format())
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    summary = ", ".join(f"{counts.get(s, 0)} {s}(s)"
                        for s in (Severity.ERROR, Severity.WARNING,
                                  Severity.INFO))
    print(f"tmoglint: {summary}")
    try:
        enforce(findings, fail_on=args.fail_on)
    except Exception:   # lint: broad-except — CLI boundary: findings already printed
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
