#!/usr/bin/env python
"""concurrency_lint — whole-program concurrency & crash-safety analysis
(the TMG8xx family of the catalog in ``transmogrifai_tpu/lint.py`` /
docs/static-analysis.md).

Unlike tmoglint's per-line TMG3xx rules, these properties are only
visible with the WHOLE package in view at once: a deadlock is two
call paths in different modules, a data race is one mutation site
missing the lock its siblings hold. The pass therefore parses every
product module, resolves lock OBJECTS (module globals, ``self.x``
instance attributes, function locals, ``fcntl.flock`` sites) to
program-wide identities, and checks:

* **TMG801** — lock-order cycles. Every nested ``with <lock>`` body,
  every ``fcntl.flock`` site and every call made while holding a lock
  (one call level deep, cross-module) contributes an ordered
  acquisition edge; any cycle in the resulting graph is a potential
  deadlock and is reported with BOTH acquisition paths quoted.
  Re-acquiring an RLock is not an edge. Escape:
  ``# lint: lock-order — reason`` on any quoted line.
* **TMG802** — thread-escape. A module global or shared-object
  attribute whose OTHER mutation sites hold a guarding lock, mutated
  lock-free from a function reachable as a ``threading.Thread``
  target (tmoglint TMG310's target resolution, made transitive over
  the module call graph). Both the unlocked and a locked site are
  quoted. Escape: ``# lint: thread-escape — reason``.
* **TMG803** — blocking call while holding a lock: ``queue.get/put``
  without ``block=False``/``timeout=``, bare ``.join()``/``.wait()``,
  ``.communicate()`` without timeout, ``subprocess.*``, socket/HTTP,
  ``time.sleep`` inside a lock body (including one call level deep:
  a lock-free blocking site in a callee fires when some caller holds
  a lock across the call). Escape:
  ``# lint: lock-blocking — reason`` on the blocking line.
* **TMG804** — atomic-write discipline: product-code
  ``open(path, "w"/"wb")`` into a shared-artifact path family
  (registry records, CURRENT pointer, cost db, trace/workload shards,
  AOT manifests, …) in a function with no ``os.replace`` and no tmp
  staging — a crash mid-write leaves a torn file every reader then
  trusts. Escape: ``# lint: atomic-write — reason``.
* **TMG805** — fault-site coverage: every site registered in
  ``resilience.FAULT_SITES`` must appear (as a string) somewhere
  under tests/ — an untested fault site is a recovery path that has
  never once run.
* **TMG399** — stale suppressions of THIS tool's markers (the same
  contract tmoglint enforces for its own vocabulary): a marker that
  no longer silences anything is itself a warning.

The runtime analog of TMG801 is the ``utils.locks`` lock-order
witness: the hierarchy this pass derives statically is what the
witness checks per-thread under the chaos suites.

Static resolution is necessarily approximate; the approximations are
deliberately CONSERVATIVE for the graph (ambiguous attribute locks
never contribute cycle edges) and the escapes exist for the rest.

Runs as a CLI and as a tier-1 pytest (``tests/test_lint.py`` asserts
the repo itself is clean)::

    python tools/concurrency_lint.py                 # lint the package
    python tools/concurrency_lint.py --fail-on warning
"""
from __future__ import annotations

import argparse
import ast
import io
import os
import re
import sys
import tokenize
from typing import Dict, List, Optional, Sequence, Set, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
if _REPO not in sys.path:                       # direct script execution
    sys.path.insert(0, _REPO)

from transmogrifai_tpu.lint import Finding, Severity, enforce  # noqa: E402

__all__ = ["analyze_sources", "lint_paths", "fault_coverage_findings",
           "main", "MARKER_RULES", "ALLOW_LOCK_ORDER",
           "ALLOW_THREAD_ESCAPE", "ALLOW_LOCK_BLOCKING",
           "ALLOW_ATOMIC_WRITE"]

#: suppression markers, checked on the finding's own source line
ALLOW_LOCK_ORDER = "lint: lock-order"
ALLOW_THREAD_ESCAPE = "lint: thread-escape"
ALLOW_LOCK_BLOCKING = "lint: lock-blocking"
ALLOW_ATOMIC_WRITE = "lint: atomic-write"

#: marker word → the rule it silences (this tool's TMG399 vocabulary;
#: tmoglint owns the TMG3xx words)
MARKER_RULES: Dict[str, str] = {
    "lock-order": "TMG801",
    "thread-escape": "TMG802",
    "lock-blocking": "TMG803",
    "atomic-write": "TMG804",
}
_MARKER_RE = re.compile(r"lint:\s*([a-z][a-z-]*)")

#: threading constructors that create a lockable object (value = lock
#: kind; an RLock may legally be re-entered, so a self-edge on one is
#: not a deadlock)
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock",
               "Condition": "condition", "Semaphore": "lock",
               "BoundedSemaphore": "lock"}

#: the utils.locks factory — its reentrant= kwarg decides the kind
_WITNESS_FACTORY = "witness_lock"

#: path-text fragments that mark a shared on-disk artifact family
#: (TMG804): files more than one process/thread reads back
_SHARED_ARTIFACT_HINTS = ("registry", "pointer", "current", "cost",
                          "manifest", "trace", "workload", "shard",
                          "version", "job", "bank")

#: container methods that mutate their receiver in place (TMG802)
_MUTATORS = {"append", "add", "update", "pop", "clear", "extend",
             "setdefault", "remove", "discard", "popleft",
             "appendleft", "insert"}


def _dotted(node) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _walk_shallow(node):
    """``ast.walk`` that does NOT descend into nested function/class
    defs — their bodies are summarized as their own ``_Func``."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


class _Func:
    """One function/method's summary. ``acquisitions`` are the locks
    the function takes DIRECTLY (with-items and flock calls) — what a
    caller holding a lock across a call pulls into the order graph
    (one call level deep, per the design)."""

    def __init__(self, module: "_Module", cls: Optional[str],
                 node: ast.AST, parent_locals: Dict[str, str]):
        self.module = module
        self.cls = cls
        self.node = node
        self.name = node.name
        self.qual = (f"{module.name}.{cls}.{node.name}" if cls
                     else f"{module.name}.{node.name}")
        #: local `x = threading.Lock()` names (closures inherit the
        #: enclosing function's, so a nested worker sees them)
        self.local_locks: Dict[str, str] = dict(parent_locals)
        self.acquisitions: List[Tuple[str, str, int]] = []  # lid, kind, line
        self.has_replace = False
        #: unmarked blocking calls that were NOT under a lock locally —
        #: candidates for one-call-deep TMG803 at a lock-holding caller
        self.lockfree_blocking: List[Tuple[int, str]] = []
        #: (ref, lineno, held) — calls made, with the locks held there
        #: as (lock id, acquisition line) pairs
        self.call_sites: List[Tuple[tuple, int,
                                    Tuple[Tuple[str, int], ...]]] = []
        #: (key, lineno, held) — shared-state mutations (TMG802)
        self.mutations: List[Tuple[tuple, int, Tuple[str, ...]]] = []


class _Module:
    def __init__(self, name: str, path: str, src: str):
        self.name = name
        self.path = path
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.module_locks: Dict[str, str] = {}        # name → kind
        self.module_globals: Set[str] = set()         # module-level names
        self.class_locks: Dict[Tuple[str, str], str] = {}  # (cls, attr) → kind
        self.class_attrs: Dict[str, Set[str]] = {}    # attr → {cls, …}
        self.functions: Dict[str, _Func] = {}         # "fn"/"Cls.fn" → _Func
        self.aliases: Dict[str, str] = {}             # local → program module
        self.time_mods: Set[str] = set()
        self.sleep_funcs: Set[str] = set()
        self.subprocess_mods: Set[str] = set()
        self.popen_funcs: Set[str] = set()
        self.socket_mods: Set[str] = set()
        self.fcntl_mods: Set[str] = set()
        self.threading_mods: Set[str] = set()
        self.thread_funcs: Set[str] = set()
        self.witness_funcs: Set[str] = set()          # witness_lock imports
        self.urlopen_funcs: Set[str] = set()
        self.thread_targets: Set[str] = set()

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def marked(self, lineno: int, marker: str) -> bool:
        if 1 <= lineno <= len(self.lines):
            return marker in self.lines[lineno - 1]
        return False


def _module_name(path: str) -> str:
    """Package-relative dotted name ('models._pallas_hist'); plain
    basename for paths outside the package (test fixtures)."""
    parts = os.path.normpath(path).split(os.sep)
    if "transmogrifai_tpu" in parts:
        rel = parts[parts.index("transmogrifai_tpu") + 1:]
    else:
        rel = parts[-1:]
    rel = [p[:-3] if p.endswith(".py") else p for p in rel]
    if rel and rel[-1] == "__init__":
        rel = rel[:-1] or ["__init__"]
    return ".".join(rel)


class _Program:
    """The whole-program view: every product module parsed, lock
    identities resolved across modules, then the per-function walks
    and the cross-module phases (graph, escape, propagation)."""

    def __init__(self) -> None:
        self.modules: Dict[str, _Module] = {}
        self.findings: List[Finding] = []
        #: attr → [(module, cls, kind)] for `self.attr = Lock()` defs
        self.attr_locks: Dict[str, List[Tuple[str, str, str]]] = {}
        #: attr → {(module, cls)} for any `self.attr = …` in __init__
        self.attr_owners: Dict[str, Set[Tuple[str, str]]] = {}
        self.lock_kinds: Dict[str, str] = {}
        #: (A, B) → [(outer_loc, outer_src, inner_loc, inner_src)]
        self.edges: Dict[Tuple[str, str],
                         List[Tuple[str, str, str, str]]] = {}
        #: path → {lineno → {rules silenced there}} (TMG399)
        self.used_markers: Dict[str, Dict[int, Set[str]]] = {}

    # -- intake ------------------------------------------------------------
    def add_source(self, path: str, src: str) -> bool:
        try:
            mod = _Module(_module_name(path), path, src)
        except SyntaxError:
            return False          # tmoglint owns TMG305 for parse errors
        self.modules[mod.name] = mod
        return True

    def _use_marker(self, path: str, lineno: int, rule: str) -> None:
        self.used_markers.setdefault(path, {}).setdefault(
            lineno, set()).add(rule)

    def _add(self, rule: str, mod: _Module, lineno: int,
             message: str) -> None:
        self.findings.append(Finding(
            rule, message, location=f"{mod.path}:{lineno}"))

    def _suppressible(self, rule: str, marker: str, mod: _Module,
                      lineno: int, message: str,
                      marker_sites: Optional[Sequence[
                          Tuple[_Module, int]]] = None) -> bool:
        """Emit unless a marker on one of ``marker_sites`` (default:
        the finding line) silences it; returns True when emitted."""
        sites = marker_sites or [(mod, lineno)]
        for m, ln in sites:
            if m.marked(ln, marker):
                self._use_marker(m.path, ln, MARKER_RULES[
                    marker.split("lint: ")[1]])
                return False
        self._add(rule, mod, lineno, message)
        return True

    # -- phase 1: per-module collection ------------------------------------
    def collect(self) -> None:
        for mod in self.modules.values():
            self._collect_module(mod)
        for mod in self.modules.values():
            for (cls, attr), kind in mod.class_locks.items():
                self.attr_locks.setdefault(attr, []).append(
                    (mod.name, cls, kind))
            for attr, clss in mod.class_attrs.items():
                for cls in clss:
                    self.attr_owners.setdefault(attr, set()).add(
                        (mod.name, cls))

    def _lock_ctor_kind(self, mod: _Module, call: ast.Call
                        ) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _LOCK_CTORS \
                and isinstance(f.value, ast.Name) \
                and f.value.id in mod.threading_mods:
            return _LOCK_CTORS[f.attr]
        if isinstance(f, ast.Name) and f.id in mod.thread_funcs \
                and f.id in _LOCK_CTORS:
            return _LOCK_CTORS[f.id]
        is_factory = (isinstance(f, ast.Name)
                      and f.id in mod.witness_funcs) or \
                     (isinstance(f, ast.Attribute)
                      and f.attr == _WITNESS_FACTORY)
        if is_factory:
            for kw in call.keywords:
                if kw.arg == "reentrant" \
                        and isinstance(kw.value, ast.Constant) \
                        and kw.value.value:
                    return "rlock"
            return "lock"
        return None

    def _collect_module(self, mod: _Module) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    tail = alias.name.split(".")[-1]
                    if alias.name == "time":
                        mod.time_mods.add(local)
                    elif alias.name == "threading":
                        mod.threading_mods.add(local)
                    elif alias.name == "subprocess":
                        mod.subprocess_mods.add(local)
                    elif alias.name == "socket":
                        mod.socket_mods.add(local)
                    elif alias.name == "fcntl":
                        mod.fcntl_mods.add(local)
                    elif tail in self.modules or alias.name in \
                            self.modules:
                        mod.aliases[alias.asname
                                    or alias.name] = alias.name
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                for alias in node.names:
                    local = alias.asname or alias.name
                    if m == "time" and alias.name == "sleep":
                        mod.sleep_funcs.add(local)
                    elif m == "threading":
                        mod.thread_funcs.add(local)
                    elif m == "subprocess" and alias.name == "Popen":
                        mod.popen_funcs.add(local)
                    elif alias.name == _WITNESS_FACTORY:
                        mod.witness_funcs.add(local)
                    elif alias.name == "urlopen":
                        mod.urlopen_funcs.add(local)
                    else:
                        # `from . import telemetry` / `from pkg import x`
                        mod.aliases[local] = alias.name
        # module-level names and locks
        for st in mod.tree.body:
            targets = []
            if isinstance(st, ast.Assign):
                targets = st.targets
                value = st.value
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                targets = [st.target]
                value = st.value
            else:
                continue
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                mod.module_globals.add(t.id)
                if isinstance(value, ast.Call):
                    kind = self._lock_ctor_kind(mod, value)
                    if kind:
                        mod.module_locks[t.id] = kind
                        self.lock_kinds[
                            f"{mod.name}.{t.id}"] = kind
        # classes: instance lock attrs + attr ownership
        for st in mod.tree.body:
            if not isinstance(st, ast.ClassDef):
                continue
            for sub in ast.walk(st):
                if not isinstance(sub, ast.Assign):
                    continue
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        mod.class_attrs.setdefault(
                            t.attr, set()).add(st.name)
                        if isinstance(sub.value, ast.Call):
                            kind = self._lock_ctor_kind(mod, sub.value)
                            if kind:
                                mod.class_locks[(st.name,
                                                 t.attr)] = kind
                                self.lock_kinds[
                                    f"{mod.name}.{st.name}."
                                    f"{t.attr}"] = kind
        # functions (methods + nested defs) and thread targets
        def add_funcs(body, cls, parent_locals):
            for st in body:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    fn = _Func(mod, cls, st, parent_locals)
                    for sub in ast.walk(st):
                        if isinstance(sub, ast.Assign) \
                                and isinstance(sub.value, ast.Call):
                            kind = self._lock_ctor_kind(mod, sub.value)
                            if kind:
                                for t in sub.targets:
                                    if isinstance(t, ast.Name):
                                        fn.local_locks[t.id] = kind
                                        self.lock_kinds[
                                            f"{fn.qual}.{t.id}"] = kind
                    key = f"{cls}.{st.name}" if cls else st.name
                    mod.functions.setdefault(key, fn)
                    add_funcs(st.body, cls, fn.local_locks)
                elif isinstance(st, ast.ClassDef):
                    add_funcs(st.body, st.name, parent_locals)
        add_funcs(mod.tree.body, None, {})
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                f = node.func
                is_thread = (isinstance(f, ast.Attribute)
                             and f.attr == "Thread"
                             and isinstance(f.value, ast.Name)
                             and f.value.id in mod.threading_mods) or \
                            (isinstance(f, ast.Name)
                             and f.id in mod.thread_funcs
                             and f.id == "Thread")
                if is_thread:
                    for kw in node.keywords:
                        if kw.arg == "target":
                            v = kw.value
                            if isinstance(v, ast.Name):
                                mod.thread_targets.add(v.id)
                            elif isinstance(v, ast.Attribute):
                                mod.thread_targets.add(v.attr)

    # -- lock-expression resolution ----------------------------------------
    def resolve_lock_expr(self, mod: _Module, fn: _Func,
                          expr) -> Optional[Tuple[str, str]]:
        """(lock id, kind) for an expression naming a lock object;
        ambiguous cross-class attribute locks get a '?'-prefixed id
        (held for TMG803, excluded from the TMG801 graph)."""
        if isinstance(expr, ast.Name):
            if expr.id in mod.module_locks:
                return (f"{mod.name}.{expr.id}",
                        mod.module_locks[expr.id])
            if expr.id in fn.local_locks:
                return (f"{fn.qual}.{expr.id}",
                        fn.local_locks[expr.id])
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and fn.cls is not None:
                    kind = mod.class_locks.get((fn.cls, expr.attr))
                    if kind:
                        return (f"{mod.name}.{fn.cls}.{expr.attr}",
                                kind)
                alias = mod.aliases.get(base.id)
                if alias is not None:
                    m2 = self._module_for(alias)
                    if m2 and expr.attr in m2.module_locks:
                        return (f"{m2.name}.{expr.attr}",
                                m2.module_locks[expr.attr])
            matches = self.attr_locks.get(expr.attr, [])
            if len(matches) == 1:
                m2, cls, kind = matches[0]
                return (f"{m2}.{cls}.{expr.attr}", kind)
            if len(matches) > 1:
                return (f"?.{expr.attr}", matches[0][2])
        return None

    def _module_for(self, dotted: str) -> Optional[_Module]:
        if dotted in self.modules:
            return self.modules[dotted]
        tail = dotted.split(".")[-1]
        if tail in self.modules:
            return self.modules[tail]
        for name, m in self.modules.items():
            if name.endswith("." + tail):
                return m
        return None

    # -- phase 2: per-function walks ---------------------------------------
    def walk(self) -> None:
        # stage A: direct acquisitions + os.replace flags (these feed
        # the one-call-deep resolution in stage B)
        for mod in self.modules.values():
            for fn in mod.functions.values():
                self._direct_summary(mod, fn)
        # stage B: held-stack walks
        for mod in self.modules.values():
            for fn in mod.functions.values():
                _FuncWalk(self, mod, fn).run()

    def _direct_summary(self, mod: _Module, fn: _Func) -> None:
        for node in _walk_shallow(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    r = self.resolve_lock_expr(mod, fn,
                                               item.context_expr)
                    if r:
                        fn.acquisitions.append(
                            (r[0], r[1], item.context_expr.lineno))
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr == "replace" \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "os":
                    fn.has_replace = True
                if self._flock_op(mod, node) == "acquire":
                    lid = f"{mod.name}.flock[{fn.name}]"
                    self.lock_kinds[lid] = "flock"
                    fn.acquisitions.append((lid, "flock",
                                            node.lineno))

    def _flock_op(self, mod: _Module, call: ast.Call) -> Optional[str]:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "flock"
                and isinstance(f.value, ast.Name)
                and f.value.id in mod.fcntl_mods):
            return None
        ops = {n.attr for a in call.args[1:2]
               for n in ast.walk(a)
               if isinstance(n, ast.Attribute)
               and n.attr.startswith("LOCK_")}
        if "LOCK_UN" in ops:
            return "release"
        return "acquire"

    # -- phase 3: cross-module rules ---------------------------------------
    def resolve_callees(self, mod: _Module, fn: _Func,
                        ref: tuple, unique: bool) -> List[_Func]:
        kind = ref[0]
        if kind == "bare":
            f = mod.functions.get(ref[1])
            if f is not None:
                return [f]
            cands = [g for q, g in mod.functions.items()
                     if q.endswith("." + ref[1])]
        elif kind == "self":
            if fn.cls is not None:
                f = mod.functions.get(f"{fn.cls}.{ref[1]}")
                if f is not None:
                    return [f]
            cands = [g for q, g in mod.functions.items()
                     if q.endswith("." + ref[1])]
        elif kind == "mod":
            m2 = self._module_for(mod.aliases.get(ref[1], ""))
            if m2 is None:
                return []
            f = m2.functions.get(ref[2])
            if f is not None:
                return [f]
            cands = [g for q, g in m2.functions.items()
                     if q.endswith("." + ref[2])]
        else:                       # ("attr", name): same-module methods
            cands = [g for q, g in mod.functions.items()
                     if q.split(".")[-1] == ref[1]]
        if unique and len(cands) != 1:
            return []
        return cands

    def record_edge(self, outer: str, inner: str,
                    outer_site: Tuple[_Module, int],
                    inner_site: Tuple[_Module, int]) -> None:
        if outer.startswith("?") or inner.startswith("?"):
            return                  # ambiguous locks never make cycles
        if outer == inner:
            if self.lock_kinds.get(outer) == "rlock":
                return              # re-entering an RLock is legal
        om, ol = outer_site
        im, il = inner_site
        self.edges.setdefault((outer, inner), []).append(
            (f"{om.path}:{ol}", om.line(ol),
             f"{im.path}:{il}", im.line(il)))

    def finish(self, stale_markers: bool = True) -> List[Finding]:
        self._call_edges_and_propagation()
        self._cycle_findings()
        self._thread_escape_findings()
        if stale_markers:
            self._stale_marker_findings()
        return sorted(self.findings, key=lambda f: f.location or "")

    def _call_edges_and_propagation(self) -> None:
        for mod in self.modules.values():
            for fn in mod.functions.values():
                for ref, lineno, held in fn.call_sites:
                    if not held or ref[0] == "attr":
                        continue    # bare attr calls resolve too
                                    # fuzzily for edge derivation
                    callees = self.resolve_callees(mod, fn, ref,
                                                   unique=True)
                    if not callees:
                        continue
                    callee = callees[0]
                    if callee is fn:
                        continue    # recursion is not a call edge
                    for lid, kind, acq_line in callee.acquisitions:
                        for h, h_line in held:
                            self.record_edge(
                                h, lid, (mod, h_line),
                                (callee.module, acq_line))
                    # one-call-deep TMG803: a lock held across a call
                    # into a function that blocks lock-free
                    for bl_line, reason in callee.lockfree_blocking:
                        locks = ", ".join(sorted(
                            h.lstrip("?") for h, _ln in held))
                        self._suppressible(
                            "TMG803", ALLOW_LOCK_BLOCKING, mod, lineno,
                            f"blocking {reason} reached while holding "
                            f"{locks}: {mod.path}:{lineno} calls "
                            f"{callee.qual} "
                            f"({callee.module.path}:{bl_line} "
                            f"'{callee.module.line(bl_line)}') with "
                            "the lock held — every thread needing it "
                            "stalls behind that wait (allow: "
                            f"'# {ALLOW_LOCK_BLOCKING} — <reason>')",
                            marker_sites=[(mod, lineno),
                                          (callee.module, bl_line)])

    def _cycle_findings(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        # self-deadlocks first (a non-reentrant lock re-acquired)
        for (a, b) in sorted(self.edges):
            if a != b:
                continue
            self._emit_cycle(
                [a], [(a, a)],
                f"non-reentrant lock {a} re-acquired while already "
                f"held — self-deadlock")
        # cycles between distinct locks: DFS over the edge graph
        seen_cycles: Set[Tuple[str, ...]] = set()
        for start in sorted(adj):
            path: List[str] = []
            on_path: Set[str] = set()

            def dfs(n: str) -> None:
                path.append(n)
                on_path.add(n)
                for nxt in sorted(adj.get(n, ())):
                    if nxt == n:
                        continue
                    if nxt in on_path:
                        cyc = path[path.index(nxt):]
                        key = tuple(sorted(cyc))
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            edges = [(cyc[i], cyc[(i + 1) % len(cyc)])
                                     for i in range(len(cyc))]
                            self._emit_cycle(
                                cyc, edges,
                                "lock-order cycle "
                                f"{' -> '.join(cyc + [cyc[0]])} — two "
                                "threads on these paths deadlock")
                    elif len(path) < 16:
                        dfs(nxt)
                path.pop()
                on_path.discard(n)

            dfs(start)

    def _module_of_loc(self, loc: str) -> Optional[_Module]:
        path = loc.rsplit(":", 1)[0]
        for m in self.modules.values():
            if m.path == path:
                return m
        return None

    def _emit_cycle(self, cyc: List[str],
                    edges: List[Tuple[str, str]], headline: str) -> None:
        lines = [headline + ":"]
        marker_sites: List[Tuple[_Module, int]] = []
        first_loc = None
        for a, b in edges:
            sites = self.edges.get((a, b), [])
            if not sites:
                continue
            outer_loc, outer_src, inner_loc, inner_src = sites[0]
            if first_loc is None:
                first_loc = inner_loc
            lines.append(f"  {a} -> {b}:")
            lines.append(f"    {outer_loc}: {outer_src}")
            lines.append(f"    {inner_loc}: {inner_src}")
            for loc in (outer_loc, inner_loc):
                m = self._module_of_loc(loc)
                if m is not None:
                    marker_sites.append(
                        (m, int(loc.rsplit(":", 1)[1])))
        if first_loc is None:
            return
        mod = self._module_of_loc(first_loc)
        if mod is None:
            return
        self._suppressible(
            "TMG801", ALLOW_LOCK_ORDER, mod,
            int(first_loc.rsplit(":", 1)[1]),
            "\n".join(lines) + "\n  break the cycle (one global "
            "acquisition order) or mark a quoted line "
            f"'# {ALLOW_LOCK_ORDER} — <reason>'",
            marker_sites=marker_sites)

    def _thread_reachable(self, mod: _Module) -> Set[str]:
        """Function quals in ``mod`` reachable from a Thread target
        (TMG310's target resolution, made transitive over the module
        call graph)."""
        roots: Set[str] = set()
        for tgt in mod.thread_targets:
            for q, fn in mod.functions.items():
                if q == tgt or q.split(".")[-1] == tgt:
                    roots.add(fn.qual)
        reach = set(roots)
        frontier = list(roots)
        quals = {fn.qual: fn for fn in mod.functions.values()}
        while frontier:
            q = frontier.pop()
            fn = quals.get(q)
            if fn is None:
                continue
            for ref, _lineno, _held in fn.call_sites:
                for callee in self.resolve_callees(mod, fn, ref,
                                                   unique=False):
                    if callee.module is mod \
                            and callee.qual not in reach:
                        reach.add(callee.qual)
                        frontier.append(callee.qual)
        return reach

    def _thread_escape_findings(self) -> None:
        # group mutation sites program-wide by state key
        groups: Dict[tuple, List[Tuple[_Func, int,
                                       Tuple[str, ...]]]] = {}
        for mod in self.modules.values():
            for fn in mod.functions.values():
                for key, lineno, held in fn.mutations:
                    groups.setdefault(key, []).append(
                        (fn, lineno, held))
        reach_cache: Dict[str, Set[str]] = {}
        for key, sites in sorted(groups.items(), key=lambda kv:
                                 str(kv[0])):
            locked = [s for s in sites if s[2]]
            unlocked = [s for s in sites if not s[2]]
            if not locked or not unlocked:
                continue
            guard = ", ".join(sorted({h.lstrip("?") for s in locked
                                      for h in s[2]}))
            ex_fn, ex_line, ex_held = locked[0]
            state = (f"{key[1]}.{key[2]}" if key[0] == "g"
                     else f"{key[1]}.{key[2]}.{key[3]}")
            for fn, lineno, _held in unlocked:
                if fn.name == "__init__":
                    continue
                mod = fn.module
                if mod.name not in reach_cache:
                    reach_cache[mod.name] = self._thread_reachable(mod)
                if fn.qual not in reach_cache[mod.name]:
                    continue
                self._suppressible(
                    "TMG802", ALLOW_THREAD_ESCAPE, mod, lineno,
                    f"shared state {state} mutated lock-free on a "
                    "thread-reachable path while its other mutation "
                    f"sites hold {guard}:\n"
                    f"  unlocked: {mod.path}:{lineno}: "
                    f"{mod.line(lineno)}\n"
                    f"  locked:   {ex_fn.module.path}:{ex_line}: "
                    f"{ex_fn.module.line(ex_line)}\n"
                    "  guard the mutation (or mark it "
                    f"'# {ALLOW_THREAD_ESCAPE} — <reason>')")

    def _stale_marker_findings(self) -> None:
        for mod in self.modules.values():
            used = self.used_markers.get(mod.path, {})
            try:
                tokens = list(tokenize.generate_tokens(
                    io.StringIO("\n".join(mod.lines) + "\n").readline))
            except (tokenize.TokenError, IndentationError,
                    SyntaxError):
                continue
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _MARKER_RE.search(tok.string)
                if m is None:
                    continue
                rule = MARKER_RULES.get(m.group(1))
                if rule is None:
                    continue        # tmoglint's vocabulary, not ours
                lineno = tok.start[0]
                if rule in used.get(lineno, ()):
                    continue
                self._add(
                    "TMG399", mod, lineno,
                    f"stale suppression: 'lint: {m.group(1)}' "
                    f"silences {rule} but nothing on this line "
                    "triggers that rule anymore — delete the marker "
                    "(or fix it if it names the wrong rule)")


class _FuncWalk:
    """Stage-B walk of one function: tracks the held-lock stack
    through nested ``with`` bodies and flock calls, recording
    acquisition edges, call sites, blocking calls and shared-state
    mutations with the locks held at each."""

    def __init__(self, prog: _Program, mod: _Module, fn: _Func):
        self.prog = prog
        self.mod = mod
        self.fn = fn
        #: flocks stay held from their call site to function end (or
        #: an explicit LOCK_UN) — function-scoped, not block-scoped;
        #: entries are (lock id, kind, acquisition line)
        self.extra: List[Tuple[str, str, int]] = []

    def run(self) -> None:
        body = getattr(self.fn.node, "body", [])
        self.stmts(body, [])

    def held_ids(self, held) -> Tuple[str, ...]:
        return tuple(lid for lid, _k, _ln in held + self.extra)

    def held_sites(self, held) -> Tuple[Tuple[str, int], ...]:
        return tuple((lid, ln) for lid, _k, ln in held + self.extra)

    # -- statements --------------------------------------------------------
    def stmts(self, body, held) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue            # summarized as their own _Func
            if isinstance(st, (ast.With, ast.AsyncWith)):
                self.with_stmt(st, held)
                continue
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self.expr(child, held)
            if isinstance(st, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign)):
                self.mutation(st, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if sub:
                    self.stmts(sub, held)
            for h in getattr(st, "handlers", []):
                self.stmts(h.body, held)

    def with_stmt(self, st, held) -> None:
        new: List[Tuple[str, str, int]] = []
        for item in st.items:
            expr = item.context_expr
            acquired: List[Tuple[str, str, int]] = []
            r = self.prog.resolve_lock_expr(self.mod, self.fn, expr)
            if r is not None:
                acquired.append((r[0], r[1], expr.lineno))
            elif isinstance(expr, ast.Call):
                # `with self._pointer_mutation(name):` — a context
                # manager call holds whatever IT directly acquires
                ref = self.call_ref(expr)
                if ref is not None:
                    for callee in self.prog.resolve_callees(
                            self.mod, self.fn, ref, unique=True):
                        for lid, kind, acq_line in callee.acquisitions:
                            acquired.append((lid, kind, expr.lineno))
                self.expr(expr, held + new)     # classify the call too
            else:
                self.expr(expr, held + new)
            for lid, kind, lineno in acquired:
                for h, _k, h_line in held + new + self.extra:
                    self.prog.record_edge(
                        h, lid, (self.mod, h_line),
                        (self.mod, lineno))
                new.append((lid, kind, lineno))
        self.stmts(st.body, held + new)

    def mutation(self, st, held) -> None:
        if self.fn.name == "__init__":
            return                  # construction is single-threaded
        targets = st.targets if isinstance(st, ast.Assign) \
            else [st.target]
        for t in targets:
            key = self.state_key(t)
            if key is not None:
                self.fn.mutations.append(
                    (key, st.lineno, self.held_ids(held)))

    def state_key(self, t) -> Optional[tuple]:
        """('g', module, name) for module-global stores, ('a', module,
        cls, attr) for shared-object attribute stores, else None."""
        # peel subscripts: `_TALLY[k] = v` mutates _TALLY
        while isinstance(t, ast.Subscript):
            t = t.value
        if isinstance(t, ast.Name):
            if t.id in self.mod.module_globals \
                    and t.id not in self.mod.module_locks:
                return ("g", self.mod.name, t.id)
            return None
        if isinstance(t, ast.Attribute) \
                and isinstance(t.value, ast.Name):
            attr = t.attr
            if t.value.id == "self" and self.fn.cls is not None:
                if (self.fn.cls, attr) in self.mod.class_locks:
                    return None
                return ("a", self.mod.name, self.fn.cls, attr)
            if t.value.id != "self":
                owners = self.prog.attr_owners.get(attr, set())
                if len(owners) == 1:
                    mname, cls = next(iter(owners))
                    m2 = self.prog._module_for(mname)
                    if m2 is not None \
                            and (cls, attr) in m2.class_locks:
                        return None
                    return ("a", mname, cls, attr)
        return None

    # -- expressions -------------------------------------------------------
    def expr(self, node, held) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self.call(sub, held)

    def call_ref(self, call: ast.Call) -> Optional[tuple]:
        f = call.func
        if isinstance(f, ast.Name):
            return ("bare", f.id)
        if isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name):
            if f.value.id == "self":
                return ("self", f.attr)
            if f.value.id in self.mod.aliases:
                return ("mod", f.value.id, f.attr)
            return ("attr", f.attr)
        if isinstance(f, ast.Attribute):
            return ("attr", f.attr)
        return None

    def call(self, call: ast.Call, held) -> None:
        op = self.prog._flock_op(self.mod, call)
        if op == "acquire":
            lid = f"{self.mod.name}.flock[{self.fn.name}]"
            for h, _k, h_line in held + self.extra:
                self.prog.record_edge(h, lid, (self.mod, h_line),
                                      (self.mod, call.lineno))
            self.extra.append((lid, "flock", call.lineno))
            return
        if op == "release":
            self.extra = [e for e in self.extra if e[1] != "flock"]
            return
        # mutator-method calls on module globals are mutations too
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.mod.module_globals \
                and f.value.id not in self.mod.module_locks \
                and self.fn.name != "__init__":
            self.fn.mutations.append(
                (("g", self.mod.name, f.value.id), call.lineno,
                 self.held_ids(held)))
        reason = self.blocking_reason(call)
        if reason is not None:
            if self.mod.marked(call.lineno, ALLOW_LOCK_BLOCKING):
                self.prog._use_marker(self.mod.path, call.lineno,
                                      "TMG803")
            elif held or self.extra:
                locks = ", ".join(sorted(
                    h.lstrip("?") for h, _k, _ln in held + self.extra))
                self.prog._add(
                    "TMG803", self.mod, call.lineno,
                    f"blocking {reason} while holding {locks} "
                    f"('{self.mod.line(call.lineno)}') — every other "
                    "thread needing the lock stalls behind I/O it "
                    "cannot see; move the call outside the lock body "
                    "(or mark it "
                    f"'# {ALLOW_LOCK_BLOCKING} — <reason>')")
            else:
                self.fn.lockfree_blocking.append((call.lineno, reason))
        ref = self.call_ref(call)
        if ref is not None:
            self.fn.call_sites.append(
                (ref, call.lineno, self.held_sites(held)))
        self.open_call(call)

    def blocking_reason(self, call: ast.Call) -> Optional[str]:
        f = call.func
        kwargs = {kw.arg for kw in call.keywords}
        if isinstance(f, ast.Name):
            if f.id in self.mod.sleep_funcs:
                return "time.sleep()"
            if f.id in self.mod.popen_funcs:
                return "subprocess.Popen()"
            if f.id in self.mod.urlopen_funcs:
                return "urlopen()"
            return None
        if not isinstance(f, ast.Attribute):
            return None
        base = _dotted(f.value) or ""
        if f.attr == "sleep" and isinstance(f.value, ast.Name) \
                and f.value.id in self.mod.time_mods:
            return "time.sleep()"
        if f.attr in ("get", "put"):
            b = base.lower()
            if "queue" in b or b.endswith("_q") or b == "q":
                if "timeout" in kwargs or "block" in kwargs:
                    return None
                if f.attr == "put" and len(call.args) > 1:
                    return None     # positional block= given
                if f.attr == "get" and call.args:
                    return None
                return f"queue.{f.attr}() with no timeout"
        if f.attr == "join" and not call.args and not call.keywords \
                and not isinstance(f.value, ast.Constant):
            return ".join() with no timeout"   # str.join has args
        if f.attr == "wait" and not call.args and not call.keywords:
            # cv.wait() RELEASES the condition it is called on — the
            # canonical pattern, not a block-while-holding
            r = self.prog.resolve_lock_expr(self.mod, self.fn,
                                            f.value)
            if r is not None and r[1] == "condition":
                return None
            return ".wait() with no timeout"
        if f.attr == "communicate" and "timeout" not in kwargs:
            return ".communicate() with no timeout"
        if isinstance(f.value, ast.Name):
            if f.value.id in self.mod.subprocess_mods and f.attr in (
                    "run", "call", "check_call", "check_output",
                    "Popen"):
                return f"subprocess.{f.attr}()"
            if f.value.id in self.mod.socket_mods:
                return f"socket.{f.attr}()"
        if f.attr in ("urlopen", "getresponse", "create_connection"):
            return f".{f.attr}()"
        return None

    def open_call(self, call: ast.Call) -> None:
        """TMG804: non-atomic writes into shared artifact families."""
        f = call.func
        if not (isinstance(f, ast.Name) and f.id == "open"):
            return
        mode = None
        if len(call.args) > 1 and isinstance(call.args[1],
                                             ast.Constant):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if not (isinstance(mode, str) and "w" in mode):
            return
        if not call.args:
            return
        seg = ast.get_source_segment(
            "\n".join(self.mod.lines) + "\n", call.args[0]) or ""
        low = seg.lower()
        if "tmp" in low or self.fn.has_replace:
            return
        if not any(h in low for h in _SHARED_ARTIFACT_HINTS):
            return
        self.prog._suppressible(
            "TMG804", ALLOW_ATOMIC_WRITE, self.mod, call.lineno,
            f"non-atomic write open({seg!r}, {mode!r}) into a shared "
            "artifact family with no tmp staging and no os.replace in "
            f"{self.fn.qual} — a crash mid-write leaves a torn file "
            "every reader then trusts; write to <path>.tmp.<pid> and "
            "os.replace() it into place (or mark a deliberate "
            f"in-place write '# {ALLOW_ATOMIC_WRITE} — <reason>')")


# -- TMG805: fault-site coverage -------------------------------------------
def fault_coverage_findings(tests_dir: str) -> List[Finding]:
    """Every registered fault site must be exercised by at least one
    test: its site string must appear under ``tests_dir``."""
    from transmogrifai_tpu import resilience
    corpus = []
    for root, dirs, files in os.walk(tests_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if fname.endswith(".py"):
                with open(os.path.join(root, fname),
                          encoding="utf-8") as fh:
                    corpus.append(fh.read())
    text = "\n".join(corpus)
    res_path = resilience.__file__
    with open(res_path, encoding="utf-8") as fh:
        res_lines = fh.read().splitlines()
    findings: List[Finding] = []
    for site in sorted(resilience.FAULT_SITES):
        if f'"{site}"' in text or f"'{site}'" in text:
            continue
        lineno = next((i + 1 for i, ln in enumerate(res_lines)
                       if f'"{site}"' in ln), 0)
        findings.append(Finding(
            "TMG805",
            f"fault site '{site}' (resilience.FAULT_SITES) is "
            f"exercised by NO test under {tests_dir} — an untested "
            "fault site is a recovery path that has never once run; "
            "add a chaos test injecting it",
            location=f"{res_path}:{lineno}"))
    return findings


# -- public API ------------------------------------------------------------
def _is_test_path(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    return "tests" in parts or os.path.basename(path).startswith(
        "test_")


def analyze_sources(files: Dict[str, str],
                    stale_markers: bool = True) -> List[Finding]:
    """Run the whole-program TMG8xx pass over ``{path: source}``."""
    prog = _Program()
    for path, src in sorted(files.items()):
        prog.add_source(path, src)
    prog.collect()
    prog.walk()
    return prog.finish(stale_markers=stale_markers)


def lint_paths(paths: Sequence[str], tests_dir: Optional[str] = None,
               stale_markers: bool = True) -> List[Finding]:
    """Analyze every product ``.py`` under ``paths`` as ONE program
    (tests and ``__pycache__`` skipped); optionally cross-check fault-
    site coverage against ``tests_dir`` (TMG805)."""
    files: Dict[str, str] = {}
    for p in paths:
        if os.path.isfile(p):
            if not _is_test_path(p):
                with open(p, encoding="utf-8") as fh:
                    files[p] = fh.read()
            continue
        for root, dirs, fnames in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", "tests"))
            for fn in sorted(fnames):
                fp = os.path.join(root, fn)
                if fn.endswith(".py") and not _is_test_path(fp):
                    with open(fp, encoding="utf-8") as fh:
                        files[fp] = fh.read()
    findings = analyze_sources(files, stale_markers=stale_markers)
    if tests_dir is not None and os.path.isdir(tests_dir):
        findings.extend(fault_coverage_findings(tests_dir))
    return sorted(findings, key=lambda f: f.location or "")


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="concurrency_lint",
        description="whole-program concurrency & crash-safety "
                    "analysis (TMG8xx)")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_REPO, "transmogrifai_tpu")],
                    help="files/directories analyzed as one program "
                         "(default: the transmogrifai_tpu package)")
    ap.add_argument("--tests", default=os.path.join(_REPO, "tests"),
                    help="tests directory for the TMG805 fault-site "
                         "coverage cross-check (default: tests/)")
    ap.add_argument("--no-tests-check", action="store_true",
                    help="skip the TMG805 coverage cross-check")
    ap.add_argument("--fail-on", choices=("error", "warning"),
                    default="error",
                    help="exit non-zero when findings reach this "
                         "severity (default: error)")
    ap.add_argument("--no-stale-markers", action="store_true",
                    help="skip the TMG399 stale-suppression pass")
    args = ap.parse_args(argv)
    findings = lint_paths(
        args.paths,
        tests_dir=None if args.no_tests_check else args.tests,
        stale_markers=not args.no_stale_markers)
    for f in findings:
        print(f.format())
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    summary = ", ".join(f"{counts.get(s, 0)} {s}(s)"
                        for s in (Severity.ERROR, Severity.WARNING,
                                  Severity.INFO))
    print(f"concurrency_lint: {summary}")
    try:
        enforce(findings, fail_on=args.fail_on)
    except Exception:   # lint: broad-except — CLI boundary: findings already printed
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
