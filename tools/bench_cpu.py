"""Host-CPU denominator for the bench (VERDICT r3 #3): the same code,
same sweeps, on the CPU backend — the honest stand-in for the reference's
Spark ``local[8]`` wall-clock, which BASELINE's "≥20× faster" north star
needs a measured denominator for.

Run as a SUBPROCESS from bench.py (the axon sitecustomize pins the jax
platform at interpreter start, so the pin must be overridden before any
backend init — env vars alone are ignored). Prints a JSON line after
EVERY completed stage (cumulative), so a caller that kills the process
on a timeout still gets whatever finished:

    {"titanic_warm_s": ..., "titanic_AuPR": ...,
     "synth_rows": N, "synth_s_incl_compile": ...}
"""
import json
import os
import signal
import sys
import time


class _Timeout(Exception):
    pass


def _raise(*_a):
    raise _Timeout()


def main() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), os.pardir))
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), os.pardir, "examples"))
    assert jax.default_backend() == "cpu", jax.default_backend()

    out = {"backend": "cpu", "cpu_count": os.cpu_count()}
    signal.signal(signal.SIGALRM, _raise)

    # The synthetic sweep FIRST: at the default reduced row count it
    # finishes on one core in ~65 s (measured: 5000 rows incl compile),
    # while the titanic cold+warm pair needs ~600 s — ordering the
    # cheap, always-capturable stage first means the caller's bounded
    # budget records a MEASURED tree-sweep denominator and only the
    # titanic number degrades to a lower bound. The sweep is otherwise
    # brutally slow on the CPU backend (largely single-core — 100k rows
    # exceeded 30 minutes); the caller extrapolates the reduced row
    # count linearly (a conservative floor) or reports the timeout.
    synth_rows = int(os.environ.get("BENCH_CPU_SYNTH_ROWS", 5_000))
    budget_s = int(os.environ.get("BENCH_CPU_SYNTH_TIMEOUT_S", 900))
    if synth_rows > 0:
        signal.alarm(budget_s)
        try:
            from synthetic_trees import run as run_synth
            t0 = time.time()
            r = run_synth(n_rows=synth_rows, num_folds=3, seed=42)
            out["synth_rows"] = synth_rows
            # single pass: includes CPU compile (small next to execution
            # at these ratios); labeled accordingly
            out["synth_s_incl_compile"] = round(r["train_time_s"], 2)
        except _Timeout:
            out["synth_rows"] = synth_rows
            out["synth_timeout_s"] = budget_s
        finally:
            signal.alarm(0)
        print(json.dumps(out), flush=True)

    # titanic under its own alarm so a partial line always lands even if
    # the CPU backend is slower than the caller's whole budget
    tit_budget = int(os.environ.get("BENCH_CPU_TITANIC_TIMEOUT_S", 180))
    signal.alarm(tit_budget)
    try:
        from titanic import run as run_titanic
        run_titanic(num_folds=3, seed=42)                   # cold
        t0 = time.time()
        r = run_titanic(num_folds=3, seed=42)
        out["titanic_warm_s"] = round(r["train_time_s"], 2)
        out["titanic_total_warm_s"] = round(time.time() - t0, 2)
        h = r["summary"].holdout_evaluation or {}
        out["titanic_AuPR"] = round(float(h.get("AuPR", 0.0)), 4)
    except _Timeout:
        out["titanic_timeout_s"] = tit_budget
    finally:
        signal.alarm(0)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
