"""Benchmark entry — run by the driver on real TPU hardware.

Runs the reference's headline workload: the Titanic
BinaryClassificationModelSelector CV sweep (README.md:62-64: LR + RF grids,
3 folds, AuPR selection) end-to-end — feature engineering, sanity checking,
the batched CV grid, final refit, holdout evaluation.

The sweep runs TWICE in-process: the first (cold) run pays tracing + XLA
compilation, the second (warm) run measures steady-state device time —
the number that scales to repeated AutoML workloads. The persistent
compilation cache makes later cold runs on the same host ≈ warm.

Prints ONE JSON line:
  metric      titanic_holdout_AuPR — parity metric against the only
              published reference number (README.md:89 AuPR = 0.8225)
  value       our holdout AuPR
  vs_baseline value / 0.8225  (>1 = better than reference)
  extras      cv_wallclock_s (warm steady-state train wall-clock),
              cv_cold_s (first run incl. compile), compile_s (difference),
              backend, n_devices
"""
from __future__ import annotations

import json
import os
import sys
import time

REFERENCE_AUPR = 0.8225  # /root/reference/README.md:89


def main() -> None:
    import jax

    os.makedirs("/tmp/transmogrifai_jax_cache", exist_ok=True)
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/transmogrifai_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    backend = jax.default_backend()
    sys.path.insert(0, "examples")
    from titanic import run

    t0 = time.time()
    out_cold = run(num_folds=3, seed=42)
    cold_s = time.time() - t0

    t1 = time.time()
    out = run(num_folds=3, seed=42)
    warm_s = time.time() - t1

    summary = out["summary"]
    holdout = summary.holdout_evaluation or {}
    aupr = float(holdout.get("AuPR", 0.0))

    print(json.dumps({
        "metric": "titanic_holdout_AuPR",
        "value": round(aupr, 4),
        "unit": "AuPR",
        "vs_baseline": round(aupr / REFERENCE_AUPR, 4),
        "cv_wallclock_s": round(out["train_time_s"], 2),
        "cv_cold_s": round(out_cold["train_time_s"], 2),
        "compile_s": round(cold_s - warm_s, 2),
        "total_wallclock_s": round(time.time() - t0, 2),
        "best_model": summary.best_model_name,
        "backend": backend,
        "n_devices": len(jax.devices()),
    }))


if __name__ == "__main__":
    main()
