"""Benchmark entry — run by the driver on real TPU hardware.

Covers the five BASELINE.json configs:

1. ``titanic``   — Titanic BinaryClassificationModelSelector CV sweep
                   (reference README.md:62-89; parity AuPR 0.8225)
2. ``iris``      — Iris MultiClassificationModelSelector (string labels
                   indexed + prediction deindexed), F1 selection
3. ``boston``    — Boston housing RegressionModelSelector (RF + GBT), RMSE
4. ``big_text``  — SmartTextVectorizer-heavy BigPassenger-schema workflow
                   at 30k synthesized rows (hashing-path text + one-hot +
                   dates), LR grid
5. ``synthetic_trees`` — RF + GBT + XGB grid, 3-fold CV, 200k×20 synthetic
                   rows by default (BENCH_SYNTH_ROWS overrides; the same
                   sweep completes at 1M rows single-chip in ~137s warm
                   via host-level fold/grid chunking — the 10M BASELINE
                   target data-shards 1.25M rows/chip on a v5e-8)

Every config runs TWICE in-process: the first (cold) run pays tracing +
XLA compilation, the second (warm) run is the steady-state number that
scales to repeated AutoML workloads (compiled executables are cached
across ``validate()`` calls keyed by trace signature + shapes).

Prints ONE JSON line. Headline metric stays ``titanic_holdout_AuPR``
(the only published reference number); per-config results ride in
``configs``.
"""
from __future__ import annotations

import json
import os
import sys
import time

REFERENCE_AUPR = 0.8225  # /root/reference/README.md:89

#: TPU v5e per-chip peaks (public spec: 197 bf16 TFLOP/s; f32 runs
#: through the same MXU at ~1/4 rate — stated assumption, see
#: docs/performance.md "MFU" for the caveats)
V5E_PEAK_BF16 = 197e12
V5E_PEAK_F32 = 49e12


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _flops_total() -> float:
    from transmogrifai_tpu.models.tuning import DEVICE_FLOPS
    return DEVICE_FLOPS["total"]


def _run_twice(fn, name: str):
    t0 = time.time()
    out_cold = fn()
    cold_s = time.time() - t0
    _log(f"[bench] {name} cold {cold_s:.1f}s")
    f0 = _flops_total()
    t1 = time.time()
    out_warm = fn()
    warm_s = time.time() - t1
    warm_flops = _flops_total() - f0
    _log(f"[bench] {name} warm {warm_s:.1f}s "
         f"({warm_flops / 1e9:.1f} GFLOP dispatched)")
    return out_cold, out_warm, cold_s, warm_s, warm_flops


def _mfu_fields(warm_flops: float, train_s: float) -> dict:
    """Achieved FLOP/s over the warm TRAIN wall-clock vs v5e-1 peak.

    Wall-clock (not device-busy) is the honest denominator for an AutoML
    sweep: host feature prep and dispatch gaps count against utilization.
    The executed-FLOP numerator comes from XLA cost analysis of every
    dispatched CV executable (models/tuning.DEVICE_FLOPS)."""
    if train_s <= 0:
        return {}
    fps = warm_flops / train_s
    return {"device_tflop": round(warm_flops / 1e12, 4),
            "achieved_tflops": round(fps / 1e12, 4),
            "mfu_bf16_pct": round(100.0 * fps / V5E_PEAK_BF16, 3),
            "mfu_f32_pct": round(100.0 * fps / V5E_PEAK_F32, 3)}


def main() -> None:
    import jax

    os.makedirs("/tmp/transmogrifai_jax_cache", exist_ok=True)
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/transmogrifai_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    backend = jax.default_backend()
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "examples"))
    configs = {}

    # 1. Titanic (headline parity config)
    from titanic import run as run_titanic
    cold, warm, cold_s, warm_s, wf = _run_twice(
        lambda: run_titanic(num_folds=3, seed=42), "titanic")
    holdout = warm["summary"].holdout_evaluation or {}
    aupr = float(holdout.get("AuPR", 0.0))
    configs["titanic"] = {
        "AuPR": round(aupr, 4),
        "vs_reference": round(aupr / REFERENCE_AUPR, 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "best_model": warm["summary"].best_model_name,
        **_mfu_fields(wf, warm["train_time_s"]),
    }

    # 2. Iris multiclass (string labels round-trip)
    from iris import run as run_iris
    cold, warm, cold_s, warm_s, wf = _run_twice(
        lambda: run_iris(num_folds=3, seed=42), "iris")
    configs["iris"] = {
        "F1": round(float(warm["metrics"]["F1"]), 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "best_model": warm["summary"].best_model_name,
        **_mfu_fields(wf, warm["train_time_s"]),
    }

    # 3. Boston regression
    from boston import run as run_boston
    cold, warm, cold_s, warm_s, wf = _run_twice(
        lambda: run_boston(num_folds=3, seed=42), "boston")
    configs["boston"] = {
        "RMSE": round(float(warm["metrics"]["RootMeanSquaredError"]), 4),
        "R2": round(float(warm["metrics"]["R2"]), 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "best_model": warm["summary"].best_model_name,
        **_mfu_fields(wf, warm["train_time_s"]),
    }

    # 4. SmartText-heavy (BigPassenger schema at scale — 300k rows per
    #    VERDICT r3 #4: host text prep + the fusion decision measured at
    #    non-toy size)
    big_rows = int(os.environ.get("BENCH_TEXT_ROWS", 300_000))
    from big_passenger import run as run_big
    cold, warm, cold_s, warm_s, wf = _run_twice(
        lambda: run_big(n_rows=big_rows, num_folds=3, seed=42), "big_text")
    from big_passenger import TARGET_AUPR
    big_aupr = float(warm["metrics"]["AuPR"])
    configs["big_text"] = {
        "rows": big_rows,
        "AuPR": round(big_aupr, 4),
        "target_AuPR": TARGET_AUPR,
        "quality": "PASS" if big_aupr >= TARGET_AUPR else "FAIL",
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "phases": warm.get("phases"),
        **_mfu_fields(wf, warm["train_time_s"]),
    }

    # 5. Synthetic tree grid at scale (the BASELINE scale config: default
    #    2M rows single-chip; BENCH_SYNTH_ROWS overrides — 10M data-shards
    #    1.25M rows/chip on a v5e-8, see docs/performance.md)
    synth_rows = int(os.environ.get("BENCH_SYNTH_ROWS", 2_000_000))
    from synthetic_trees import run as run_synth
    cold, warm, cold_s, warm_s, wf = _run_twice(
        lambda: run_synth(n_rows=synth_rows, num_folds=3, seed=42),
        "synthetic_trees")
    configs["synthetic_trees"] = {
        "rows": synth_rows,
        "AuPR": round(float(warm["metrics"]["AuPR"]), 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "best_model": warm["summary"].best_model_name,
        "phases": warm.get("phases"),
        **_mfu_fields(wf, warm["train_time_s"]),
    }

    # 5b. The FULL 10M-row BASELINE config (VERDICT r3 #2) — one pass
    #     (its own shapes compile fresh; a second pass would double a
    #     multi-minute run for a number that matters as "it runs at all").
    full_rows = int(os.environ.get("BENCH_SYNTH_FULL_ROWS", 10_000_000))
    if full_rows > synth_rows and backend == "tpu":
        try:
            f0 = _flops_total()
            t0 = time.time()
            out_full = run_synth(n_rows=full_rows, num_folds=3, seed=42)
            full_total = time.time() - t0
            configs["synthetic_trees_full"] = {
                "rows": full_rows,
                "AuPR": round(float(out_full["metrics"]["AuPR"]), 4),
                "train_s_incl_compile": round(
                    out_full["train_time_s"], 2),
                "total_s": round(full_total, 2),
                "best_model": out_full["summary"].best_model_name,
                "phases": out_full.get("phases"),
                **_mfu_fields(_flops_total() - f0,
                              out_full["train_time_s"]),
            }
        except Exception as e:          # record instead of killing bench
            _log(f"[bench] 10M config failed: {e!r}")
            configs["synthetic_trees_full"] = {
                "rows": full_rows, "error": repr(e)[:400]}

    # CPU-host denominator (VERDICT r3 #3): same code on the host CPU
    # backend as the Spark-local[8] proxy. Subprocess (the axon shim pins
    # the platform per process). Synthetic runs at a reduced row count by
    # default and extrapolates LINEARLY — conservative: CPU throughput
    # degrades with rows (cache pressure), so the reported speedup is a
    # floor. BENCH_CPU=0 disables; BENCH_CPU_SYNTH_ROWS overrides.
    if os.environ.get("BENCH_CPU", "1") != "0" and backend == "tpu":
        import subprocess
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        try:
            t0 = time.time()
            proc = subprocess.run(
                [sys.executable, os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "tools", "bench_cpu.py")],
                env=env, capture_output=True, text=True,
                timeout=int(os.environ.get("BENCH_CPU_TIMEOUT_S", 2400)))
            line = proc.stdout.strip().splitlines()[-1]
            cpu = json.loads(line)
            cpu["wall_s"] = round(time.time() - t0, 1)
            configs["cpu_host_denominator"] = cpu
            tw = configs["titanic"]["cv_warm_s"]
            if tw > 0 and cpu.get("titanic_warm_s"):
                configs["titanic"]["speedup_vs_cpu_host"] = round(
                    cpu["titanic_warm_s"] / tw, 2)
            sw = configs["synthetic_trees"]["cv_warm_s"]
            cpu_rows = cpu.get("synth_rows")
            if sw > 0 and cpu_rows:
                scale = synth_rows / cpu_rows
                if cpu.get("synth_s_incl_compile"):
                    # linear extrapolation from the measured small-row CPU
                    # run — a conservative FLOOR (CPU throughput degrades
                    # with working-set size)
                    configs["synthetic_trees"]["speedup_vs_cpu_host_est"] \
                        = round(cpu["synth_s_incl_compile"] * scale / sw, 2)
                elif cpu.get("synth_timeout_s"):
                    # CPU did not finish even the reduced config in the
                    # budget: the extrapolated timeout is a hard LOWER
                    # bound on the speedup
                    configs["synthetic_trees"][
                        "speedup_vs_cpu_host_at_least"] = round(
                        cpu["synth_timeout_s"] * scale / sw, 2)
                configs["synthetic_trees"]["cpu_extrapolated_from_rows"] \
                    = cpu_rows
        except Exception as e:
            _log(f"[bench] cpu denominator failed: {e!r}")

    # fusion gate state (process-wide probe; VERDICT r3 #4)
    try:
        from transmogrifai_tpu.workflow import fusion_state
        fus = fusion_state()
    except Exception:
        fus = None

    # profiled warm pass (BENCH_PROFILE=0 disables): device-busy time and
    # top-5 XLA ops from the xplane trace — the compute- vs bandwidth-
    # bound evidence for the tree sweep
    if os.environ.get("BENCH_PROFILE", "1") != "0" and backend == "tpu":
        import shutil
        trace_dir = "/tmp/jaxtrace_bench"
        shutil.rmtree(trace_dir, ignore_errors=True)
        f0 = _flops_total()
        tprof = time.time()
        with jax.profiler.trace(trace_dir):
            run_synth(n_rows=synth_rows, num_folds=3, seed=42)
        prof_s = time.time() - tprof
        prof_flops = _flops_total() - f0
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        try:
            from xplane_top_ops import device_op_times, latest_xplane
            xp = latest_xplane(trace_dir)
            # scope to the profiled window: some libtpu builds dump every
            # op since process start into the trace
            planes = (device_op_times(xp, window_ps=int(prof_s * 1e12))
                      if xp else [])
            if planes:
                p = max(planes, key=lambda p: p["busy_ps"])
                busy_s = p["busy_ps"] / 1e12
                sum_ps = p["sum_ps"]
                top5 = [{"op": op[:80], "ms": round(t / 1e9, 2),
                         "pct_incl": round(100.0 * t / sum_ps, 1)}
                        for op, t in sorted(p["ops"].items(),
                                            key=lambda kv: -kv[1])[:5]]
                dev_fps = prof_flops / busy_s if busy_s > 0 else 0.0
                configs["synthetic_trees"]["profile"] = {
                    "wall_s": round(prof_s, 2),
                    "device_busy_s": round(busy_s, 2),
                    "device_util_pct": round(100.0 * busy_s / prof_s, 1),
                    "device_mfu_bf16_pct": round(
                        100.0 * dev_fps / V5E_PEAK_BF16, 3),
                    "top_ops": top5,
                }
        except Exception as e:          # profiling is best-effort
            _log(f"[bench] profile parse failed: {e!r}")

    t_aupr = configs["titanic"]["AuPR"]
    print(json.dumps({
        "metric": "titanic_holdout_AuPR",
        "value": t_aupr,
        "unit": "AuPR",
        "vs_baseline": round(t_aupr / REFERENCE_AUPR, 4),
        "cv_wallclock_s": configs["titanic"]["cv_warm_s"],
        "cv_cold_s": configs["titanic"]["cv_cold_s"],
        "configs": configs,
        "fusion_gate": fus,
        "backend": backend,
        "n_devices": len(jax.devices()),
    }))


if __name__ == "__main__":
    main()
