"""Benchmark entry — run by the driver on real TPU hardware.

Runs the reference's headline workload: the Titanic
BinaryClassificationModelSelector CV sweep (README.md:62-64: LR + RF grids,
3 folds, AuPR selection) end-to-end — feature engineering, sanity checking,
the batched CV grid, final refit, holdout evaluation.

Prints ONE JSON line:
  metric      titanic_holdout_AuPR — parity metric against the only
              published reference number (README.md:89 AuPR = 0.8225)
  value       our holdout AuPR
  vs_baseline value / 0.8225  (>1 = better than reference)
  extras      cv_wallclock_s (the CV-grid fit wall-clock), backend
"""
from __future__ import annotations

import json
import sys
import time

REFERENCE_AUPR = 0.8225  # /root/reference/README.md:89


def main() -> None:
    import jax

    backend = jax.default_backend()
    sys.path.insert(0, "examples")
    from titanic import run

    t0 = time.time()
    out = run(num_folds=3, seed=42)
    total_s = time.time() - t0

    summary = out["summary"]
    holdout = summary.holdout_evaluation or {}
    aupr = float(holdout.get("AuPR", 0.0))

    print(json.dumps({
        "metric": "titanic_holdout_AuPR",
        "value": round(aupr, 4),
        "unit": "AuPR",
        "vs_baseline": round(aupr / REFERENCE_AUPR, 4),
        "cv_wallclock_s": round(out["train_time_s"], 2),
        "total_wallclock_s": round(total_s, 2),
        "best_model": summary.best_model_name,
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
