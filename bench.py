"""Benchmark entry — run by the driver on real TPU hardware.

Covers the five BASELINE.json configs:

1. ``titanic``   — Titanic BinaryClassificationModelSelector CV sweep
                   (reference README.md:62-89; parity AuPR 0.8225)
2. ``iris``      — Iris MultiClassificationModelSelector (string labels
                   indexed + prediction deindexed), F1 selection
3. ``boston``    — Boston housing RegressionModelSelector (RF + GBT), RMSE
4. ``big_text``  — SmartTextVectorizer-heavy BigPassenger-schema workflow
                   at 30k synthesized rows (hashing-path text + one-hot +
                   dates), LR grid
5. ``synthetic_trees`` — RF + GBT + XGB grid, 3-fold CV, 200k×20 synthetic
                   rows by default (BENCH_SYNTH_ROWS overrides; the same
                   sweep completes at 1M rows single-chip in ~137s warm
                   via host-level fold/grid chunking — the 10M BASELINE
                   target data-shards 1.25M rows/chip on a v5e-8)

Every config runs TWICE in-process: the first (cold) run pays tracing +
XLA compilation, the second (warm) run is the steady-state number that
scales to repeated AutoML workloads (compiled executables are cached
across ``validate()`` calls keyed by trace signature + shapes).

Prints ONE JSON line. Headline metric stays ``titanic_holdout_AuPR``
(the only published reference number); per-config results ride in
``configs``.
"""
from __future__ import annotations

import json
import os
import sys
import time

REFERENCE_AUPR = 0.8225  # /root/reference/README.md:89


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _run_twice(fn, name: str):
    t0 = time.time()
    out_cold = fn()
    cold_s = time.time() - t0
    _log(f"[bench] {name} cold {cold_s:.1f}s")
    t1 = time.time()
    out_warm = fn()
    warm_s = time.time() - t1
    _log(f"[bench] {name} warm {warm_s:.1f}s")
    return out_cold, out_warm, cold_s, warm_s


def main() -> None:
    import jax

    os.makedirs("/tmp/transmogrifai_jax_cache", exist_ok=True)
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/transmogrifai_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    backend = jax.default_backend()
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "examples"))
    configs = {}

    # 1. Titanic (headline parity config)
    from titanic import run as run_titanic
    cold, warm, cold_s, warm_s = _run_twice(
        lambda: run_titanic(num_folds=3, seed=42), "titanic")
    holdout = warm["summary"].holdout_evaluation or {}
    aupr = float(holdout.get("AuPR", 0.0))
    configs["titanic"] = {
        "AuPR": round(aupr, 4),
        "vs_reference": round(aupr / REFERENCE_AUPR, 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "best_model": warm["summary"].best_model_name,
    }

    # 2. Iris multiclass (string labels round-trip)
    from iris import run as run_iris
    cold, warm, cold_s, warm_s = _run_twice(
        lambda: run_iris(num_folds=3, seed=42), "iris")
    configs["iris"] = {
        "F1": round(float(warm["metrics"]["F1"]), 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "best_model": warm["summary"].best_model_name,
    }

    # 3. Boston regression
    from boston import run as run_boston
    cold, warm, cold_s, warm_s = _run_twice(
        lambda: run_boston(num_folds=3, seed=42), "boston")
    configs["boston"] = {
        "RMSE": round(float(warm["metrics"]["RootMeanSquaredError"]), 4),
        "R2": round(float(warm["metrics"]["R2"]), 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "best_model": warm["summary"].best_model_name,
    }

    # 4. SmartText-heavy (BigPassenger schema at scale)
    big_rows = int(os.environ.get("BENCH_TEXT_ROWS", 30_000))
    from big_passenger import run as run_big
    cold, warm, cold_s, warm_s = _run_twice(
        lambda: run_big(n_rows=big_rows, num_folds=3, seed=42), "big_text")
    configs["big_text"] = {
        "rows": big_rows,
        "AuPR": round(float(warm["metrics"]["AuPR"]), 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
    }

    # 5. Synthetic tree grid at scale
    synth_rows = int(os.environ.get("BENCH_SYNTH_ROWS", 200_000))
    from synthetic_trees import run as run_synth
    cold, warm, cold_s, warm_s = _run_twice(
        lambda: run_synth(n_rows=synth_rows, num_folds=3, seed=42),
        "synthetic_trees")
    configs["synthetic_trees"] = {
        "rows": synth_rows,
        "AuPR": round(float(warm["metrics"]["AuPR"]), 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "best_model": warm["summary"].best_model_name,
    }

    t_aupr = configs["titanic"]["AuPR"]
    print(json.dumps({
        "metric": "titanic_holdout_AuPR",
        "value": t_aupr,
        "unit": "AuPR",
        "vs_baseline": round(t_aupr / REFERENCE_AUPR, 4),
        "cv_wallclock_s": configs["titanic"]["cv_warm_s"],
        "cv_cold_s": configs["titanic"]["cv_cold_s"],
        "configs": configs,
        "backend": backend,
        "n_devices": len(jax.devices()),
    }))


if __name__ == "__main__":
    main()
