"""Benchmark entry — run by the driver on real TPU hardware.

Covers the five BASELINE.json configs:

1. ``titanic``   — Titanic BinaryClassificationModelSelector CV sweep
                   (reference README.md:62-89; parity AuPR 0.8225)
2. ``iris``      — Iris MultiClassificationModelSelector (string labels
                   indexed + prediction deindexed), F1 selection
3. ``boston``    — Boston housing RegressionModelSelector (RF + GBT), RMSE
4. ``big_text``  — SmartTextVectorizer-heavy BigPassenger-schema workflow
                   at 300k synthesized rows (hashing-path text + one-hot +
                   dates), LR grid
5. ``synthetic_trees`` — RF + GBT + XGB grid, 3-fold CV, 2M×20 synthetic
                   rows by default (BENCH_SYNTH_ROWS overrides), plus the
                   full 10M BASELINE config as a single budget-gated pass

**Evidence discipline (VERDICT r4 #1):** round 4's bench outgrew the
driver's wall-clock budget and died rc=124 with NO JSON line — a round of
perf work with no captured numbers. This bench therefore:

* prints the FULL cumulative JSON line after EVERY config (flushed), so
  the last parseable stdout line is always a valid, monotonically
  growing artifact even if the process is killed mid-run;
* installs SIGTERM/SIGALRM handlers that dump the current state before
  dying;
* budgets itself: ``BENCH_BUDGET_S`` (default 1050 s) is a soft
  wall-clock cap — optional stages (10M pass, CPU denominator) are
  skipped with a structured reason when the remaining budget cannot
  cover their estimated cost, never silently.

Small configs run ``BENCH_WARM_REPS`` (default 3) warm reps and report
median/min/spread (VERDICT r4 #6). The synthetic warm pass runs under
``jax.profiler.trace`` so the device-busy MFU and top-ops evidence come
from the SAME pass that produces the warm number (no third sweep).

Headline metric stays ``titanic_holdout_AuPR`` (the only published
reference number); per-config results ride in ``configs``.
"""
from __future__ import annotations

import json
import os
import signal
import statistics
import sys
import time

REFERENCE_AUPR = 0.8225  # /root/reference/README.md:89

#: TPU v5e per-chip peaks (public spec: 197 bf16 TFLOP/s; f32 runs
#: through the same MXU at ~1/4 rate — stated assumption, see
#: docs/performance.md "MFU" for the caveats)
V5E_PEAK_BF16 = 197e12
V5E_PEAK_F32 = 49e12


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _flops_total() -> float:
    from transmogrifai_tpu.models.tuning import DEVICE_FLOPS
    return DEVICE_FLOPS["total"]


def _compile_s() -> float:
    try:
        from transmogrifai_tpu.workflow import _COMPILE_CLOCK
        return float(_COMPILE_CLOCK["s"])
    except Exception:
        return 0.0


def _mfu_fields(warm_flops: float, train_s: float) -> dict:
    """Achieved FLOP/s over the warm TRAIN wall-clock vs v5e-1 peak.

    Wall-clock (not device-busy) is the honest denominator for an AutoML
    sweep: host feature prep and dispatch gaps count against utilization.
    The executed-FLOP numerator comes from XLA cost analysis of every
    dispatched CV executable (models/tuning.DEVICE_FLOPS) plus the
    analytic Pallas-histogram estimate (documented as erring low); the
    profile block's device-busy MFU cross-checks it (VERDICT r4 weak #5).
    """
    if train_s <= 0:
        return {}
    fps = warm_flops / train_s
    return {"device_tflop": round(warm_flops / 1e12, 4),
            "achieved_tflops": round(fps / 1e12, 4),
            "mfu_bf16_pct": round(100.0 * fps / V5E_PEAK_BF16, 3),
            "mfu_f32_pct": round(100.0 * fps / V5E_PEAK_F32, 3)}


def _std_config(warm, cold, st) -> dict:
    """Shared per-config fields (the three small configs differ only in
    their metric keys)."""
    return {
        "cv_warm_s": st.get("train_s_median",
                            round(warm["train_time_s"], 2)),
        "cv_warm_s_reps": st.get("train_s_reps", st["warm_s_all"]),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "compile_clock_s": st["compile_clock_s"],
        "best_model": warm["summary"].best_model_name,
        **_mfu_fields(st["warm_flops"], warm["train_time_s"]),
    }


class Bench:
    """Cumulative result document with incremental emission + budget."""

    def __init__(self) -> None:
        self.t0 = time.time()
        self.budget_s = float(os.environ.get("BENCH_BUDGET_S", 1050))
        self.doc = {"metric": "titanic_holdout_AuPR", "value": None,
                    "unit": "AuPR", "vs_baseline": None, "configs": {},
                    "partial": True}
        signal.signal(signal.SIGTERM, self._die)
        try:
            signal.signal(signal.SIGALRM, self._die)
        except (AttributeError, ValueError):
            pass

    def _die(self, signum, _frame) -> None:
        self.doc["killed_by_signal"] = int(signum)
        # enrich=False: no imports / jax calls inside a signal handler
        # (import-lock deadlock, non-reentrant runtime) — the cumulative
        # doc already carries the last emit's gate/clock/cache fields
        self.emit(enrich=False)
        os._exit(1)

    def elapsed(self) -> float:
        return time.time() - self.t0

    def remaining(self) -> float:
        return self.budget_s - self.elapsed()

    def emit(self, final: bool = False, enrich: bool = True) -> None:
        self.doc["elapsed_s"] = round(self.elapsed(), 1)
        # every emitted doc carries the fusion gate state, the cumulative
        # compile clock and the scoring-engine cache tallies (VERDICT r3
        # asked every benched number to say whether fusion was on; the
        # compile/cache counters explain cold-vs-warm deltas in place).
        # enrich=False is the signal-handler path: dump as-is.
        if enrich:
            try:
                from transmogrifai_tpu.workflow import fusion_state
                self.doc["fusion_gate"] = fusion_state()
            except Exception:
                self.doc.setdefault("fusion_gate", None)
            self.doc["compile_clock_s"] = round(_compile_s(), 2)
            try:
                from transmogrifai_tpu.scoring import engine_cache_stats
                self.doc["scoring_cache"] = engine_cache_stats()
            except Exception:
                self.doc.setdefault("scoring_cache", None)
            # fused fit-statistics tallies (layers fused, passes saved,
            # bytes scanned) ride on EVERY doc, like the scoring cache
            try:
                from transmogrifai_tpu import fitstats
                self.doc["fitstats"] = fitstats.fitstats_stats()
            except Exception:
                self.doc.setdefault("fitstats", None)
            # whole-DAG planner tallies (plans built, CSE merges, dead
            # columns, per-tier stage counts) ride on EVERY doc too
            try:
                from transmogrifai_tpu import planner
                self.doc["planner"] = planner.planner_stats()
            except Exception:
                self.doc.setdefault("planner", None)
            # AOT program-bank + model-server tallies (banks exported /
            # loaded, requests, coalescing factor, SLO attainment) ride
            # on EVERY doc too — the serving tier's evidence
            try:
                from transmogrifai_tpu import aot
                self.doc["aot"] = aot.aot_stats()
            except Exception:
                self.doc.setdefault("aot", None)
            try:
                from transmogrifai_tpu import server
                self.doc["server"] = server.server_stats()
            except Exception:
                self.doc.setdefault("server", None)
            # model-lifecycle tallies (registry traffic, rollout
            # promotions/rollbacks, drift windows + advisories) ride on
            # EVERY doc too — the deployment loop's evidence
            # (lifecycle.py, docs/lifecycle.md)
            try:
                from transmogrifai_tpu import lifecycle
                self.doc["lifecycle"] = lifecycle.lifecycle_stats()
            except Exception:
                self.doc.setdefault("lifecycle", None)
            # continuous-training tallies (drift windows, retrain
            # triggers vs storm suppression, job outcomes, warm-start
            # vs full-refit split) ride on EVERY doc too — the
            # self-healing loop's evidence (continual.py)
            try:
                from transmogrifai_tpu import continual
                self.doc["continual"] = continual.continual_stats()
            except Exception:
                self.doc.setdefault("continual", None)
            # serving-fleet tallies (workers spawned/respawned, routed
            # requests, failovers, load shed) ride on EVERY doc too —
            # the horizontal tier's evidence (fleet.py, docs/fleet.md)
            try:
                from transmogrifai_tpu import fleet
                self.doc["fleet"] = fleet.fleet_stats()
            except Exception:
                self.doc.setdefault("fleet", None)
            # input-pipeline tallies (converged prefetch depth, worker
            # count, buffer reuse, sustained bandwidth) ride on EVERY
            # doc too — the ingest tier's evidence (pipeline.py)
            try:
                from transmogrifai_tpu import pipeline
                self.doc["pipeline"] = pipeline.pipeline_stats()
            except Exception:
                self.doc.setdefault("pipeline", None)
            # temporal-tier tallies (columnar vs row-wise aggregation
            # split, join traffic, bounded-table spills) ride on EVERY
            # doc too — the event-log workload's evidence (temporal.py)
            try:
                from transmogrifai_tpu import temporal
                self.doc["temporal"] = temporal.temporal_stats()
            except Exception:
                self.doc.setdefault("temporal", None)
            # tree-engine kernel tallies (per-kernel trace counts,
            # mesh-sharded histogram builds, gate state) ride on EVERY
            # doc too — the tree-training tier's evidence
            # (models/_pallas_hist.py, docs/performance.md)
            try:
                from transmogrifai_tpu.models import _pallas_hist
                self.doc["trees"] = _pallas_hist.tree_kernel_stats()
            except Exception:
                self.doc.setdefault("trees", None)
            # telemetry-plane tallies (recording state, event/metric
            # counts, traces minted/adopted, trace shards written) and
            # the executed-FLOP device-cost block (per-phase flops/
            # seconds, achieved TFLOP/s, MFU vs platform peak) ride on
            # EVERY doc too — the observability tier's own evidence
            # (telemetry.py, docs/observability.md)
            try:
                from transmogrifai_tpu import telemetry
                self.doc["telemetry"] = telemetry.telemetry_stats()
                self.doc["mfu"] = telemetry.device_cost_stats()
            except Exception:
                self.doc.setdefault("telemetry", None)
                self.doc.setdefault("mfu", None)
            # workload flight-recorder tallies (records enqueued/written/
            # dropped, payload policy, rotations, merge/replay/parity
            # counters) ride on EVERY doc too — the capture-and-replay
            # tier's evidence (workload.py, docs/observability.md)
            try:
                from transmogrifai_tpu import workload
                self.doc["workload"] = workload.workload_stats()
            except Exception:
                self.doc.setdefault("workload", None)
            # offline-autotuner tallies (searches, replay legs, parity
            # rejections, incumbent improvements) ride on EVERY doc —
            # the self-tuning tier's evidence (tuner.py, docs/tuning.md)
            try:
                from transmogrifai_tpu import tuner
                self.doc["tuner"] = tuner.tuner_stats()
            except Exception:
                self.doc.setdefault("tuner", None)
            # peak RSS (self + reaped children) rides on EVERY doc —
            # the out-of-core tier's memory evidence: streamed fits must
            # show a bounded high-water mark where materialized fits
            # grow with the dataset (docs/performance.md)
            try:
                from transmogrifai_tpu import telemetry
                self.doc["peak_rss_mb"] = telemetry.peak_rss_mb()
            except Exception:
                self.doc.setdefault("peak_rss_mb", None)
        if final:
            self.doc.pop("partial", None)
        print(json.dumps(self.doc), flush=True)

    def run_config(self, name: str, fn, reps: int = 1):
        """cold + ``reps`` warm runs; returns (last_warm_out, stats dict).

        The cumulative doc is emitted after the config completes; the
        per-config dict carries compile clock and warm-rep statistics."""
        c0 = _compile_s()
        t0 = time.time()
        out_cold = fn()
        cold_s = time.time() - t0
        compile_s = _compile_s() - c0
        _log(f"[bench] {name} cold {cold_s:.1f}s "
             f"(compile clock {compile_s:.1f}s)")
        warm_outs, warm_secs = [], []
        f0 = _flops_total()
        for i in range(max(reps, 1)):
            t1 = time.time()
            warm_outs.append(fn())
            warm_secs.append(time.time() - t1)
        warm_flops = (_flops_total() - f0) / max(reps, 1)
        med = statistics.median(warm_secs)
        _log(f"[bench] {name} warm {med:.1f}s median of {warm_secs} "
             f"({warm_flops / 1e9:.1f} GFLOP dispatched/rep)")
        stats = {"cold_s": round(cold_s, 2),
                 "compile_clock_s": round(compile_s, 2),
                 "warm_s_median": round(med, 2),
                 "warm_s_min": round(min(warm_secs), 2),
                 "warm_s_all": [round(s, 2) for s in warm_secs],
                 "warm_flops": warm_flops}
        trains = [o.get("train_time_s") for o in warm_outs
                  if isinstance(o, dict) and o.get("train_time_s")]
        if trains:
            # the MEDIAN train clock is the reported cv_warm_s — the last
            # rep alone would hand the headline to a one-off stall
            stats["train_s_median"] = round(statistics.median(trains), 2)
            stats["train_s_reps"] = [round(t, 2) for t in trains]
        return out_cold, warm_outs[-1], stats


def _apply_cpu_denominator(cpu: dict, configs: dict,
                           synth_rows: int) -> None:
    """Fold a (possibly partial) bench_cpu result into the per-config
    speedups — shared by the clean-exit and timeout-salvage paths so a
    killed child's completed stages still produce their numbers."""
    tw = configs["titanic"]["cv_warm_s"]
    if tw > 0 and cpu.get("titanic_warm_s"):
        configs["titanic"]["speedup_vs_cpu_host"] = round(
            cpu["titanic_warm_s"] / tw, 2)
    elif tw > 0 and cpu.get("titanic_timeout_s"):
        # the CPU host could not finish cold+warm inside its stage
        # alarm: the alarm is a hard LOWER bound on the CPU cost
        # (includes the CPU compile, stated in the note)
        configs["titanic"]["speedup_vs_cpu_host_at_least"] = round(
            cpu["titanic_timeout_s"] / tw, 2)
        configs["titanic"]["cpu_bound_note"] = (
            "CPU host (1 core) did not finish cold+warm within "
            f"{cpu['titanic_timeout_s']}s")
    sw = configs["synthetic_trees"]["cv_warm_s"]
    cpu_rows = cpu.get("synth_rows")
    if sw > 0 and cpu_rows:
        scale = synth_rows / cpu_rows
        if cpu.get("synth_s_incl_compile"):
            # linear extrapolation from the measured small-row CPU run
            # — a conservative FLOOR (CPU throughput degrades with
            # working-set size)
            configs["synthetic_trees"]["speedup_vs_cpu_host_est"] = \
                round(cpu["synth_s_incl_compile"] * scale / sw, 2)
        elif cpu.get("synth_timeout_s"):
            # CPU did not finish even the reduced config: the
            # extrapolated timeout is a hard LOWER bound
            configs["synthetic_trees"]["speedup_vs_cpu_host_at_least"] \
                = round(cpu["synth_timeout_s"] * scale / sw, 2)
        configs["synthetic_trees"]["cpu_extrapolated_from_rows"] = \
            cpu_rows


def _scoring_throughput() -> dict:
    """Serving-path benchmark: one fitted LR workflow scored three ways —
    the per-layer reference path (one host↔device crossing per DAG
    layer), the compiled batched engine (ONE fused program per bucket),
    and the engine's overlapped streaming mode (host prep of micro-batch
    k+1 concurrent with batch k's device compute). Reports rows/s; every
    number states whether the engine's bandwidth gate was open."""
    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values)
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.readers import stream_score
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import fusion_state

    rows = int(os.environ.get("BENCH_SCORE_ROWS", 200_000))
    train_rows = min(20_000, rows)
    rng = np.random.default_rng(11)
    y = rng.integers(0, 2, rows).astype(float)
    xs = {f"x{j}": rng.normal(size=rows) + (0.3 * j) * y for j in range(6)}
    cats = np.array(["a", "b", "c", "d", None], dtype=object)[
        rng.integers(0, 5, rows)]

    def store_of(sl):
        cols = {"label": column_from_values(ft.RealNN, y[sl])}
        for k, v in xs.items():
            cols[k] = column_from_values(ft.Real, list(v[sl]))
        cols["cat"] = column_from_values(ft.PickList, list(cats[sl]))
        return ColumnStore(cols, len(y[sl]))

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
             for j in range(6)]
    feats.append(FeatureBuilder.PickList("cat").from_column().as_predictor())
    vec = transmogrify(feats)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=5)
    pred = label.transform_with(selector, vec)
    model = (Workflow().set_input_store(store_of(slice(0, train_rows)))
             .set_result_features(pred).train())
    full = store_of(slice(0, rows))

    out: dict = {"rows": rows, "fusion_gate": fusion_state()}

    def _rate(fn, reps=2):
        fn()                                   # warm-up (compile) pass
        secs = []
        for _ in range(reps):
            t0 = time.time()
            fn()
            secs.append(time.time() - t0)
        return rows / statistics.median(secs), statistics.median(secs)

    rate, s = _rate(lambda: model.score(full, engine=False))
    out["per_layer_rows_per_s"] = round(rate)
    out["per_layer_s"] = round(s, 3)

    eng = model.scoring_engine()
    if eng is not None and eng.enabled():
        # use_cache=False: fresh host_prepare every rep — the honest
        # apples-to-apples number against the per-layer path above
        rate, s = _rate(lambda: eng.score_store(full, use_cache=False))
        out["engine_rows_per_s"] = round(rate)
        out["engine_s"] = round(s, 3)
        out["engine_speedup"] = round(
            out["engine_rows_per_s"] / out["per_layer_rows_per_s"], 2)
        # repeat-call rate: host_prepare amortized across calls on the
        # same store (score → evaluate pattern) — device path only
        rate, s = _rate(lambda: eng.score_store(full))
        out["engine_repeat_rows_per_s"] = round(rate)
        out["engine_compiles"] = eng.compile_count
        out["bucket_cap"] = eng.bucket_cap

        # streaming: record batches through the same reader contract the
        # StreamingScore run type uses; the host record→column conversion
        # is part of the measured (and overlapped) host work
        records = [
            {"label": float(y[i]), "cat": cats[i],
             **{f"x{j}": float(xs[f"x{j}"][i]) for j in range(6)}}
            for i in range(rows)]
        bs = 8192
        batches = [records[i:i + bs] for i in range(0, rows, bs)]

        def drain(overlap):
            def go():
                for _ in stream_score(model, batches, overlap=overlap):
                    pass
            return go
        rate, s = _rate(drain(False), reps=1)
        out["stream_rows_per_s"] = round(rate)
        rate, s = _rate(drain(True), reps=1)
        out["stream_overlap_rows_per_s"] = round(rate)
        out["stream_overlap_speedup"] = round(
            out["stream_overlap_rows_per_s"] / out["stream_rows_per_s"], 2)
        out["stream_batch_size"] = bs
    else:
        out["engine"] = ("gated_off: link below FUSE_MIN_BANDWIDTH_MBPS"
                         if eng is not None else "unavailable")
    return out


def _input_pipeline() -> dict:
    """Staged input-pipeline benchmark (the tf.data-analog proof): one
    fitted LR workflow scores a directory of Avro micro-batch files —
    the StreamingScore regime where ingest (decode + host prep), not
    compute, was the measured bottleneck — serial vs pipelined:

    * **serial** — the PRE-PIPELINE ingest path: single-thread
      per-record Python decode (``columnar=False``), plain per-batch
      scoring (``workers=1``, ``overlap=False``);
    * **pipelined at 1/2/4 workers** — the staged pipeline: vectorized
      columnar decode (``avro.read_avro_table`` — numpy, GIL-releasing)
      on parallel decode workers
      (``DirectoryStreamReader.stream(workers=N)``) feeding the staged
      engine path (parallel host prep, autotuned prefetch,
      double-buffered uploads).

    Reports rows/s per leg, the overlap_efficiency gauge of the widest
    pipelined leg, the converged prefetch depth + buffer-reuse tallies,
    and a pass flag = fusion gate ON (via sustained_mbps) AND best
    pipelined ingest ≥ 2× serial. Scores are asserted bit-identical
    between the serial and pipelined legs — the pipeline buys
    throughput, never answers."""
    import shutil
    import tempfile

    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values, pipeline, telemetry)
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.readers import DirectoryStreamReader, stream_score
    from transmogrifai_tpu.readers.avro import write_avro_records
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import fusion_state

    n_files = int(os.environ.get("BENCH_PIPELINE_FILES", 24))
    # deliberately NOT a power of two: every batch pads to its bucket,
    # so the pinned-buffer pool's reuse shows in the tallies
    rows_per_file = int(os.environ.get("BENCH_PIPELINE_FILE_ROWS", 7600))
    rows = n_files * rows_per_file
    train_rows = 20_000
    rng = np.random.default_rng(31)
    y = rng.integers(0, 2, rows).astype(float)
    xs = {f"x{j}": rng.normal(size=rows) + (0.3 * j) * y for j in range(6)}

    cols = {"label": column_from_values(ft.RealNN, y[:train_rows])}
    for k, v in xs.items():
        cols[k] = column_from_values(ft.Real, list(v[:train_rows]))
    store = ColumnStore(cols, train_rows)
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
             for j in range(6)]
    vec = transmogrify(feats)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=5)
    pred = label.transform_with(selector, vec)
    model = (Workflow().set_input_store(store)
             .set_result_features(pred).train())

    out: dict = {"rows": rows, "files": n_files,
                 "rows_per_file": rows_per_file,
                 "fusion_gate": fusion_state()}
    eng = model.scoring_engine()
    if eng is None or not eng.enabled():
        out["status"] = ("engine_gated_off: sustained link below "
                         "FUSE_MIN_BANDWIDTH_MBPS")
        return out

    work = tempfile.mkdtemp(prefix="tmog_pipeline_bench_")
    try:
        for i in range(n_files):
            lo = i * rows_per_file
            recs = [{"label": float(y[lo + r]),
                     **{f"x{j}": float(xs[f"x{j}"][lo + r])
                        for j in range(6)}}
                    for r in range(rows_per_file)]
            write_avro_records(os.path.join(work, f"b{i:04d}.avro"), recs)

        def ingest(workers, overlap, columnar=True):
            """Decode the directory + score every batch; returns
            (seconds, per-batch probabilities — EVERY batch, so the
            parity flag catches a reorder/stale-buffer regression in
            batch 2..N, not just the first)."""
            reader = DirectoryStreamReader(work, pattern="*.avro",
                                           settle_s=0.0,
                                           columnar=columnar)
            t0 = time.time()
            probs = []
            for s in stream_score(
                    model,
                    reader.stream(max_batches=n_files, timeout_s=60.0,
                                  workers=workers),
                    overlap=overlap, workers=workers):
                probs.append(s[pred.name].probability.copy())
            return time.time() - t0, probs

        ingest(4, True)                      # warm-up: compile the ladder
        serial_s, p_serial = ingest(1, False, columnar=False)
        out["serial_rows_per_s"] = round(rows / serial_s)
        out["serial_s"] = round(serial_s, 3)
        best = 0.0
        for w in (1, 2, 4):
            before = pipeline.pipeline_stats()
            tel_on = not telemetry.enabled()
            if tel_on:
                telemetry.enable()
            try:
                sec, p_pipe = ingest(w, True)
            finally:
                eff = telemetry.gauge("stream.overlap_efficiency").value
                if tel_on:
                    telemetry.disable()
            after = pipeline.pipeline_stats()
            leg = {"rows_per_s": round(rows / sec), "s": round(sec, 3),
                   "overlap_efficiency": round(float(eff), 3),
                   "prefetch_depth": after["last_prefetch_depth"],
                   "starvations": (after["starvations"]
                                   - before["starvations"]),
                   "buffer_reuses": (after["buffer_reuses"]
                                     - before["buffer_reuses"]),
                   "parity": bool(
                       len(p_serial) == len(p_pipe)
                       and all(np.array_equal(a, b)
                               for a, b in zip(p_serial, p_pipe)))}
            out[f"pipelined_{w}w"] = leg
            best = max(best, leg["rows_per_s"])
        out["best_pipelined_rows_per_s"] = round(best)
        out["ingest_speedup"] = round(best / out["serial_rows_per_s"], 2)
        out["pass"] = bool(
            out["fusion_gate"]["fusion"] == "ON"
            and out["ingest_speedup"] >= 2.0
            and all(out[f"pipelined_{w}w"]["parity"] for w in (1, 2, 4)))
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return out


def _event_log() -> dict:
    """Temporal join+aggregate benchmark (the event-log workload family
    the reader tier opens — clickstream / transactions / activity-window
    churn): a seeded two-stream event log (transactions keyed by user ×
    a small users dimension table) is joined and point-in-time
    aggregated against a cutoff — per-user spend sum, windowed mean,
    max, joined segment, and a strictly-after-cutoff response — three
    ways:

    * **serial row-wise** — the pre-temporal path: per-record Python
      Avro decode, dict hash join, per-record monoid folds
      (``aggregateColumnar: false``);
    * **columnar** — vectorized decode (``read_avro_table``), vectorized
      join probe + stable-argsort group/fold on one thread;
    * **columnar + workers** — decode → join → partial-aggregate inside
      the ordered worker pool (``temporal.join_aggregate_directory``),
      monoid partials merged in file order.

    Headline is join+aggregate events/s per leg. Pass flag =
    columnar+workers ≥ 5× serial AND all three stores bit-identical
    (the engine buys throughput, never answers)."""
    import shutil
    import tempfile

    import numpy as np

    from transmogrifai_tpu import FeatureBuilder, temporal
    from transmogrifai_tpu.readers import (CutOffTime, DataReaders,
                                           JoinedAggregateDataReader)
    from transmogrifai_tpu.readers.avro import (read_avro_records,
                                                read_avro_table,
                                                write_avro_records)
    from transmogrifai_tpu.utils.aggregators import (LogicalOrAggregator,
                                                     MaxAggregator,
                                                     MeanAggregator,
                                                     SumAggregator)

    n_files = int(os.environ.get("BENCH_EVENT_FILES", 16))
    rows_per_file = int(os.environ.get("BENCH_EVENT_FILE_ROWS", 10_000))
    n_users = int(os.environ.get("BENCH_EVENT_USERS", 5_000))
    rows = n_files * rows_per_file
    cutoff = 800.0
    rng = np.random.default_rng(47)

    key = temporal.field("user")
    ts = temporal.field("ts")
    feats = [
        FeatureBuilder.Real("spend").extract(temporal.field("amount"),
                                             "amount")
        .aggregate(SumAggregator()).as_predictor(),
        FeatureBuilder.Real("spend_recent")
        .extract(temporal.field("amount"), "amount")
        .aggregate(MeanAggregator()).window(200).as_predictor(),
        FeatureBuilder.Real("peak").extract(temporal.field("amount"),
                                            "amount")
        .aggregate(MaxAggregator()).as_predictor(),
        FeatureBuilder.Real("segment").extract(temporal.field("seg"),
                                               "seg")
        .aggregate(MaxAggregator()).as_predictor(),
        FeatureBuilder.Binary("churned").extract(temporal.field("flag"),
                                                 "flag")
        .aggregate(LogicalOrAggregator()).as_response(),
    ]
    users = [{"user": float(u), "seg": float(u % 7)}
             for u in range(n_users)]
    out: dict = {"rows": rows, "files": n_files, "users": n_users,
                 "rows_per_file": rows_per_file, "cutoff": cutoff}

    work = tempfile.mkdtemp(prefix="tmog_event_log_")
    try:
        for i in range(n_files):
            uid = rng.integers(0, n_users, rows_per_file).astype(float)
            recs = [{"user": float(uid[r]),
                     "ts": float(rng.uniform(0, 1000.0)),
                     "amount": float(rng.gamma(2.0, 10.0)),
                     "flag": bool(rng.random() < 0.05)}
                    for r in range(rows_per_file)]
            write_avro_records(os.path.join(work, f"e{i:04d}.avro"), recs)
        files = sorted(os.path.join(work, f) for f in os.listdir(work))

        class _Src:
            """In-memory reader handing the join its decoded source."""

            def __init__(self, data):
                self._data = data
                self.key_fn = key

            def read_records(self):
                return self._data

        def serial_leg():
            prev = temporal.set_run_defaults(columnar=False)
            try:
                t0 = time.time()
                recs = []
                for fp in files:
                    recs.extend(read_avro_records(fp))
                reader = JoinedAggregateDataReader(
                    _Src(recs), DataReaders.simple.records(
                        users, key_fn=key),
                    ts, CutOffTime.at(cutoff))
                store = reader.generate_store(feats)
                return time.time() - t0, store
            finally:
                temporal.set_run_defaults(**prev)

        def columnar_leg():
            t0 = time.time()
            tab = temporal.concat_tables(
                [read_avro_table(fp) for fp in files])
            reader = JoinedAggregateDataReader(
                _Src(tab), _Src(temporal.table_from_records(users)),
                ts, CutOffTime.at(cutoff))
            store = reader.generate_store(feats)
            return time.time() - t0, store

        def workers_leg(w):
            t0 = time.time()
            store = temporal.join_aggregate_directory(
                work, feats, temporal.table_from_records(users), ts, key,
                cutoff_ms=cutoff, workers=w)
            return time.time() - t0, store

        def parity(a, b):
            if a.n_rows != b.n_rows:
                return False
            for f in feats:
                ca, cb = a[f.name], b[f.name]
                if not (np.array_equal(ca.values, cb.values,
                                       equal_nan=True)
                        and np.array_equal(ca.mask, cb.mask)):
                    return False
            return True

        serial_s, s_serial = serial_leg()
        out["serial_rowwise"] = {"s": round(serial_s, 3),
                                 "rows_per_s": round(rows / serial_s)}
        col_s, s_col = columnar_leg()
        out["columnar"] = {"s": round(col_s, 3),
                           "rows_per_s": round(rows / col_s),
                           "parity": parity(s_serial, s_col)}
        best = 0.0
        for w in (2, 4):
            sec, s_w = workers_leg(w)
            leg = {"s": round(sec, 3), "rows_per_s": round(rows / sec),
                   "parity": parity(s_serial, s_w)}
            out[f"columnar_{w}w"] = leg
            best = max(best, leg["rows_per_s"])
        out["best_columnar_workers_rows_per_s"] = round(best)
        out["speedup_columnar"] = round(
            out["columnar"]["rows_per_s"]
            / out["serial_rowwise"]["rows_per_s"], 2)
        out["speedup_columnar_workers"] = round(
            best / out["serial_rowwise"]["rows_per_s"], 2)
        out["keys"] = s_serial.n_rows
        out["pass"] = bool(
            out["speedup_columnar_workers"] >= 5.0
            and out["columnar"]["parity"]
            and all(out[f"columnar_{w}w"]["parity"] for w in (2, 4)))
        out["temporal"] = temporal.temporal_stats()
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return out


def _wide_sparse() -> dict:
    """Wide-sparse tree workload (the PR 14 matrix-shape proof): a
    high-cardinality OneHot/text-hash-shaped feature matrix — hundreds
    of mostly-zero indicator columns beside a few dense reals
    (TransmogrifAI's 45 feature types, PAPER.md §L2) — trained with the
    sparsity-aware binning path (2-bin indicator blocks; on the kernel
    path additionally the sparse01 kernel, which streams the 0/1 bin
    matrix itself instead of a 2×-wider dense indicator) against the
    naive full-width quantile binning. Headline: rows/s of the
    sparse-aware leg; pass = ≥ 2× the dense-binning leg at matched model
    quality (holdout AuPR within 0.02 — DIFFERENT binning grows
    different trees, so quality parity is the honest flag, unlike the
    bit-parity the kernel-vs-XLA tests assert at fixed binning)."""
    import jax.numpy as jnp
    import numpy as np

    from transmogrifai_tpu.evaluators import metrics as M
    from transmogrifai_tpu.models import _pallas_hist
    from transmogrifai_tpu.models._treefit import tree_mesh_scope
    from transmogrifai_tpu.models.trees import RandomForestFamily
    from transmogrifai_tpu.parallel.mesh import process_default_mesh

    rows = int(os.environ.get("BENCH_WS_ROWS", 20_000))
    Fs, Fd = 512, 4
    rng = np.random.default_rng(14)
    dense = rng.normal(size=(rows, Fd)).astype(np.float32)
    # each row activates ~8 of 512 indicator columns (≈1.6% density —
    # the one-hot/text-hash shape)
    sparse = (rng.random((rows, Fs)) < 8.0 / Fs).astype(np.float32)
    beta = rng.normal(size=16).astype(np.float32)
    logits = dense[:, 0] + 1.5 * (sparse[:, :16] @ beta)
    y = (logits + rng.normal(size=rows).astype(np.float32) * 0.5 > 0
         ).astype(np.float32)
    X = np.concatenate([dense, sparse], axis=1)
    bmask = np.array([False] * Fd + [True] * Fs)
    n_tr = int(rows * 0.8)
    Xd = jnp.asarray(X[:n_tr])
    yd = jnp.asarray(y[:n_tr])
    wd = jnp.ones((n_tr,), jnp.float32)
    X_ho = jnp.asarray(X[n_tr:])
    y_ho = y[n_tr:]
    out: dict = {"rows": rows, "features": Fd + Fs,
                 "indicator_columns": Fs,
                 "density_pct": round(100.0 * float(sparse.mean()), 2)}

    captured: dict = {}

    def leg(mask, shards=1, key=None):
        import jax as _jax

        from transmogrifai_tpu.models._treefit import feature_shards_scope
        from transmogrifai_tpu.parallel.mesh import feature_shard_mesh
        fam = RandomForestFamily(
            grid=[{"maxDepth": 6, "minInstancesPerNode": 2,
                   "minInfoGain": 0.0}], num_trees=8, seed=14)
        fam.binary_mask = mask
        tk0 = _pallas_hist.tree_kernel_stats()
        # ONE jitted program reused across reps (fit_prepared builds a
        # fresh jit per call, which would re-trace+re-compile — the
        # "warm" number would then mostly measure compiler speed, not
        # training throughput; the review caught BENCH_r07's first cut
        # with warm_s ≈ 91% of cold_s for exactly that reason)
        grid = fam.stack_grid()
        mesh = (feature_shard_mesh(shards) if shards > 1
                else process_default_mesh())

        def run(trace_fresh):
            from transmogrifai_tpu.models.trees import (_tree_rows,
                                                        pad_rows_to)
            with tree_mesh_scope(mesh), feature_shards_scope(shards):
                def go():
                    Xarg = fam.device_prep(Xd)
                    yp, wp = pad_rows_to(_tree_rows(Xarg), yd, wd)
                    if trace_fresh[0] is None:
                        trace_fresh[0] = _jax.jit(
                            lambda X, y, w: fam.fit_batch(X, y, w, grid))
                    return trace_fresh[0](Xarg, yp, wp)
                return _jax.device_get(
                    _pallas_hist.with_pallas_fallback(go))
        fit = [None]
        t0 = time.time()
        params = run(fit)
        cold_s = time.time() - t0
        warm = []
        for _ in range(3):
            t1 = time.time()
            params = run(fit)
            warm.append(time.time() - t1)
        warm_s = statistics.median(warm)
        pred, _raw, prob = fam.predict_batch(
            {k: jnp.asarray(v) for k, v in params.items()
             if k not in ("train_node", "train_margin")}, X_ho)
        m = M.binary_metrics(y_ho, np.asarray(pred)[0],
                             np.asarray(prob)[0][:, 1])
        tk1 = _pallas_hist.tree_kernel_stats()
        if key is not None:
            captured[key] = params
        return {"cold_s": round(cold_s, 2), "warm_s": round(warm_s, 3),
                "rows_per_s": round(n_tr / warm_s),
                "holdout_AuPR": round(float(m["AuPR"]), 4),
                "kernel_traces": {
                    k: tk1[k] - tk0[k]
                    for k in ("cumhist_traces", "sparse01_traces",
                              "split_scan_traces",
                              "sharded_hist_traces",
                              "feature_shard_traces")}}

    out["dense_binning"] = leg(None)
    out["sparse_binning"] = leg(bmask, key="sparse")
    out["speedup_vs_dense"] = round(
        out["dense_binning"]["warm_s"]
        / max(out["sparse_binning"]["warm_s"], 1e-9), 2)
    out["quality_parity"] = bool(
        out["sparse_binning"]["holdout_AuPR"]
        >= out["dense_binning"]["holdout_AuPR"] - 0.02)
    out["pass"] = bool(out["speedup_vs_dense"] >= 2.0
                       and out["quality_parity"])

    # Feature-axis-sharded leg (PR 16, the beyond-VMEM proof): the same
    # sparse workload with columns sharded over the mesh grid axis.
    # Split winners must be BIT-identical to the single-shard pass (the
    # merge rule is the kernel's own (score desc, idx asc) — same
    # ordering, partitioned domain), and the leg must actually have
    # engaged the sharded kernel (feature_shard_traces > 0), or a
    # silent fail-open gate would read as a passing parity check.
    # scaling_efficiency here is rate_sharded / rate_unsharded over the
    # SAME device pool (the grid axis re-slices it, data 8→4 × grid 2):
    # ideal is 1.0 — the reshape buys VMEM headroom, not throughput.
    import jax as _jax
    G = 2
    ndev = len(_jax.devices())
    if ndev < 2 or ndev % G:
        out["feature_sharded"] = {"status": "skipped_devices",
                                  "devices": ndev, "grid": G}
    else:
        fs = leg(bmask, shards=G, key="sharded")
        base, shard = captured["sparse"], captured["sharded"]
        fs["grid"] = G

        def _eq(a, b):
            a, b = np.asarray(a), np.asarray(b)
            if a.dtype.kind == "f" and b.dtype.kind == "f":
                # NaN marks an un-split node slot: bit-parity must
                # treat identical NaN patterns as equal
                return bool(np.array_equal(a, b, equal_nan=True))
            return bool(np.array_equal(a, b))

        # winners (split feature, threshold, leaves, training routing)
        # are BIT-identical — integer-valued histogram stats make the
        # merged argmax exact. The recorded per-node ``gain``
        # DIAGNOSTIC is recomputed under a different fused program
        # shape (shard-width blocks), so it carries float-fusion noise
        # at the 1e-9 scale; it gets an allclose gate of its own, not
        # a silent exemption
        fs["winner_parity"] = bool(
            set(base) == set(shard)
            and all(_eq(base[k], shard[k]) for k in base
                    if k != "gain"))
        ga = np.asarray(base.get("gain", 0.0))
        gs = np.asarray(shard.get("gain", 0.0))
        fs["gain_parity"] = bool(np.allclose(ga, gs, rtol=1e-4,
                                             atol=1e-7, equal_nan=True))
        fs["gain_max_abs_diff"] = float(np.nanmax(np.abs(
            np.nan_to_num(ga) - np.nan_to_num(gs)))) if ga.size else 0.0
        fs["engaged"] = fs["kernel_traces"]["feature_shard_traces"] > 0
        fs["scaling_efficiency"] = round(
            fs["rows_per_s"]
            / max(out["sparse_binning"]["rows_per_s"], 1), 3)
        out["feature_sharded"] = fs
        out["pass"] = bool(out["pass"] and fs["winner_parity"]
                           and fs["gain_parity"] and fs["engaged"])
    out["trees"] = _pallas_hist.tree_kernel_stats()
    return out


def _out_of_core() -> dict:
    """Out-of-core streaming fit (the PR 16 beyond-RAM proof): a
    synthetic avro event log deliberately larger than the declared
    host-memory budget trains end-to-end in a subprocess under a HARD
    heap cap — ``resource.setrlimit(RLIMIT_DATA)``, armed after backend
    init, enforced by the kernel: an ingest that secretly materialized
    would die with MemoryError — vs the materialized fit on the same
    directory, uncapped (its peak RSS is the evidence the log exceeds
    the budget; its holdout metric the parity reference). One fresh
    interpreter per leg (``ru_maxrss`` never resets). pass = the capped
    streamed leg survives with measured ``peak_rss_mb`` < ``rssCapMb``,
    trained on the bounded subsample (not the full log), at holdout
    AuPR parity (within 0.02) with the in-memory fit."""
    import subprocess
    import tempfile

    import numpy as np

    from transmogrifai_tpu.readers.avro import write_avro_records

    rows = int(os.environ.get("BENCH_OOC_ROWS", 300_000))
    shards = 12
    cap_mb = float(os.environ.get("BENCH_OOC_RSS_CAP_MB", 450))
    sample_rows = int(os.environ.get("BENCH_OOC_SAMPLE_ROWS", 32_768))
    here = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="tmog_ooc_")
    out: dict = {"rows": rows, "shards": shards, "rssCapMb": cap_mb,
                 "sample_rows": sample_rows}
    try:
        data = os.path.join(work, "events")
        os.makedirs(data)
        beta = np.random.default_rng(16).normal(size=6)

        def make(n, seed):
            r = np.random.default_rng(seed)
            X = r.normal(size=(n, 6))
            y = (X @ beta + r.normal(size=n) * 0.5 > 0).astype(float)
            return [{"label": float(y[i]),
                     **{f"x{j}": float(X[i, j]) for j in range(6)}}
                    for i in range(n)]

        for s in range(shards):        # one shard in memory at a time
            write_avro_records(os.path.join(data, f"part-{s:04d}.avro"),
                               make(rows // shards, 100 + s))
        holdout = os.path.join(work, "holdout.avro")
        write_avro_records(holdout, make(4_000, 999))
        out["dataset_mb_on_disk"] = round(
            sum(os.path.getsize(os.path.join(data, f))
                for f in os.listdir(data)) / 2**20, 1)

        def child(mode, cap):
            env = dict(os.environ)
            # glibc grows one 64 MiB malloc arena per contending
            # thread; under RLIMIT_DATA those RESERVATIONS count, so an
            # uncapped arena count turns worker-thread jitter into
            # spurious MemoryErrors far below the real working set
            env["MALLOC_ARENA_MAX"] = "2"
            # the leg proves a HOST-memory property on the single-CPU
            # backend (bench_ooc pins it); inherited XLA_FLAGS — e.g. a
            # forced 8-device host platform from a mesh test rig —
            # would multiply the child's baseline arenas and swamp the
            # working-set signal under the cap
            env.pop("XLA_FLAGS", None)
            t0 = time.time()
            proc = subprocess.run(
                [sys.executable, os.path.join(here, "tools",
                                              "bench_ooc.py"),
                 data, holdout, mode, str(cap), str(sample_rows)],
                env=env, capture_output=True, text=True, timeout=420)
            if proc.returncode:
                return {"rc": proc.returncode,
                        "error": (proc.stderr or "")[-400:]}
            doc = json.loads([ln for ln in proc.stdout.splitlines()
                              if ln.startswith("{")][-1])
            doc["wall_s"] = round(time.time() - t0, 1)
            return doc

        st = child("stream", cap_mb)
        out["stream"] = st
        mt = child("materialize", 0.0)
        out["materialized"] = mt
        ok = "error" not in st and "error" not in mt
        out["quality_parity"] = bool(
            ok and abs(st["holdout_AuPR"] - mt["holdout_AuPR"]) <= 0.02)
        # the "deliberately larger than the budget" evidence: the
        # uncapped in-memory fit's high-water mark vs the cap
        out["materialize_exceeds_cap"] = bool(
            ok and (mt.get("peak_rss_mb") or 0) > cap_mb)
        out["pass"] = bool(
            ok and out["quality_parity"]
            and st.get("peak_rss_mb") is not None
            and st["peak_rss_mb"] < cap_mb
            and st["rows_trained"] <= sample_rows < rows)
    finally:
        import shutil
        shutil.rmtree(work, ignore_errors=True)
    return out


_COLD_PROBE_SCRIPT = r"""
import json, os, sys, time
import jax
sys.path.insert(0, sys.argv[1])
from transmogrifai_tpu import aot
from transmogrifai_tpu.cli import _populate_stage_registry
from transmogrifai_tpu.scoring import ScoringEngine
from transmogrifai_tpu.workflow import WorkflowModel
model_dir, export_dir, cap, use_bank = (
    sys.argv[2], sys.argv[3], int(sys.argv[4]), sys.argv[5] == "bank")
_populate_stage_registry()
model = WorkflowModel.load(model_dir)
eng = ScoringEngine(model, gate_bandwidth=False, mesh=False,
                    bucket_cap=cap)
t_load0 = time.perf_counter()
report = {"loaded": []}
if use_bank:
    report = aot.load_program_bank(eng, export_dir)
load_ms = (time.perf_counter() - t_load0) * 1e3
records = json.load(open(os.path.join(export_dir, "bench_req.json")))
t0 = time.perf_counter()
out = eng.score_store(records)
first_ms = (time.perf_counter() - t0) * 1e3
print("COLDJSON " + json.dumps({
    "first_request_ms": round(first_ms, 3),
    "bank_load_ms": round(load_ms, 3),
    "bank_buckets": report["loaded"],
    "compile_count": eng.compile_count,
    "rows": out.n_rows}))
"""


def _serving_latency() -> dict:
    """AOT bank + model server benchmark (the millions-of-users tier):

    1. **Cold-process first-request latency** — a fresh interpreter
       loads the saved smoke model and answers one request, with vs
       without the AOT program bank. Honest cold: the subprocess does
       NOT inherit this process's persistent compile cache. Pass flag:
       ``bank_cold_start_ms < 0.05 * jit_cold_start_ms`` (the 10×
       acceptance criterion with margin).
    2. **Steady-state serving** — a ModelServer under a Poisson-ish
       synthetic load at two batching deadlines: p50/p99 request
       latency, throughput and the measured coalescing factor.
    """
    import subprocess
    import tempfile
    import threading

    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values, serving)
    from transmogrifai_tpu import server as server_mod
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    cap = int(os.environ.get("BENCH_SERVE_BUCKET_CAP", 1024))
    train_rows = 20_000
    rng = np.random.default_rng(17)
    y = rng.integers(0, 2, train_rows).astype(float)
    xs = {f"x{j}": rng.normal(size=train_rows) + (0.3 * j) * y
          for j in range(6)}
    cols = {"label": column_from_values(ft.RealNN, y)}
    for k, v in xs.items():
        cols[k] = column_from_values(ft.Real, list(v))
    store = ColumnStore(cols, train_rows)
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
             for j in range(6)]
    vec = transmogrify(feats)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=5)
    pred = label.transform_with(selector, vec)
    model = (Workflow().set_input_store(store)
             .set_result_features(pred).train())

    records = [{"label": float(y[i]),
                **{f"x{j}": float(xs[f"x{j}"][i]) for j in range(6)}}
               for i in range(2048)]

    work = tempfile.mkdtemp(prefix="tmog_serve_bench_")
    model_dir = os.path.join(work, "model")
    export_dir = os.path.join(work, "export")
    model.save(model_dir)
    t0 = time.time()
    meta = serving.export_scoring_fn(model, export_dir, records[:8],
                                     bucket_cap=cap)
    export_s = time.time() - t0
    with open(os.path.join(export_dir, "bench_req.json"), "w") as fh:
        json.dump(records[:64], fh)

    out: dict = {"bucket_cap": cap, "export_s": round(export_s, 2),
                 "aot_meta": meta["aot"]}
    here = os.path.dirname(os.path.abspath(__file__))

    def cold_probe(mode: str) -> dict:
        proc = subprocess.run(
            [sys.executable, "-c", _COLD_PROBE_SCRIPT, here, model_dir,
             export_dir, str(cap), mode],
            capture_output=True, text=True, timeout=600)
        for line in proc.stdout.splitlines():
            if line.startswith("COLDJSON "):
                return json.loads(line[len("COLDJSON "):])
        raise RuntimeError(
            f"cold probe ({mode}) produced no result: rc="
            f"{proc.returncode} stderr={proc.stderr[-400:]!r}")

    def cold_probe_inproc(mode: str) -> dict:
        """Fallback when a second process cannot attach the accelerator
        (TPU runtimes are exclusive): a FRESH engine per probe — its
        program cache starts empty — with the persistent compile cache
        disabled so the JIT leg pays a real compile."""
        import jax

        from transmogrifai_tpu import aot
        from transmogrifai_tpu.scoring import ScoringEngine
        prev = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
        try:
            eng = ScoringEngine(model, gate_bandwidth=False, mesh=False,
                                bucket_cap=cap)
            t0 = time.perf_counter()
            report = {"loaded": []}
            if mode == "bank":
                report = aot.load_program_bank(eng, export_dir)
            load_ms = (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
            res = eng.score_store(records[:64], use_cache=False)
            return {"first_request_ms":
                    round((time.perf_counter() - t0) * 1e3, 3),
                    "bank_load_ms": round(load_ms, 3),
                    "bank_buckets": report["loaded"],
                    "compile_count": eng.compile_count,
                    "rows": res.n_rows}
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    if meta["aot"] is None:
        out["cold_start"] = {"status": "bank_unavailable_on_backend"}
    else:
        try:
            jit = cold_probe("jit")
            bank = cold_probe("bank")
            out["cold_mode"] = "subprocess"
        except (RuntimeError, subprocess.TimeoutExpired) as e:
            # exclusive-accelerator runtimes (TPU) refuse a second
            # process: measure with fresh engines in THIS process,
            # persistent compile cache off — the compile_count field
            # still proves the zero-compile claim
            _log(f"[bench] cold subprocess unavailable ({e!r}); "
                 "in-process fresh-engine fallback")
            jit = cold_probe_inproc("jit")
            bank = cold_probe_inproc("bank")
            out["cold_mode"] = "in_process_fresh_engine"
        out["cold_start"] = {
            "jit_cold_start_ms": jit["first_request_ms"],
            "jit_compiles": jit["compile_count"],
            "bank_cold_start_ms": bank["first_request_ms"],
            "bank_load_ms": bank["bank_load_ms"],
            "bank_compiles": bank["compile_count"],
            "speedup": round(jit["first_request_ms"]
                             / max(bank["first_request_ms"], 1e-9), 1),
            "pass": (bank["compile_count"] == 0
                     and bank["first_request_ms"]
                     < 0.05 * jit["first_request_ms"]),
        }

    # -- steady state: Poisson-ish load at two batching deadlines ----------
    duration_s = float(os.environ.get("BENCH_SERVE_SECONDS", 3.0))
    rate_hz = float(os.environ.get("BENCH_SERVE_RATE_HZ", 400.0))
    n_clients = 4
    out["steady_state"] = {}
    for deadline_ms in (0.0, 5.0):
        srv = server_mod.ModelServer(batch_deadline_s=deadline_ms / 1e3,
                                     bucket_cap=cap, slo_ms=50.0)
        srv.register("m", model_dir=model_dir, bank_dir=export_dir,
                     preload=True)
        stats_before = server_mod.server_stats()
        lat: list = []
        lat_lock = threading.Lock()

        def client(k: int) -> None:
            crng = np.random.default_rng(100 + k)
            t_end = time.perf_counter() + duration_s
            while time.perf_counter() < t_end:
                # exponential inter-arrival — the Poisson-ish load
                time.sleep(float(crng.exponential(
                    n_clients / rate_hz)))
                lo = int(crng.integers(0, len(records) - 8))
                n = int(crng.integers(1, 9))
                try:
                    res = srv.submit(
                        "m", records[lo:lo + n]).result(timeout=60)
                except server_mod.ServerBusy:
                    continue
                with lat_lock:
                    lat.append(res.seconds)

        threads = [threading.Thread(target=client, args=(k,),
                                    name=f"bench-client-{k}",
                                    daemon=True)
                   for k in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=duration_s * 4 + 60)
        wall = time.perf_counter() - t0
        srv.shutdown(drain=True)
        d = {k: v - stats_before[k]
             for k, v in server_mod.server_stats().items()
             if isinstance(v, (int, float))
             and isinstance(stats_before.get(k), (int, float))}
        arr = np.asarray(lat, dtype=np.float64) * 1e3
        out["steady_state"][f"deadline_{deadline_ms:g}ms"] = {
            "requests": int(arr.size),
            "requests_per_s": round(arr.size / wall, 1),
            "p50_ms": round(float(np.percentile(arr, 50)), 3)
            if arr.size else None,
            "p99_ms": round(float(np.percentile(arr, 99)), 3)
            if arr.size else None,
            "coalescing_factor": (round(d["requests"]
                                        / max(d["batches"], 1), 2)),
            "bank_hit_batches": d.get("bank_hit_batches", 0),
            "quarantined": d.get("quarantined_requests", 0),
            "slo50ms_attainment": (round(
                d.get("slo_met", 0)
                / max(d.get("slo_met", 0) + d.get("slo_missed", 0), 1),
                4)),
        }
    return out


def _trace_overhead() -> dict:
    """Observability-plane overhead benchmark (telemetry.py /
    docs/observability.md "Distributed tracing"): FLEET serving
    throughput with the full tracing plane OFF vs ON — telemetry
    recording on the worker, router-minted trace contexts + request
    spans + batch span links, the per-model latency-decomposition
    histograms, and trace-shard accounting. Pass flag: median paired
    overhead < 5%.

    Measured through the REAL fleet path: two 1-worker fleets over the
    same registry — one booted with ``serveMetrics``+``traceDir``
    (tracing on), one without — each behind its own in-process
    consistent-hash router (``serve_fleet_http``), pumped with
    identical traffic. Overhead is the paired ratio of MEDIAN
    per-request latency (for a serial closed-loop client the same
    per-request cost as mean throughput, but robust to the discrete
    ambient stalls — GC, CFS throttling, noisy neighbors — that fatten
    a mean by 10%+ on shared machines; a 2%-scale signal under a fixed
    5% gate needs the robust estimator). Legs INTERLEAVE with
    ALTERNATING order per pair so slow drift hits both sides and
    within-pair ordering bias cancels."""
    import http.client
    import tempfile

    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values, lifecycle,
                                   serving, telemetry)
    from transmogrifai_tpu import fleet as fleet_mod
    from transmogrifai_tpu import resilience
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    cap = int(os.environ.get("BENCH_TRACE_BUCKET_CAP", 1024))
    train_rows = 20_000
    n_feats = 6
    rng = np.random.default_rng(23)
    y = rng.integers(0, 2, train_rows).astype(float)
    xs = {f"x{j}": rng.normal(size=train_rows) + (0.3 * j) * y
          for j in range(n_feats)}
    cols = {"label": column_from_values(ft.RealNN, y)}
    for k, v in xs.items():
        cols[k] = column_from_values(ft.Real, list(v))
    store = ColumnStore(cols, train_rows)
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
             for j in range(n_feats)]
    vec = transmogrify(feats)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=23)
    pred = label.transform_with(selector, vec)
    model = (Workflow().set_input_store(store)
             .set_result_features(pred).train())
    model._engine_breaker().reset()
    records = [{"label": float(y[i]),
                **{f"x{j}": float(xs[f"x{j}"][i])
                   for j in range(n_feats)}}
               for i in range(4096)]

    work = tempfile.mkdtemp(prefix="tmog_trace_bench_")
    mdir = os.path.join(work, "model")
    edir = os.path.join(work, "export")
    model.save(mdir)
    serving.export_scoring_fn(model, edir, records[:8], bucket_cap=cap)
    registry = lifecycle.ModelRegistry(os.path.join(work, "registry"))
    registry.register("bench", mdir, bank_dir=edir, promote=True)
    trace_dir = os.path.join(work, "traces")
    base = {"registryDir": os.path.join(work, "registry"),
            "serveBucketCap": cap, "serveBatchDeadlineMs": 0.0}
    params = {}
    for leg_name, extra in (
            ("tracing_off", {}),
            ("tracing_on", {"serveMetrics": True,
                            "traceDir": trace_dir})):
        p = os.path.join(work, f"params_{leg_name}.json")
        with open(p, "w") as fh:
            json.dump({"customParams": {**base, **extra}}, fh)
        params[leg_name] = p

    # legs long enough to amortize discrete ambient stalls (GC, CFS
    # throttling, page-cache churn): a 10%+ spike in a 3 s leg is one
    # ~300 ms stall, which a 6 s leg halves — the gate hunts a ~2%
    # signal, so leg length is the noise knob that matters
    duration_s = float(os.environ.get("BENCH_TRACE_SECONDS", 6.0))
    batch = 64
    reps = int(os.environ.get("BENCH_TRACE_REPS", 7))
    backoff = resilience.RetryPolicy(max_attempts=8, base_delay_s=0.05,
                                     max_delay_s=0.5, jitter=0.1,
                                     seed=7)
    bodies = [json.dumps({"records": records[lo:lo + batch]}).encode()
              for lo in range(0, len(records) - batch, batch)]

    sups = {}
    routers = {}
    ports = {}
    for leg_name in ("tracing_off", "tracing_on"):
        sup = fleet_mod.FleetSupervisor(params[leg_name], workers=1,
                                        respawn_max=4,
                                        probe_interval_s=0.1,
                                        backoff=backoff)
        sup.start()
        sup.wait_ready(timeout_s=240)
        httpd = fleet_mod.serve_fleet_http(sup, port=0, retry_budget=1,
                                           forward_timeout_s=120.0)
        sups[leg_name] = sup
        routers[leg_name] = httpd
        ports[leg_name] = httpd.server_address[1]

    def pump(leg_name: str) -> dict:
        port = ports[leg_name]
        rows = reqs = 0
        lats: list = []
        t_end = time.perf_counter() + duration_s
        t0 = time.perf_counter()
        i = 0
        while time.perf_counter() < t_end:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=120)
            t_req = time.perf_counter()
            try:
                conn.request("POST", "/v1/models/bench:score",
                             bodies[i % len(bodies)],
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 200, resp.status
            finally:
                conn.close()
            lats.append(time.perf_counter() - t_req)
            i += 1
            rows += batch
            reqs += 1
        wall = time.perf_counter() - t0
        return {"rows": rows, "requests": reqs,
                "rows_per_s": round(rows / wall, 1),
                "p50_ms": round(float(np.median(lats)) * 1e3, 4)}

    was_enabled = telemetry.enabled()

    def leg(leg_name: str) -> dict:
        # the traced fleet's ROUTER lives in this process: recording on
        # during its legs so fleet:route spans + minted contexts pay
        # their real cost (the worker's telemetry rides its params)
        if leg_name == "tracing_on":
            telemetry.enable()
        try:
            return pump(leg_name)
        finally:
            telemetry.disable()

    legs = {"tracing_off": {"rep_rows_per_s": [], "rep_p50_ms": []},
            "tracing_on": {"rep_rows_per_s": [], "rep_p50_ms": []}}
    ratios = []
    spans_recorded = 0
    try:
        for name in ("tracing_off", "tracing_on"):
            pump(name)                   # warm both paths off-clock
        for rep in range(reps):
            if rep % 2 == 0:
                off, on = leg("tracing_off"), leg("tracing_on")
            else:
                on, off = leg("tracing_on"), leg("tracing_off")
            legs["tracing_off"]["rep_rows_per_s"].append(
                off["rows_per_s"])
            legs["tracing_on"]["rep_rows_per_s"].append(
                on["rows_per_s"])
            legs["tracing_off"]["rep_p50_ms"].append(off["p50_ms"])
            legs["tracing_on"]["rep_p50_ms"].append(on["p50_ms"])
            ratios.append(on["p50_ms"] / max(off["p50_ms"], 1e-9)
                          - 1.0)
        spans_recorded = sum(
            1 for ev in telemetry.trace_events()
            if ev.get("ph") == "X")
    finally:
        for httpd in routers.values():
            httpd.shutdown()
        for sup in sups.values():
            sup.stop(drain=True)
        telemetry.reset(keep_listeners=True)
        if was_enabled:
            telemetry.enable()
        else:
            telemetry.disable()
    shards = []
    try:
        shards = [f for f in os.listdir(trace_dir)
                  if f.endswith(".trace.json")]
    except OSError:
        pass
    for leg_name in legs:
        legs[leg_name]["rows_per_s"] = max(
            legs[leg_name]["rep_rows_per_s"])
        legs[leg_name]["p50_ms"] = min(legs[leg_name]["rep_p50_ms"])
    overhead = float(np.median(ratios))
    return {"bucket_cap": cap, "duration_s_per_leg": duration_s,
            "reps": reps, "legs": legs,
            "paired_overheads": [round(r, 4) for r in ratios],
            "tracing_overhead": round(overhead, 4),
            "router_spans_recorded": spans_recorded,
            "worker_trace_shards": shards,
            "pass": bool(overhead < 0.05)}


def _workload_replay() -> dict:
    """Workload capture-and-replay benchmark (workload.py /
    docs/observability.md "Workload capture & replay" +
    "Critical-path analysis"), four phases over two 1-worker fleets
    serving the same registry — one booted with the flight recorder
    (``workloadDir``), one without, both with the tracing plane on so
    the pairing isolates the RECORDER's marginal cost:

    1. **Record** — pump the recording fleet with the router-side
       recorder installed too, then merge the per-process shards into
       one arrival-ordered workload (router+worker records combined).
    2. **Overhead** — ONE in-process `serve_http` instance, recorder
       toggled per leg in ALTERNATING order; overhead is the median
       paired ratio of MEDIAN per-request latency (the
       `trace_overhead` discipline — the recorder's request-path
       cost is one bounded-queue put, so the gate hunts a sub-1%
       signal; pairing two fleet instances instead bakes in
       cross-instance asymmetry that swamps it). Pass: median < 5%.
    3. **Replay** — re-drive the merged workload open-loop at 1x
       (recorded arrival offsets) against the OTHER fleet; score
       parity must hold everywhere outputs were recorded, and the
       replayed per-phase p50s must agree with the recorded ones
       within tolerance (phase stats are arrival-process-dependent,
       so agreement proves the recording reproduces the workload).
    4. **Analyze** — a clean traced window, then the critical-path
       analyzer over the merged trace shards: >= 95% of every
       request's e2e attributed to named phases, self-diff clean, and
       a perturbed baseline must trip the regression verdict."""
    import http.client
    import shutil
    import tempfile

    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values, lifecycle,
                                   serving, telemetry)
    from transmogrifai_tpu import fleet as fleet_mod
    from transmogrifai_tpu import resilience
    from transmogrifai_tpu import workload as workload_mod
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    cap = int(os.environ.get("BENCH_TRACE_BUCKET_CAP", 1024))
    train_rows = 20_000
    n_feats = 6
    rng = np.random.default_rng(29)
    y = rng.integers(0, 2, train_rows).astype(float)
    xs = {f"x{j}": rng.normal(size=train_rows) + (0.3 * j) * y
          for j in range(n_feats)}
    cols = {"label": column_from_values(ft.RealNN, y)}
    for k, v in xs.items():
        cols[k] = column_from_values(ft.Real, list(v))
    store = ColumnStore(cols, train_rows)
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
             for j in range(n_feats)]
    vec = transmogrify(feats)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=29)
    pred = label.transform_with(selector, vec)
    model = (Workflow().set_input_store(store)
             .set_result_features(pred).train())
    model._engine_breaker().reset()
    records = [{"label": float(y[i]),
                **{f"x{j}": float(xs[f"x{j}"][i])
                   for j in range(n_feats)}}
               for i in range(4096)]

    work = tempfile.mkdtemp(prefix="tmog_workload_bench_")
    mdir = os.path.join(work, "model")
    edir = os.path.join(work, "export")
    model.save(mdir)
    serving.export_scoring_fn(model, edir, records[:8], bucket_cap=cap)
    registry = lifecycle.ModelRegistry(os.path.join(work, "registry"))
    registry.register("bench", mdir, bank_dir=edir, promote=True)
    wdir = os.path.join(work, "workload")
    trace_dirs = {n: os.path.join(work, f"traces_{n}")
                  for n in ("recorder_off", "recorder_on")}
    base = {"registryDir": os.path.join(work, "registry"),
            "serveBucketCap": cap, "serveBatchDeadlineMs": 0.0,
            "serveMetrics": True}
    params = {}
    for leg_name, extra in (
            ("recorder_off", {}),
            ("recorder_on", {"workloadDir": wdir,
                             "workloadMaxMb": 8.0,
                             "workloadPayloads": True})):
        p = os.path.join(work, f"params_{leg_name}.json")
        with open(p, "w") as fh:
            json.dump({"customParams": {
                **base, "traceDir": trace_dirs[leg_name],
                **extra}}, fh)
        params[leg_name] = p

    record_s = float(os.environ.get("BENCH_WORKLOAD_RECORD_SECONDS", 6.0))
    duration_s = float(os.environ.get("BENCH_WORKLOAD_SECONDS", 5.0))
    reps = int(os.environ.get("BENCH_WORKLOAD_REPS", 7))
    batch = 64
    backoff = resilience.RetryPolicy(max_attempts=8, base_delay_s=0.05,
                                     max_delay_s=0.5, jitter=0.1,
                                     seed=11)
    bodies = [records[lo:lo + batch]
              for lo in range(0, len(records) - batch, batch)]
    raw_bodies = [json.dumps({"records": b}).encode() for b in bodies]

    sups = {}
    routers = {}
    ports = {}
    for leg_name in ("recorder_off", "recorder_on"):
        sup = fleet_mod.FleetSupervisor(params[leg_name], workers=1,
                                        respawn_max=4,
                                        probe_interval_s=0.1,
                                        backoff=backoff)
        sup.start()
        sup.wait_ready(timeout_s=240)
        httpd = fleet_mod.serve_fleet_http(sup, port=0, retry_budget=1,
                                           forward_timeout_s=120.0)
        sups[leg_name] = sup
        routers[leg_name] = httpd
        ports[leg_name] = httpd.server_address[1]

    def pump(leg_name: str, seconds: float,
             pace_s: float = 0.0) -> dict:
        # pace_s > 0 leaves idle gaps between requests: a recording
        # made at ~100% utilization cannot replay at 1x without the
        # queue exploding (any service-time jitter accumulates), so
        # the RECORD pass runs paced while the overhead legs stay
        # closed-loop for maximum sensitivity
        port = ports[leg_name]
        reqs = 0
        lats: list = []
        t_end = time.perf_counter() + seconds
        i = 0
        while time.perf_counter() < t_end:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=120)
            t_req = time.perf_counter()
            try:
                conn.request("POST", "/v1/models/bench:score",
                             raw_bodies[i % len(raw_bodies)],
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 200, resp.status
            finally:
                conn.close()
            lats.append(time.perf_counter() - t_req)
            i += 1
            reqs += 1
            if pace_s:
                time.sleep(pace_s)
        return {"requests": reqs,
                "p50_ms": round(float(np.median(lats)) * 1e3, 4)}

    def leg(leg_name: str, seconds: float,
            pace_s: float = 0.0) -> dict:
        # the recording fleet's ROUTER lives in this process: its
        # recorder is installed only during recorder-on legs so the
        # off legs pay zero recorder cost (legs never overlap)
        if leg_name == "recorder_on":
            workload_mod.start_recorder(wdir, role="router")
        try:
            return pump(leg_name, seconds, pace_s=pace_s)
        finally:
            workload_mod.stop_recorder()

    was_enabled = telemetry.enabled()
    telemetry.enable()   # tracing plane ON for both legs — constant
    out: dict = {"duration_s_per_leg": duration_s, "reps": reps}
    try:
        # -- phase 1: record ------------------------------------------------
        rec_leg = leg("recorder_on", record_s, pace_s=0.01)
        merged = workload_mod.merge_workload_shards(wdir)
        recorded = workload_mod.summarize_workload(merged)
        out["recorded"] = {
            "requests": merged["requests"],
            "shards": merged["mergedShards"],
            "tornRecordsSkipped": merged["tornRecordsSkipped"],
            "combinedSources": sorted(
                {s for r in merged["records"]
                 for s in r.get("sources", ())}),
            "phases": recorded["models"].get("bench", {}).get("phases"),
        }

        # -- phase 2: recorder overhead on ONE server instance -------------
        # the drift_canary pairing discipline: same instance, same
        # stream, recorder toggled per leg with ALTERNATING order — a
        # two-fleet pairing bakes in cross-instance asymmetry (worker
        # process placement, allocator state) that dwarfs a
        # microsecond-scale recorder signal. serve_http runs the SAME
        # handler + zero-copy record path the fleet workers run.
        from transmogrifai_tpu import server as server_mod
        srv_local = server_mod.ModelServer(bucket_cap=cap,
                                           batch_deadline_s=0.0)
        srv_local.register("bench", model_dir=mdir, bank_dir=edir)
        httpd_local = server_mod.serve_http(srv_local, port=0)
        ports["local"] = httpd_local.server_address[1]
        odir = os.path.join(work, "workload_overhead")

        def leg_local(recording: bool, seconds: float) -> dict:
            if recording:
                workload_mod.start_recorder(odir, role="overhead")
            try:
                return pump("local", seconds)
            finally:
                workload_mod.stop_recorder()

        try:
            pump("local", min(duration_s, 3.0))     # warm off-clock
            legs = {n: {"rep_p50_ms": []}
                    for n in ("recorder_off", "recorder_on")}
            ratios = []
            for rep in range(reps):
                if rep % 2 == 0:
                    off = leg_local(False, duration_s)
                    on = leg_local(True, duration_s)
                else:
                    on = leg_local(True, duration_s)
                    off = leg_local(False, duration_s)
                legs["recorder_off"]["rep_p50_ms"].append(off["p50_ms"])
                legs["recorder_on"]["rep_p50_ms"].append(on["p50_ms"])
                ratios.append(on["p50_ms"] / max(off["p50_ms"], 1e-9)
                              - 1.0)
        finally:
            httpd_local.shutdown()
            srv_local.shutdown(drain=True)
        for n in legs:
            legs[n]["p50_ms"] = min(legs[n]["rep_p50_ms"])
        overhead = float(np.median(ratios))
        out["legs"] = legs
        out["paired_overheads"] = [round(r, 4) for r in ratios]
        out["recorder_overhead"] = round(overhead, 4)

        # -- phase 3: replay at 1x against the OTHER fleet ------------------
        replayed = workload_mod.replay_workload(
            merged, f"127.0.0.1:{ports['recorder_off']}", speed=1.0,
            timeout_s=60.0)
        rec_phases = (recorded["models"].get("bench", {})
                      .get("phases") or {})
        rep_phases = (replayed["models"].get("bench", {})
                      .get("phases") or {})
        agreement = {}
        agree_ok = True
        for ph in sorted(set(rec_phases) & set(rep_phases)):
            a, b = rec_phases[ph]["p50Ms"], rep_phases[ph]["p50Ms"]
            tol = max(0.5 * a, 10.0)   # ms: arrival-dependent phases
            ok = abs(b - a) <= tol
            agree_ok = agree_ok and ok
            agreement[ph] = {"recorded_p50_ms": a, "replayed_p50_ms": b,
                             "tol_ms": round(tol, 3), "ok": ok}
        out["replay"] = {
            "sent": replayed["sent"], "failed": replayed["failed"],
            "skipped_no_payload": replayed["skippedNoPayload"],
            "late_sends": replayed["lateSends"],
            "parity_checked": replayed["parityChecked"],
            "parity_failures": replayed["parityFailures"],
            "parity_max_abs_delta": replayed["parityMaxAbsDelta"],
            "phase_agreement": agreement,
        }
        parity_ok = (replayed["parityChecked"] > 0
                     and replayed["parityFailures"] == 0)

        # -- phase 4: critical-path analysis on a clean traced window ------
        telemetry.reset(keep_listeners=True)
        telemetry.enable()
        pump("recorder_on", 2.0)
    finally:
        for httpd in routers.values():
            httpd.shutdown()
        for sup in sups.values():
            sup.stop(drain=True)     # workers write their trace shards
        router_events = telemetry.trace_events()
        telemetry.reset(keep_listeners=True)
        if was_enabled:
            telemetry.enable()
        else:
            telemetry.disable()
    # hand-write the router's shard (its events were captured above,
    # before the reset restored ambient telemetry state)
    os.makedirs(trace_dirs["recorder_on"], exist_ok=True)
    with open(os.path.join(trace_dirs["recorder_on"],
                           "shard-router-0.trace.json"), "w") as fh:
        json.dump({"role": "router", "pid": 0,
                   "epochUnixS": time.time()
                   - time.perf_counter() + telemetry._EPOCH,
                   "traceEvents": router_events}, fh)
    analysis = workload_mod.analyze_trace(trace_dirs["recorder_on"],
                                          top_k=3)
    self_diff = workload_mod.diff_analyses(analysis, analysis)
    # a baseline whose p99s were all HALVED must trip the watchdog
    perturbed = json.loads(json.dumps(analysis))
    for ph in perturbed["phases"].values():
        ph["p99Ms"] = ph["p99Ms"] / 2.0
    trip_diff = workload_mod.diff_analyses(analysis, perturbed)
    coverage_ok = bool(analysis["requests"] > 0
                       and analysis["coverage"]["min"] >= 0.95)
    out["analysis"] = {
        "requests": analysis["requests"],
        "coverage": analysis["coverage"],
        "phase_shares": {n: p["share"]
                         for n, p in analysis["phases"].items()},
        "slowest_path": [s["name"] for s in
                         (analysis["slowest"][0]["path"]
                          if analysis["slowest"] else [])],
        "self_diff_ok": self_diff["ok"],
        "perturbed_baseline_regressions": trip_diff["regressions"],
    }
    shutil.rmtree(work, ignore_errors=True)
    out["record_leg_requests"] = rec_leg["requests"]
    out["workload_stats"] = workload_mod.workload_stats()
    out["pass"] = bool(overhead < 0.05 and parity_ok and agree_ok
                       and coverage_ok and self_diff["ok"]
                       and trip_diff["regressions"] > 0)
    return out


def _autotune() -> dict:
    """Self-tuning runtime benchmark (tuner.py + the server's online
    deadline controller, docs/tuning.md), two phases:

    1. **Offline tune** — record a paced workload against a
       default-config server, then run the coordinate-descent
       autotuner over ``serveBatchDeadlineMs`` + ``pipelineWorkers``
       under a small budget:
       per candidate the tuner boots a fresh server and re-drives the
       recording through the replay harness. Pass: the emitted config
       never loses to the baseline and EVERY ranked leg held score
       parity (the tuner's hard gate, asserted here from the report).
    2. **Online adaptation** — the same model served with
       ``adaptDeadline`` on, driven through a shifted arrival process
       (paced-sparse then closed-loop bursts) past several adaptation
       windows. Pass: the controller closed windows, any adapted
       deadline stayed inside the registry's declared tune bounds,
       and every request was answered (zero failures).
    """
    import http.client
    import shutil
    import tempfile

    import numpy as np

    from transmogrifai_tpu import FeatureBuilder, Workflow, config
    from transmogrifai_tpu import server as server_mod
    from transmogrifai_tpu import tuner as tuner_mod
    from transmogrifai_tpu import workload as workload_mod
    from transmogrifai_tpu.cli import build_server_from_params
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.runner import OpParams

    rng = np.random.default_rng(31)
    rows = 2000
    y = rng.integers(0, 2, rows).astype(float)
    records = [{"label": float(y[i]),
                "x1": float(rng.normal() + 0.8 * y[i]),
                "x2": float(rng.normal())} for i in range(rows)]
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    f1 = FeatureBuilder.Real("x1").from_column().as_predictor()
    f2 = FeatureBuilder.Real("x2").from_column().as_predictor()
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=31)
    pred = label.transform_with(sel, transmogrify([f1, f2]))
    model = (Workflow().set_input_records(records)
             .set_result_features(pred).train())
    model._engine_breaker().reset()

    work = tempfile.mkdtemp(prefix="tmog_autotune_bench_")
    out: dict = {}
    try:
        mdir = os.path.join(work, "model")
        model.save(mdir)
        pf = os.path.join(work, "params.json")
        with open(pf, "w") as fh:
            json.dump({"modelLocation": mdir,
                       "customParams": {"serveBatchDeadlineMs": 2.0,
                                        "serveBucketCap": 256}}, fh)

        def pump(port: int, n: int, batch: int = 16,
                 pace_s: float = 0.0) -> int:
            sent = 0
            for i in range(n):
                lo = (i * batch) % (rows - batch)
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                try:
                    conn.request(
                        "POST", "/v1/models/default:score",
                        json.dumps({"records": records[lo:lo + batch]}),
                        {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    resp.read()
                    assert resp.status == 200, resp.status
                finally:
                    conn.close()
                sent += 1
                if pace_s:
                    time.sleep(pace_s)
            return sent

        # -- phase 1: record, then tune offline ----------------------
        srv = build_server_from_params(OpParams.from_file(pf))
        httpd = server_mod.serve_http(srv, port=0)
        wdir = os.path.join(work, "workload")
        workload_mod.start_recorder(wdir, role="bench-tune")
        try:
            pump(httpd.server_address[1],
                 int(os.environ.get("BENCH_TUNE_RECORD_REQUESTS", 48)),
                 pace_s=0.005)
        finally:
            workload_mod.stop_recorder()
            httpd.shutdown()
            srv.shutdown(drain=True)
            for e in srv._entries.values():
                if e.model is not None:
                    e.model._engine_breaker().reset()
        merged = workload_mod.merge_workload_shards(wdir)
        budget_s = float(os.environ.get("BENCH_TUNE_BUDGET_S", 60.0))
        tuned = tuner_mod.tune(pf, merged, objective="p99",
                               budget_s=budget_s,
                               knobs=["serveBatchDeadlineMs",
                                      "pipelineWorkers"],
                               speed=20.0)
        rep = tuned["report"]
        ranked = [l for l in rep["legs"] if l.get("rejected") is None]
        tune_parity_ok = all(l["parityFailures"] == 0 for l in ranked)
        tune_ok = bool(rep["winnerScore"] <= rep["baselineScore"]
                       and tune_parity_ok and len(ranked) >= 2
                       and not config.check_custom_params(
                           tuned["tunedParams"]["customParams"]))
        out["tune"] = {
            "objective": rep["objective"],
            "baseline_p99_ms": rep["baselineScore"],
            "winner_p99_ms": rep["winnerScore"],
            "improvement": rep["improvement"],
            "winner": rep["winner"],
            "legs_ranked": len(ranked),
            "legs_total": len(rep["legs"]),
            "budget_expired": rep["budgetExpired"],
            "parity_ok": tune_parity_ok,
        }

        # -- phase 2: online deadline adaptation ---------------------
        with open(pf, "w") as fh:
            json.dump({"modelLocation": mdir,
                       "customParams": {"serveBatchDeadlineMs": 2.0,
                                        "serveBucketCap": 256,
                                        "adaptDeadline": True}}, fh)
        srv = build_server_from_params(OpParams.from_file(pf))
        httpd = server_mod.serve_http(srv, port=0)
        before = {k: v for k, v in server_mod.server_stats().items()
                  if k.startswith("deadline_")}
        try:
            # shifted arrival process across several adaptation
            # windows: paced-sparse first (coalesce hold dominates the
            # split), then closed-loop bursts (queue wait grows)
            n_win = server_mod.ADAPT_WINDOW_REQUESTS
            pump(httpd.server_address[1], 2 * n_win + 8, batch=8,
                 pace_s=0.004)
            pump(httpd.server_address[1], 2 * n_win + 8, batch=8)
            entry = srv._entries["default"]
            lo_ms, hi_ms = config.knob_bounds("serveBatchDeadlineMs")
            adapted_ms = (None if entry.deadline_s is None
                          else entry.deadline_s * 1e3)
            in_bounds = (adapted_ms is None
                         or lo_ms <= adapted_ms <= hi_ms)
            failures = entry.failures
        finally:
            httpd.shutdown()
            srv.shutdown(drain=True)
            for e in srv._entries.values():
                if e.model is not None:
                    e.model._engine_breaker().reset()
        after = {k: v for k, v in server_mod.server_stats().items()
                 if k.startswith("deadline_")}
        delta = {k: after[k] - before.get(k, 0) for k in after}
        adapt_ok = bool(delta["deadline_adapt_windows"] > 0
                        and in_bounds and failures == 0)
        out["adaptation"] = {
            "windows": delta["deadline_adapt_windows"],
            "increases": delta["deadline_increases"],
            "decreases": delta["deadline_decreases"],
            "holds": delta["deadline_holds"],
            "clamped": delta["deadline_clamped"],
            "advisories": delta["deadline_advisories"],
            "adapted_deadline_ms": (None if adapted_ms is None
                                    else round(adapted_ms, 4)),
            "bounds_ms": [lo_ms, hi_ms],
            "in_bounds": in_bounds,
            "failed_requests": failures,
        }
        out["pass"] = bool(tune_ok and adapt_ok)
        return out
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _drift_canary() -> dict:
    """Model lifecycle benchmark (registry + drift sentinel + canary
    rollout, lifecycle.py / docs/lifecycle.md):

    1. **Sentinel overhead** — scoring throughput through a ModelServer
       with the serving-time drift sentinel off vs on over the SAME
       request stream. Pass flag: overhead < 5% (the sentinel is
       host-side numpy accumulation off the request's critical path).
    2. **Detection latency** — a synthetically shifted stream must trip
       a TMG6xx drift advisory within ONE comparison window.
    3. **Canary switchover** — a canary rollout of a second registered
       version runs to automated promotion under live traffic; every
       request across the switch is answered (zero drops).
    """
    import tempfile
    import threading

    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values, lifecycle, serving)
    from transmogrifai_tpu import server as server_mod
    from transmogrifai_tpu.filters.raw_feature_filter import \
        RawFeatureFilter
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    cap = int(os.environ.get("BENCH_DRIFT_BUCKET_CAP", 1024))
    train_rows = 20_000
    n_feats = 6

    def train(seed: int):
        rng = np.random.default_rng(seed)
        y = rng.integers(0, 2, train_rows).astype(float)
        xs = {f"x{j}": rng.normal(size=train_rows) + (0.3 * j) * y
              for j in range(n_feats)}
        cols = {"label": column_from_values(ft.RealNN, y)}
        for k, v in xs.items():
            cols[k] = column_from_values(ft.Real, list(v))
        store = ColumnStore(cols, train_rows)
        label = FeatureBuilder.RealNN("label").from_column().as_response()
        feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
                 for j in range(n_feats)]
        vec = transmogrify(feats)
        selector = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2, families=[LogisticRegressionFamily(
                grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
            splitter=None, seed=seed)
        pred = label.transform_with(selector, vec)
        model = (Workflow().set_input_store(store)
                 .with_raw_feature_filter(RawFeatureFilter(bins=50))
                 .set_result_features(pred).train())
        records = [{"label": float(y[i]),
                    **{f"x{j}": float(xs[f"x{j}"][i])
                       for j in range(n_feats)}}
                   for i in range(4096)]
        return model, records

    work = tempfile.mkdtemp(prefix="tmog_drift_bench_")
    registry = lifecycle.ModelRegistry(os.path.join(work, "registry"))
    vids = []
    records = None
    for i, seed in enumerate((17, 18)):
        model, recs = train(seed)
        mdir = os.path.join(work, f"model_v{i}")
        edir = os.path.join(work, f"export_v{i}")
        model.save(mdir)
        serving.export_scoring_fn(model, edir, recs[:8], bucket_cap=cap)
        vids.append(registry.register("bench", mdir, bank_dir=edir,
                                      promote=(i == 0)))
        if records is None:
            records = recs
        model._engine_breaker().reset()
    out: dict = {"versions": vids, "bucket_cap": cap}

    # -- 1. sentinel overhead: off vs on over the same stream --------------
    duration_s = float(os.environ.get("BENCH_DRIFT_SECONDS", 3.0))
    batch = 64

    def pump(srv: "server_mod.ModelServer") -> dict:
        # pipelined load (a sliding window of in-flight requests): the
        # throughput of a serial request→response ping-pong is dominated
        # by GIL handoff latency, which any third thread perturbs by
        # far more than its work share — capacity is what we measure
        from collections import deque
        rows = 0
        reqs = 0
        depth = 8
        inflight: deque = deque()
        t_end = time.perf_counter() + duration_s
        t0 = time.perf_counter()
        i = 0
        while time.perf_counter() < t_end:
            while len(inflight) < depth:
                lo = (i * batch) % (len(records) - batch)
                inflight.append(srv.submit("bench",
                                           records[lo:lo + batch]))
                i += 1
            inflight.popleft().result(timeout=120)
            rows += batch
            reqs += 1
        while inflight:
            inflight.popleft().result(timeout=120)
            rows += batch
            reqs += 1
        wall = time.perf_counter() - t0
        return {"rows": rows, "requests": reqs, "wall_s": wall,
                "rows_per_s": round(rows / wall, 1)}

    reps = int(os.environ.get("BENCH_DRIFT_REPS", 3))
    servers = {}
    for leg, window in (("sentinel_off", None), ("sentinel_on", 2048)):
        srv = server_mod.ModelServer(bucket_cap=cap, batch_deadline_s=0.0,
                                     registry=registry,
                                     drift_window=window)
        srv.register_from_registry("bench")
        srv.score("bench", records[:batch], timeout_s=600)  # warm
        servers[leg] = srv
    # ambient machine noise swings a single interval's rate by more
    # than the 5% gate: INTERLEAVE the legs (off, on, off, on, ...) so
    # slow system drift hits both sides of each pair, and take the
    # median paired ratio
    legs = {"sentinel_off": {"rep_rows_per_s": []},
            "sentinel_on": {"rep_rows_per_s": []}}
    ratios = []
    for _ in range(reps):
        off = pump(servers["sentinel_off"])
        on = pump(servers["sentinel_on"])
        legs["sentinel_off"]["rep_rows_per_s"].append(off["rows_per_s"])
        legs["sentinel_on"]["rep_rows_per_s"].append(on["rows_per_s"])
        ratios.append(off["rows_per_s"] / max(on["rows_per_s"], 1e-9)
                      - 1.0)
    for leg in legs:
        legs[leg]["rows_per_s"] = max(legs[leg]["rep_rows_per_s"])
    import numpy as _np
    overhead = float(_np.median(ratios))
    legs["sentinel_on"]["paired_overheads"] = [round(r, 4)
                                               for r in ratios]
    servers["sentinel_off"].shutdown(drain=True)
    srv = servers["sentinel_on"]
    srv.drain_drift()
    st = srv.stats()["models"]["bench"]["drift"]
    legs["sentinel_on"]["windows_compared"] = st["windowsCompared"]
    legs["sentinel_on"]["advisories"] = st["advisories"]
    srv.shutdown(drain=True)
    out["overhead"] = {**legs, "overhead_frac": round(overhead, 4),
                       "pass": bool(overhead < 0.05)}

    # -- 2. detection latency: shifted stream trips within one window ------
    window = 2048
    srv = server_mod.ModelServer(bucket_cap=cap, batch_deadline_s=0.0,
                                 registry=registry, drift_window=window)
    srv.register_from_registry("bench")
    rng = np.random.default_rng(99)
    shifted = [{**r, "x1": float(rng.normal() + 40.0)} for r in records]
    sent_rows = 0
    tripped_at = None
    for i in range(0, 2 * window, batch):
        lo = i % (len(shifted) - batch)
        srv.score("bench", shifted[lo:lo + batch], timeout_s=120)
        sent_rows += batch
        srv.drain_drift()
        if srv.stats()["models"]["bench"]["drift"]["advisories"]:
            tripped_at = sent_rows
            break
    srv.shutdown(drain=True)
    out["detection"] = {
        "window_rows": window, "shifted_rows_until_advisory": tripped_at,
        "pass": bool(tripped_at is not None and tripped_at <= window)}

    # -- 3. canary switchover: rollout to auto-promote, zero drops ---------
    srv = server_mod.ModelServer(bucket_cap=cap, batch_deadline_s=0.0,
                                 registry=registry, drift_window=None)
    srv.register_from_registry("bench")
    srv.score("bench", records[:batch], timeout_s=600)
    srv.deploy("bench", vids[1], mode="canary", fraction=0.25,
               window_requests=16, promote_windows=2)
    answered = 0
    submitted = 0
    t0 = time.perf_counter()
    while registry.current("bench") != vids[1] and submitted < 2000:
        lo = (submitted * 8) % (len(records) - 8)
        res = srv.score("bench", records[lo:lo + 8], timeout_s=120)
        submitted += 1
        answered += bool(res.rows == 8)
    switch_s = time.perf_counter() - t0
    # traffic KEEPS flowing after the switch (the promoted model serves)
    for i in range(8):
        res = srv.score("bench", records[i * 8:(i + 1) * 8], timeout_s=120)
        submitted += 1
        answered += bool(res.rows == 8)
    srv.shutdown(drain=True)
    promoted = registry.current("bench") == vids[1]
    out["switchover"] = {
        "requests": submitted, "answered": answered,
        "switch_s": round(switch_s, 3), "promoted": bool(promoted),
        "dropped": submitted - answered,
        "pass": bool(promoted and submitted == answered)}
    out["pass"] = bool(out["overhead"]["pass"] and out["detection"]["pass"]
                       and out["switchover"]["pass"])
    return out


def _self_healing() -> dict:
    """Continuous-training benchmark (continual.py — the closed
    drift→retrain→promote loop, docs/lifecycle.md "Continuous
    training"):

    1. A stable model serves; a covariate-shifted live stream (the
       informative feature's sign flipped + moved out of the train
       range) must trip TMG601.
    2. The retrain controller arms after consecutive drifted windows
       and runs a REAL supervised trainer subprocess, warm-started by
       monoid-merging the persisted train-time sufficient statistics
       with the fresh slice.
    3. The candidate registers and canary-promotes on evidence; holdout
       AuPR recovers within K windows; ZERO requests drop end to end.

    Headline number: **time_to_recovery_s** — drift first detected →
    candidate promoted (the unattended-loop latency a human used to
    be)."""
    import sys
    import tempfile
    import textwrap

    import numpy as np

    from transmogrifai_tpu import continual, lifecycle, serving
    from transmogrifai_tpu import server as server_mod
    from transmogrifai_tpu.evaluators.metrics import binary_metrics

    cap = int(os.environ.get("BENCH_HEAL_BUCKET_CAP", 256))
    train_rows = int(os.environ.get("BENCH_HEAL_TRAIN_ROWS", 4000))
    window = 1024

    gen_src = textwrap.dedent(f"""
        import numpy as np

        def gen(seed, n, shifted=False):
            rng = np.random.default_rng(seed)
            y = rng.integers(0, 2, n).astype(float)
            recs = []
            for i in range(n):
                base = float(0.8 * rng.normal() + 2.0 * y[i])
                x1 = (40.0 - base) if shifted else base
                recs.append({{"label": float(y[i]),
                             "x1": (None if rng.random() < 0.05 else x1),
                             "x2": float(rng.normal()),
                             "x3": float(rng.normal() + 0.2 * y[i])}})
            return recs

        def build(recs, seed=1):
            from transmogrifai_tpu import FeatureBuilder, Workflow
            from transmogrifai_tpu.filters.raw_feature_filter import \\
                RawFeatureFilter
            from transmogrifai_tpu.models.linear import \\
                LogisticRegressionFamily
            from transmogrifai_tpu.models.selector import \\
                BinaryClassificationModelSelector
            from transmogrifai_tpu.ops.transmogrifier import transmogrify
            label = (FeatureBuilder.RealNN("label").from_column()
                     .as_response())
            feats = [FeatureBuilder.Real(n).from_column().as_predictor()
                     for n in ("x1", "x2", "x3")]
            vec = transmogrify(feats)
            sel = BinaryClassificationModelSelector.with_cross_validation(
                num_folds=2, families=[LogisticRegressionFamily(
                    grid=[{{"regParam": 0.01, "elasticNetParam": 0.0}}])],
                splitter=None, seed=seed)
            pred = label.transform_with(sel, vec)
            return (Workflow().set_input_records(recs)
                    .with_raw_feature_filter(RawFeatureFilter(bins=50))
                    .set_result_features(pred))
    """)
    ns: dict = {}
    exec(gen_src, ns)
    gen, build = ns["gen"], ns["build"]

    work = tempfile.mkdtemp(prefix="tmog_heal_bench_")
    model = build(gen(17, train_rows)).train()
    mdir = os.path.join(work, "model_v0")
    edir = os.path.join(work, "export_v0")
    model.save(mdir)
    sample = gen(17, 16)
    serving.export_scoring_fn(model, edir, sample[:8], bucket_cap=cap)
    registry = lifecycle.ModelRegistry(os.path.join(work, "registry"))
    from transmogrifai_tpu.continual import _metric_of
    v0_aupr = _metric_of(model.summary(), "AuPR")
    vid0 = registry.register("heal", mdir, bank_dir=edir,
                             train_metrics={"AuPR": v0_aupr},
                             promote=True)
    model._engine_breaker().reset()

    trainer = os.path.join(work, "trainer.py")
    with open(trainer, "w") as fh:
        fh.write(gen_src + textwrap.dedent(f"""
            import json, os
            from transmogrifai_tpu import continual, serving

            out = os.environ["TMOG_RETRAIN_OUT"]
            stable = os.environ.get("TMOG_RETRAIN_STABLE") or None
            recs = gen(18, {train_rows}, shifted=True)
            wf = build(recs, seed=2)
            warm = continual.load_warm_stats(stable)
            wf.with_warm_fit_stats(warm)
            model = wf.train()
            model.save(os.path.join(out, "model"))
            serving.export_scoring_fn(model, os.path.join(out, "export"),
                                      recs[:8], bucket_cap={cap})
            doc = model.summary()
            doc["warmStarted"] = bool(warm)
            with open(os.path.join(out, "metrics.json"), "w") as mfh:
                json.dump(doc, mfh, default=str)
        """))

    srv = server_mod.ModelServer(bucket_cap=cap, batch_deadline_s=0.0,
                                 registry=registry, drift_window=window)
    srv.register_from_registry("heal")
    srv.score("heal", sample[:8], timeout_s=600)
    ctrl = continual.RetrainController(
        "heal", registry, [sys.executable, trainer], server=srv,
        job_dir=os.path.join(work, "jobs"),
        arm_windows=2, cooldown_s=3600.0, max_failures=2,
        timeout_s=600.0, heartbeat_timeout_s=600.0,
        deploy_mode="canary", canary_fraction=0.3,
        window_requests=16, promote_windows=2,
        holdout_metric="AuPR", holdout_tolerance=0.3).attach()

    def _prob_of(store):
        for n in store.names():
            col = store[n]
            if hasattr(col, "probability"):
                p = np.asarray(col.probability)
                return p[:, 1] if p.ndim == 2 and p.shape[1] >= 2 \
                    else np.asarray(col.prediction, float)
        raise AssertionError("no prediction column")

    def _aupr(y, s):
        y, s = np.asarray(y), np.asarray(s)
        return binary_metrics(y, (s > 0.5).astype(float), s)["AuPR"]

    shifted = gen(99, 16384, shifted=True)
    batch = 32
    labels: list = []
    probs: list = []
    submitted = answered = 0
    t0 = time.perf_counter()
    t_drift = t_job = t_promote = None
    deadline = t0 + float(os.environ.get("BENCH_HEAL_SECONDS", 420))
    i = 0
    while time.perf_counter() < deadline:
        lo = (i * batch) % (len(shifted) - batch)
        recs = shifted[lo:lo + batch]
        res = srv.score("heal", recs, timeout_s=600)
        submitted += 1
        answered += bool(res.rows == batch)
        labels.extend(r["label"] for r in recs)
        probs.extend(_prob_of(res.store))
        i += 1
        srv.drain_drift()
        st = srv.stats()["models"]["heal"]["drift"]
        if t_drift is None and st and st["advisories"]:
            t_drift = time.perf_counter()
        if t_job is None and ctrl.jobs():
            t_job = time.perf_counter()
        if registry.current("heal") != vid0:
            t_promote = time.perf_counter()
            break
    promoted = t_promote is not None
    rows_at_promote = len(labels)
    # traffic keeps flowing on the promoted model: the recovery windows
    post_labels: list = []
    post_probs: list = []
    for k in range(48):
        lo = (k * batch) % (len(shifted) - batch)
        recs = shifted[lo:lo + batch]
        res = srv.score("heal", recs, timeout_s=600)
        submitted += 1
        answered += bool(res.rows == batch)
        post_labels.extend(r["label"] for r in recs)
        post_probs.extend(_prob_of(res.store))
    srv.shutdown(drain=True)
    job = ctrl.jobs()[-1] if ctrl.jobs() else None
    rec = (registry.record("heal", job["version"])
           if job and job.get("version") else None)
    n_before = min(rows_at_promote, 512)
    aupr_before = _aupr(labels[:n_before], probs[:n_before]) \
        if n_before else None
    aupr_after = _aupr(post_labels, post_probs) if post_labels else None
    recovered = bool(aupr_after is not None and aupr_before is not None
                     and aupr_after > max(aupr_before, 0.7))
    out = {
        "train_rows": train_rows, "window_rows": window,
        "bucket_cap": cap, "stable_aupr": v0_aupr,
        "drift_detected_s": (round(t_drift - t0, 3) if t_drift else None),
        "job_started_s": (round(t_job - t0, 3) if t_job else None),
        "promoted_s": (round(t_promote - t0, 3) if promoted else None),
        # the headline: how long the loop took to heal itself once the
        # stream drifted — detection → promoted candidate serving
        "time_to_recovery_s": (round(t_promote - t_drift, 3)
                               if promoted and t_drift else None),
        "job_state": job["state"] if job else None,
        "warm_started": bool(rec and (rec.get("trainMetrics") or {})
                             .get("warmStarted")),
        "aupr_under_drift": (round(aupr_before, 4)
                             if aupr_before is not None else None),
        "aupr_after_promote": (round(aupr_after, 4)
                               if aupr_after is not None else None),
        "requests": submitted, "answered": answered,
        "dropped": submitted - answered,
        "controller": ctrl.status(),
    }
    out["pass"] = bool(t_drift is not None and promoted
                       and out["dropped"] == 0 and out["warm_started"]
                       and recovered)
    return out


def _fleet_resilience() -> dict:
    """Horizontal serving fleet benchmark (fleet.py, docs/fleet.md):

    1. **Scaling** — router throughput at 1 vs N workers over the SAME
       shared registry + AOT bank: requests/s, rows/s and
       ``scaling_efficiency = rate_N / (N * rate_1)``.
    2. **Chaos** — SIGKILL one worker mid-load: recovery time (kill →
       the respawned worker probes READY again), the client-observed
       failed-request count (must be 0 — sibling failover absorbs the
       in-flight loss within the router's retry budget), post-respawn
       throughput, and a fresh check that the registry CURRENT pointer
       survived the kill unmoved.
    """
    import http.client
    import tempfile
    import threading

    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values, serving)
    from transmogrifai_tpu import fleet as fleet_mod
    from transmogrifai_tpu.lifecycle import ModelRegistry
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft

    cap = int(os.environ.get("BENCH_FLEET_BUCKET_CAP", 256))
    # >= 2 workers ALWAYS: the chaos phase SIGKILLs one, and failover
    # needs a sibling — a 1-worker "fleet" would report a guaranteed
    # failure that says nothing about the failover contract
    n_fleet = max(2, int(os.environ.get("BENCH_FLEET_WORKERS",
                                        min(3, os.cpu_count() or 2))))
    load_s = float(os.environ.get("BENCH_FLEET_SECONDS", 3.0))
    train_rows = 10_000
    rng = np.random.default_rng(23)
    y = rng.integers(0, 2, train_rows).astype(float)
    xs = {f"x{j}": rng.normal(size=train_rows) + (0.3 * j) * y
          for j in range(4)}
    cols = {"label": column_from_values(ft.RealNN, y)}
    for k, v in xs.items():
        cols[k] = column_from_values(ft.Real, list(v))
    store = ColumnStore(cols, train_rows)
    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
             for j in range(4)]
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=7)
    pred = label.transform_with(selector, transmogrify(feats))
    model = (Workflow().set_input_store(store)
             .set_result_features(pred).train())
    records = [{"label": float(y[i]),
                **{f"x{j}": float(xs[f"x{j}"][i]) for j in range(4)}}
               for i in range(1024)]

    work = tempfile.mkdtemp(prefix="tmog_fleet_bench_")
    model_dir = os.path.join(work, "model")
    export_dir = os.path.join(work, "export")
    model.save(model_dir)
    serving.export_scoring_fn(model, export_dir, records[:8],
                              bucket_cap=cap)
    reg_dir = os.path.join(work, "registry")
    registry = ModelRegistry(reg_dir)
    vid = registry.register("m", model_dir, bank_dir=export_dir,
                            promote=True)
    params_path = os.path.join(work, "params.json")
    with open(params_path, "w") as fh:
        json.dump({"customParams": {
            "registryDir": reg_dir, "serveBucketCap": cap,
            "serveBatchDeadlineMs": 1.0, "validate": False,
            "plan": False}}, fh)

    fleet_before = fleet_mod.fleet_stats()
    out: dict = {"workers": n_fleet, "bucket_cap": cap,
                 "load_s": load_s, "version": vid}

    def pump(port: int, seconds: float, n_clients: int = 4) -> dict:
        """Closed-loop client threads against the router; every non-200
        answer counts as a failed request."""
        ok = [0] * n_clients
        fail = [0] * n_clients
        rows = [0] * n_clients
        stop_at = time.perf_counter() + seconds

        def client(k: int) -> None:
            crng = np.random.default_rng(300 + k)
            while time.perf_counter() < stop_at:
                lo = int(crng.integers(0, len(records) - 8))
                body = json.dumps({"records": records[lo:lo + 8]})
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=60)
                    conn.request("POST", "/v1/models/m:score", body,
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    resp.read()
                    status = resp.status
                    conn.close()
                except OSError:
                    status = 599
                if status == 200:
                    ok[k] += 1
                    rows[k] += 8
                else:
                    fail[k] += 1

        threads = [threading.Thread(target=client, args=(k,),
                                    name=f"fleet-bench-client-{k}",
                                    daemon=True)
                   for k in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=seconds * 4 + 120)
        wall = time.perf_counter() - t0
        return {"requests": sum(ok), "failed": sum(fail),
                "requests_per_s": round(sum(ok) / wall, 1),
                "rows_per_s": round(sum(rows) / wall, 1)}

    def run_fleet(n: int):
        sup = fleet_mod.FleetSupervisor(params_path, workers=n,
                                        respawn_max=4,
                                        probe_interval_s=0.1)
        sup.start()
        sup.wait_ready(timeout_s=300)
        httpd = fleet_mod.serve_fleet_http(sup, port=0, retry_budget=2)
        return sup, httpd, httpd.server_address[1]

    # -- 1. scaling: 1 worker vs N -----------------------------------------
    sup, httpd, port = run_fleet(1)
    try:
        pump(port, 0.5)                         # warmup: banks touched
        out["one_worker"] = pump(port, load_s)
    finally:
        httpd.shutdown()
        sup.stop(drain=True)
    sup, httpd, port = run_fleet(n_fleet)
    try:
        pump(port, 0.5)
        out["n_workers"] = pump(port, load_s)
        r1 = max(out["one_worker"]["requests_per_s"], 1e-9)
        out["scaling_efficiency"] = round(
            out["n_workers"]["requests_per_s"] / (n_fleet * r1), 3)

        # -- 2. chaos: SIGKILL one worker under sustained load -------------
        victim = sup.workers[0]
        spawns_before = victim.spawns
        res_box: dict = {}

        def chaos_load() -> None:
            res_box["load"] = pump(port, load_s * 2, n_clients=4)

        loader = threading.Thread(target=chaos_load,
                                  name="fleet-bench-chaos-load",
                                  daemon=True)
        loader.start()
        time.sleep(load_s * 0.3)
        t_kill = time.perf_counter()
        victim.proc.kill()                      # SIGKILL: a real crash
        while victim.spawns == spawns_before \
                or victim.state != fleet_mod.READY:
            if time.perf_counter() - t_kill > 240:
                break
            time.sleep(0.05)
        recovery_s = time.perf_counter() - t_kill
        loader.join(timeout=load_s * 8 + 240)
        out["chaos"] = {
            **res_box.get("load", {}),
            "recovery_s": round(recovery_s, 3),
            "respawned": bool(victim.state == fleet_mod.READY),
            "pointer_intact": registry.current("m") == vid,
        }
        out["post_respawn"] = pump(port, load_s)
        out["chaos"]["pass"] = bool(
            out["chaos"].get("failed") == 0
            and out["chaos"]["respawned"]
            and out["chaos"]["pointer_intact"]
            and out["post_respawn"]["requests"] > 0)
    finally:
        httpd.shutdown()
        sup.stop(drain=True)
    d = fleet_mod.fleet_stats()
    out["fleet_delta"] = {
        k: v - fleet_before.get(k, 0) for k, v in d.items()
        if isinstance(v, (int, float))
        and isinstance(fleet_before.get(k), (int, float))}
    out["pass"] = bool(out.get("chaos", {}).get("pass"))
    return out


def _fit_stats() -> dict:
    """Fit-path statistics engine benchmark: ONE wide DAG layer of
    opted-in estimators (mean imputers + pivots + a bucketizer over the
    same synthetic store) trained with the fused fit-statistics pass
    (fitstats.py) vs the sequential per-stage loop. Reports the train
    wall-clock and the data-prep split of both modes plus the pass-count
    math the engine is about: k estimators = k full scans sequentially,
    exactly 1 fused."""
    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values, fitstats)
    from transmogrifai_tpu.types import feature_types as ft

    rows = int(os.environ.get("BENCH_FITSTATS_ROWS", 1_000_000))
    n_num = 6
    rng = np.random.default_rng(17)
    t0 = time.time()
    cols = {}
    for j in range(n_num):
        v = rng.normal(size=rows) * (j + 1)
        v[rng.random(rows) < 0.1] = np.nan
        cols[f"x{j}"] = column_from_values(ft.Real, v)
    cat_pool = np.array([f"c{i}" for i in range(24)] + [None],
                        dtype=object)
    for j in range(2):
        cols[f"cat{j}"] = column_from_values(
            ft.PickList, list(cat_pool[rng.integers(0, 25, rows)]))
    store = ColumnStore(cols, rows)
    prep_s = time.time() - t0

    def build():
        feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
                 for j in range(n_num)]
        cats = [FeatureBuilder.PickList(f"cat{j}").from_column()
                .as_predictor() for j in range(2)]
        outs = [f.fill_missing_with_mean() for f in feats[:3]]
        outs += [f.z_normalize() for f in feats[3:5]]
        outs += [feats[5].bucketize(num_buckets=6)]
        outs += [c.pivot(top_k=10) for c in cats]
        return outs

    def train(fused: bool):
        old = fitstats.FITSTATS_ENABLED
        fitstats.FITSTATS_ENABLED = fused
        before = fitstats.fitstats_stats()
        try:
            t1 = time.time()
            Workflow().set_input_store(store) \
                .set_result_features(*build()).train()
            dt = time.time() - t1
        finally:
            fitstats.FITSTATS_ENABLED = old
        after = fitstats.fitstats_stats()
        return dt, {k: after[k] - before[k] for k in after}

    # untimed warmup compiles the transform-layer AND fitstats fold
    # programs, so neither timed mode inherits the other's compile
    # amortization (A/B discipline, docs/performance.md gotchas)
    train(fused=True)
    seq_s, _ = train(fused=False)
    fused_s, delta = train(fused=True)
    n_opted = 8                  # 3 mean + 2 norm + 1 bucketize + 2 pivot
    return {
        "rows": rows,
        "opted_in_estimators": n_opted,
        "data_prep_s": round(prep_s, 2),
        "sequential": {"train_s": round(seq_s, 2),
                       "fit_passes_per_layer": n_opted},
        "fused": {"train_s": round(fused_s, 2),
                  "fit_passes_per_layer": delta["layers_fused"],
                  "passes_saved": delta["passes_saved"],
                  "bytes_scanned_mb": round(
                      delta["bytes_scanned"] / 1e6, 1),
                  "device_passes": delta["device_passes"],
                  "host_passes": delta["host_passes"]},
        "speedup": round(seq_s / fused_s, 2) if fused_s > 0 else None,
    }


def _planner() -> dict:
    """Whole-DAG planner benchmark (planner.py): ONE fitted workflow
    carrying a duplicated vectorizer (CSE bait) and a pruning sanity
    checker, scored planned (CSE fan-out + dead-column pruning + the
    measured tier decision from a cost db) vs gate-only. Reports both
    rows/s, the plan's stats (pruned columns, CSE merges, per-tier
    stage counts) and a strict bit-parity flag — the planner must
    change cost, never results."""
    import statistics as _stats
    import tempfile

    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values, planner)
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import fusion_state

    rows = int(os.environ.get("BENCH_PLANNER_ROWS", 200_000))
    train_rows = min(20_000, rows)
    rng = np.random.default_rng(29)
    y = rng.integers(0, 2, rows).astype(float)
    xs = {f"x{j}": rng.normal(size=rows) + (0.3 * j) * y for j in range(5)}
    junk = np.zeros(rows)                      # sanity checker drops it
    cats = np.array(["a", "b", "c", "d", None], dtype=object)[
        rng.integers(0, 5, rows)]

    def store_of(sl):
        cols = {"label": column_from_values(ft.RealNN, y[sl])}
        for k, v in xs.items():
            cols[k] = column_from_values(ft.Real, list(v[sl]))
        cols["junk"] = column_from_values(ft.Real, list(junk[sl]))
        cols["cat"] = column_from_values(ft.PickList, list(cats[sl]))
        return ColumnStore(cols, len(y[sl]))

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"x{j}").from_column().as_predictor()
             for j in range(5)]
    feats.append(FeatureBuilder.Real("junk").from_column().as_predictor())
    fcat = FeatureBuilder.PickList("cat").from_column().as_predictor()
    # two structurally identical pivots over the same feature: CSE bait
    vec = transmogrify(feats + [fcat.pivot(), fcat.pivot()])
    checked = label.sanity_check(vec, remove_bad_features=True)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=5)
    pred = label.transform_with(selector, checked)
    model = (Workflow().set_input_store(store_of(slice(0, train_rows)))
             .set_result_features(pred).train())
    full = store_of(slice(0, rows))

    with tempfile.TemporaryDirectory() as td:
        db = planner.CostDatabase.load(os.path.join(td, "cost_db.json"))
        planner.record_fit_costs(model, db)
        db.save()
        plan = model.plan(cost_db=db, attach=False)
        out: dict = {"rows": rows, "fusion_gate": fusion_state(),
                     "plan": plan.counts(),
                     "report_bytes": len(plan.report())}

        def _rate(fn, reps=2):
            fn()                               # warm-up (compile) pass
            secs = []
            for _ in range(reps):
                t0 = time.time()
                fn()
                secs.append(time.time() - t0)
            return rows / _stats.median(secs)

        eng_plain = model.scoring_engine(plan=None)
        if eng_plain is None or not eng_plain.enabled():
            out["status"] = ("engine_gated_off: link below "
                             "FUSE_MIN_BANDWIDTH_MBPS")
            return out
        r_host = _rate(lambda: model.score(full, engine=False), reps=1)
        r_plain = _rate(lambda: eng_plain.score_store(full,
                                                      use_cache=False))
        model.attach_plan(plan)
        eng_planned = model.scoring_engine()
        r_planned = _rate(lambda: eng_planned.score_store(full,
                                                          use_cache=False))
        # BOTH whole-chain halves feed the persisted db — the NEXT
        # process's plan decides the engine tier from measurements
        # (planner._engine_tier needs host AND engine cost), and the
        # fit's drained phase observations complete the per-phase tiers
        db.record_chain(host_rows_per_s=r_host,
                        engine_rows_per_s=r_plain)
        planner.drain_phase_observations(db)
        db.save()
        replanned = planner.plan_model(model, cost_db=db)
        out["next_process_engine_tier"] = replanned.engine_tier
        s_plain = eng_plain.score_store(full)
        s_planned = eng_planned.score_store(full)
        nm = [n for n in s_plain.names()][0]
        parity = bool(
            np.array_equal(s_plain[nm].prediction,
                           s_planned[nm].prediction)
            and np.array_equal(s_plain[nm].probability,
                               s_planned[nm].probability))
        out.update({
            "host_rows_per_s": round(r_host),
            "unplanned_rows_per_s": round(r_plain),
            "planned_rows_per_s": round(r_planned),
            "planned_speedup": round(r_planned / r_plain, 3),
            "parity": parity,
        })
    return out


def _multichip_scaling() -> dict:
    """Multichip-promotion proof (ROADMAP #1): the SAME fitstats fold
    pass, CV sweep and engine-scoring batch run at 1 device and at all N
    visible devices via the process mesh, reporting rows/s per leg and a
    ``scaling_efficiency`` ratio (rate_N / (N × rate_1); near-linear ≥
    0.7). The CV leg additionally asserts the sharded sweep picks the
    SAME winner with the SAME cv_metric as the single-device run — the
    mesh must buy throughput, never answers."""
    import statistics as _stats

    import jax
    import numpy as np

    from transmogrifai_tpu import (ColumnStore, FeatureBuilder, Workflow,
                                   column_from_values)
    from transmogrifai_tpu.fitstats import LayerStatsPlan, StatRequest
    from transmogrifai_tpu.models.linear import LogisticRegressionFamily
    from transmogrifai_tpu.models.selector import \
        BinaryClassificationModelSelector
    from transmogrifai_tpu.models.tuning import CrossValidation
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.parallel import mesh as pmesh
    from transmogrifai_tpu.types import feature_types as ft

    n_dev = len(jax.devices())
    out: dict = {"n_devices": n_dev,
                 "mesh": pmesh.mesh_topology()}
    if n_dev < 2:
        out["status"] = "skipped_single_device"
        return out
    mesh1 = pmesh.make_mesh(n_devices=1)       # degenerate 1×1
    meshN = pmesh.process_default_mesh()

    def _rate(fn, rows, reps=3):
        fn()                                   # warm-up (compile) pass
        secs = []
        for _ in range(reps):
            t0 = time.time()
            fn()
            secs.append(time.time() - t0)
        return rows / _stats.median(secs)

    rng = np.random.default_rng(23)

    # -- fitstats fold pass: rows/s of the device stats tier ------------
    fs_rows = int(os.environ.get("BENCH_MESH_FITSTATS_ROWS", 2_000_000))
    k = 8
    store = ColumnStore(
        {f"x{j}": column_from_values(ft.Real,
                                     rng.normal(size=fs_rows) * (j + 1))
         for j in range(k)}, fs_rows)
    plan = LayerStatsPlan(
        [StatRequest(kind, f"x{j}") for j in range(k)
         for kind in ("count", "mean", "variance", "min", "max")],
        n_stages=k)
    r1 = _rate(lambda: plan.run(store, device=True, mesh=mesh1), fs_rows)
    rN = _rate(lambda: plan.run(store, device=True, mesh=meshN), fs_rows)
    out["fitstats"] = {
        "rows": fs_rows,
        "rows_per_s_1dev": round(r1), "rows_per_s_ndev": round(rN),
        "scaling_efficiency": round(rN / (n_dev * r1), 3)}

    # -- CV sweep: sharded run must reproduce the single-device answer --
    cv_rows = int(os.environ.get("BENCH_MESH_CV_ROWS", 200_000))
    y = rng.integers(0, 2, cv_rows).astype(float)
    X = rng.normal(size=(cv_rows, 12))
    X[:, :4] += 0.4 * y[:, None]
    grid = [{"regParam": r, "elasticNetParam": 0.0}
            for r in (0.0, 0.01, 0.1, 0.3)]

    def sweep(mesh):
        cv = CrossValidation(num_folds=3, metric_name="AuROC",
                             task="binary", seed=7)
        return cv.validate([LogisticRegressionFamily(grid=list(grid))],
                           X, y, mesh=mesh)
    t0 = time.time()
    _f1, hp1, summ1 = sweep(mesh1)
    cv_s_1 = time.time() - t0
    t0 = time.time()
    _fN, hpN, summN = sweep(meshN)
    cv_s_n = time.time() - t0
    m1 = summ1.best.mean_metric
    mN = summN.best.mean_metric
    out["cv"] = {
        "rows": cv_rows, "s_1dev": round(cv_s_1, 3),
        "s_ndev": round(cv_s_n, 3),
        "winner_1dev": summ1.best.family_name,
        "winner_ndev": summN.best.family_name,
        "winner_match": summ1.best.family_name == summN.best.family_name,
        "best_params_match": hp1 == hpN,
        "cv_metric_1dev": m1, "cv_metric_ndev": mN,
        "cv_metric_match": bool(m1 == mN
                                or abs(m1 - mN) <= 1e-6 * max(1.0, abs(m1)))}

    # -- engine scoring: data-sharded bucket dispatch -------------------
    sc_rows = int(os.environ.get("BENCH_MESH_SCORE_ROWS", 200_000))
    ys = rng.integers(0, 2, sc_rows).astype(float)
    xs = {f"s{j}": rng.normal(size=sc_rows) + 0.3 * j * ys
          for j in range(6)}

    def store_of(sl):
        cols = {"label": column_from_values(ft.RealNN, ys[sl])}
        for kk, v in xs.items():
            cols[kk] = column_from_values(ft.Real, list(v[sl]))
        return ColumnStore(cols, len(ys[sl]))

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = [FeatureBuilder.Real(f"s{j}").from_column().as_predictor()
             for j in range(6)]
    vec = transmogrify(feats)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, families=[LogisticRegressionFamily(
            grid=[{"regParam": 0.01, "elasticNetParam": 0.0}])],
        splitter=None, seed=5)
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_store(store_of(slice(0, 20_000)))
             .set_result_features(pred).train())
    full = store_of(slice(0, sc_rows))
    eng1 = model.scoring_engine(mesh=mesh1)
    engN = model.scoring_engine(mesh=meshN)
    if eng1 is None or engN is None or not eng1.enabled():
        out["engine"] = ("unavailable" if eng1 is None or engN is None
                         else "gated_off: link below "
                              "FUSE_MIN_BANDWIDTH_MBPS")
    else:
        prep1 = eng1.prepare_batch(full)
        prepN = engN.prepare_batch(full)
        e1 = _rate(lambda: eng1.run_batch(prep1), sc_rows)
        eN = _rate(lambda: engN.run_batch(prepN), sc_rows)
        out["engine"] = {
            "rows": sc_rows,
            "rows_per_s_1dev": round(e1), "rows_per_s_ndev": round(eN),
            "scaling_efficiency": round(eN / (n_dev * e1), 3)}

    eff = [out["fitstats"]["scaling_efficiency"]]
    if isinstance(out.get("engine"), dict):
        eff.append(out["engine"]["scaling_efficiency"])
    out["pass"] = bool(all(e >= 0.7 for e in eff)
                       and out["cv"]["cv_metric_match"]
                       and out["cv"]["winner_match"]
                       and out["cv"]["best_params_match"])
    return out


def main() -> None:
    import jax

    os.makedirs("/tmp/transmogrifai_jax_cache", exist_ok=True)
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/transmogrifai_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    backend = jax.default_backend()
    if backend == "tpu":
        # overlap the one-time Pallas probe compile (~10-15 s over a
        # tunnelled compile service) with the first config's data load
        from transmogrifai_tpu.models._pallas_hist import warm_probe_async
        warm_probe_async()
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "examples"))
    bench = Bench()
    doc = bench.doc
    doc["backend"] = backend
    doc["n_devices"] = len(jax.devices())
    # the process mesh every heavy phase shards over (PR 6: multichip is
    # the mainline substrate — every benched number states its topology)
    from transmogrifai_tpu.parallel.mesh import mesh_topology
    doc["mesh"] = mesh_topology()
    configs = doc["configs"]
    reps = int(os.environ.get("BENCH_WARM_REPS", 3))

    # 1. Titanic (headline parity config)
    from titanic import run as run_titanic
    cold, warm, st = bench.run_config(
        "titanic", lambda: run_titanic(num_folds=3, seed=42), reps=reps)
    holdout = warm["summary"].holdout_evaluation or {}
    aupr = float(holdout.get("AuPR", 0.0))
    configs["titanic"] = {
        "AuPR": round(aupr, 4),
        "vs_reference": round(aupr / REFERENCE_AUPR, 4),
        **_std_config(warm, cold, st),
    }
    doc["value"] = configs["titanic"]["AuPR"]
    doc["vs_baseline"] = round(aupr / REFERENCE_AUPR, 4)
    doc["cv_wallclock_s"] = configs["titanic"]["cv_warm_s"]
    doc["cv_cold_s"] = configs["titanic"]["cv_cold_s"]
    bench.emit()

    # 2. Iris multiclass (string labels round-trip)
    # configs 2-4 record a structured error instead of killing the
    # round (the evidence discipline): a host without the reference
    # checkout's datasets still produces every synthetic config below
    try:
        from iris import run as run_iris
        cold, warm, st = bench.run_config(
            "iris", lambda: run_iris(num_folds=3, seed=42), reps=reps)
        configs["iris"] = {
            "F1": round(float(warm["metrics"]["F1"]), 4),
            **_std_config(warm, cold, st),
        }
    except Exception as e:
        _log(f"[bench] iris failed: {e!r}")
        configs["iris"] = {"error": repr(e)[:400]}
    bench.emit()

    # 3. Boston regression
    try:
        from boston import run as run_boston
        cold, warm, st = bench.run_config(
            "boston", lambda: run_boston(num_folds=3, seed=42), reps=reps)
        configs["boston"] = {
            "RMSE": round(float(warm["metrics"]["RootMeanSquaredError"]),
                          4),
            "R2": round(float(warm["metrics"]["R2"]), 4),
            **_std_config(warm, cold, st),
        }
    except Exception as e:
        _log(f"[bench] boston failed: {e!r}")
        configs["boston"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4. SmartText-heavy (BigPassenger schema at scale — 300k rows per
    #    VERDICT r3 #4: host text prep + the fusion decision measured at
    #    non-toy size). Shrinks to 100k if the budget is already tight.
    big_rows = int(os.environ.get("BENCH_TEXT_ROWS", 300_000))
    if bench.remaining() < 180 and big_rows > 100_000:
        _log(f"[bench] budget tight ({bench.remaining():.0f}s left): "
             f"big_text shrinks to 100k rows")
        big_rows = 100_000
    try:
        from big_passenger import run as run_big
        from big_passenger import TARGET_AUPR
        cold, warm, st = bench.run_config(
            "big_text",
            lambda: run_big(n_rows=big_rows, num_folds=3, seed=42),
            reps=1)
        big_aupr = float(warm["metrics"]["AuPR"])
        configs["big_text"] = {
            "rows": big_rows,
            "AuPR": round(big_aupr, 4),
            "target_AuPR": TARGET_AUPR,
            "quality": "PASS" if big_aupr >= TARGET_AUPR else "FAIL",
            "cv_warm_s": round(warm["train_time_s"], 2),
            "whole_run_warm_s": st["warm_s_median"],
            "cv_cold_s": round(cold["train_time_s"], 2),
            "compile_clock_s": st["compile_clock_s"],
            "phases": warm.get("phases"),
            **_mfu_fields(st["warm_flops"], warm["train_time_s"]),
        }
    except Exception as e:
        _log(f"[bench] big_text failed: {e!r}")
        configs["big_text"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b. Scoring throughput (serving path): rows/s of the compiled
    #     batched scoring engine and the overlapped streaming mode vs the
    #     per-layer reference path, on a synthetic LR workflow. Optional
    #     stage: budget-gated like the 10M pass (the training cost is a
    #     small fixed 20k-row fit; measurement is pure scoring).
    if bench.remaining() < 120:
        configs["scoring_throughput"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] scoring_throughput skipped: remaining "
             f"{bench.remaining():.0f}s < 120s")
    else:
        try:
            configs["scoring_throughput"] = _scoring_throughput()
        except Exception as e:
            _log(f"[bench] scoring_throughput failed: {e!r}")
            configs["scoring_throughput"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b1b. Input pipeline (the tf.data-analog proof): serial vs
    #       pipelined decode→score ingest at 1/2/4 workers over a
    #       directory of Avro micro-batches, with overlap_efficiency,
    #       the converged prefetch depth and a ≥2×-serial + gate-ON
    #       pass flag. Budget-gated like its siblings.
    if bench.remaining() < 120:
        configs["input_pipeline"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] input_pipeline skipped: remaining "
             f"{bench.remaining():.0f}s < 120s")
    else:
        try:
            configs["input_pipeline"] = _input_pipeline()
        except Exception as e:
            _log(f"[bench] input_pipeline failed: {e!r}")
            configs["input_pipeline"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b1c. Event-log temporal workload (the reader-tier proof): a
    #       seeded two-stream transactions+users log joined and
    #       point-in-time aggregated against a cutoff — serial row-wise
    #       vs columnar vs columnar+workers, headline join+aggregate
    #       rows/s with a ≥5×-serial + bit-parity pass flag. Pure host
    #       work (numpy + worker threads): cheap, budget-gated anyway.
    if bench.remaining() < 90:
        configs["event_log"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] event_log skipped: remaining "
             f"{bench.remaining():.0f}s < 90s")
    else:
        try:
            configs["event_log"] = _event_log()
        except Exception as e:
            _log(f"[bench] event_log failed: {e!r}")
            configs["event_log"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b1d. Wide-sparse tree workload (the PR 14 matrix-shape proof):
    #       hundreds of mostly-zero indicator columns trained with
    #       sparsity-aware 2-bin binning (+ the sparse01 kernel on the
    #       kernel path) vs naive full-width quantile binning —
    #       headline rows/s, pass = ≥2× at matched holdout AuPR.
    if bench.remaining() < 240:
        configs["wide_sparse"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] wide_sparse skipped: remaining "
             f"{bench.remaining():.0f}s < 240s")
    else:
        try:
            configs["wide_sparse"] = _wide_sparse()
        except Exception as e:
            _log(f"[bench] wide_sparse failed: {e!r}")
            configs["wide_sparse"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b1e. Out-of-core streaming fit (PR 16): a synthetic avro event
    #       log larger than the declared host-memory budget trains
    #       end-to-end under a setrlimit-enforced RSS cap in a
    #       subprocess, at holdout parity with the uncapped in-memory
    #       fit. Budget-gated: two interpreter spawns + dataset
    #       generation (~70 s measured on the CPU host).
    if bench.remaining() < 150:
        configs["out_of_core"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] out_of_core skipped: remaining "
             f"{bench.remaining():.0f}s < 150s")
    else:
        try:
            configs["out_of_core"] = _out_of_core()
        except Exception as e:
            _log(f"[bench] out_of_core failed: {e!r}")
            configs["out_of_core"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b2. Serving latency (the AOT bank + model server proof):
    #      cold-process first-request latency with vs without the
    #      program bank (subprocess — honest cold), steady-state
    #      p50/p99 under Poisson-ish load at two batching deadlines.
    #      Budget-gated: two interpreter spawns dominate its cost.
    if bench.remaining() < 180:
        configs["serving_latency"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] serving_latency skipped: remaining "
             f"{bench.remaining():.0f}s < 180s")
    else:
        try:
            configs["serving_latency"] = _serving_latency()
        except Exception as e:
            _log(f"[bench] serving_latency failed: {e!r}")
            configs["serving_latency"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b2b. Tracing overhead (the observability-plane gate): full
    #      tracing — telemetry recording, per-request minted trace
    #      contexts + request spans, batch span links, decomposition
    #      histograms — vs tracing off over the same serving stream;
    #      interleaved paired legs, pass = median overhead < 5%. Runs BEFORE the
    #      lifecycle/fleet/continual configs: those spawn persistent
    #      sentinel/monitor/retrain threads whose GIL share rides on
    #      top of BOTH legs but noisily — a 5%-scale signal needs the
    #      quietest process state the round can offer.
    if bench.remaining() < 150:
        configs["trace_overhead"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] trace_overhead skipped: remaining "
             f"{bench.remaining():.0f}s < 150s")
    else:
        try:
            configs["trace_overhead"] = _trace_overhead()
        except Exception as e:
            _log(f"[bench] trace_overhead failed: {e!r}")
            configs["trace_overhead"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b2c. Workload capture & replay (the flight-recorder gate):
    #      record a fleet run, merge the shards, replay at 1x against a
    #      second fleet — score parity + per-phase agreement — with the
    #      recorder's overhead paired-measured < 5% and the critical-
    #      path analyzer attributing >= 95% of every request's e2e.
    #      Budget-gated: boots two 1-worker fleets.
    if bench.remaining() < 240:
        configs["workload_replay"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] workload_replay skipped: remaining "
             f"{bench.remaining():.0f}s < 240s")
    else:
        try:
            configs["workload_replay"] = _workload_replay()
        except Exception as e:
            _log(f"[bench] workload_replay failed: {e!r}")
            configs["workload_replay"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b2d. Self-tuning runtime (the declared-knob autotuner gate):
    #      record a workload, coordinate-descent tune two knobs offline
    #      (tuned config must not lose to
    #      the default, parity on every ranked leg), then drive the
    #      online deadline controller through a shifted arrival process
    #      (windows close, bounds hold, zero failures).
    #      Budget-gated: boots a server per tuner candidate.
    if bench.remaining() < 180:
        configs["autotune"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] autotune skipped: remaining "
             f"{bench.remaining():.0f}s < 180s")
    else:
        try:
            configs["autotune"] = _autotune()
        except Exception as e:
            _log(f"[bench] autotune failed: {e!r}")
            configs["autotune"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b3. Model lifecycle (the registry + drift sentinel + canary
    #      rollout proof): sentinel overhead off vs on (< 5% to pass),
    #      drift detection within one window on a shifted stream, and a
    #      canary→promote switchover with zero dropped requests.
    #      Budget-gated: trains two model versions.
    if bench.remaining() < 150:
        configs["drift_canary"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] drift_canary skipped: remaining "
             f"{bench.remaining():.0f}s < 150s")
    else:
        try:
            configs["drift_canary"] = _drift_canary()
        except Exception as e:
            _log(f"[bench] drift_canary failed: {e!r}")
            configs["drift_canary"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b4. Fleet resilience (the horizontal serving tier proof):
    #      throughput at 1 vs N workers (scaling efficiency), then
    #      SIGKILL one worker mid-load — recovery time, zero failed
    #      client requests beyond the retry budget, post-respawn
    #      throughput, registry pointer intact. Budget-gated: spawns
    #      1 + N + 1 worker interpreters.
    if bench.remaining() < 240:
        configs["fleet_resilience"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] fleet_resilience skipped: remaining "
             f"{bench.remaining():.0f}s < 240s")
    else:
        try:
            configs["fleet_resilience"] = _fleet_resilience()
        except Exception as e:
            _log(f"[bench] fleet_resilience failed: {e!r}")
            configs["fleet_resilience"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4b5. Self-healing loop (the continuous-training proof): a seeded
    #      covariate-shifted stream must trip TMG601, arm a supervised
    #      retrain job (warm-started from the persisted sufficient
    #      statistics), canary-promote the candidate on evidence, and
    #      recover AuPR — zero dropped requests; headline number is
    #      time_to_recovery_s (drift detected → promoted). Budget-
    #      gated: trains two models (one in a trainer subprocess).
    if bench.remaining() < 240:
        configs["self_healing"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] self_healing skipped: remaining "
             f"{bench.remaining():.0f}s < 240s")
    else:
        try:
            configs["self_healing"] = _self_healing()
        except Exception as e:
            _log(f"[bench] self_healing failed: {e!r}")
            configs["self_healing"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4c. Fit-statistics engine (fit path): one-pass-per-layer fused
    #     sufficient statistics vs the sequential per-stage loop on a
    #     wide synthetic layer. Budget-gated like scoring_throughput.
    if bench.remaining() < 100:
        configs["fit_stats"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] fit_stats skipped: remaining "
             f"{bench.remaining():.0f}s < 100s")
    else:
        try:
            configs["fit_stats"] = _fit_stats()
        except Exception as e:
            _log(f"[bench] fit_stats failed: {e!r}")
            configs["fit_stats"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4c2. Whole-DAG planner (the cost-based middle-end proof): planned
    #      (CSE + pruning + measured tier) vs gate-only scoring on one
    #      fitted workflow, with bit-parity asserted. Budget-gated.
    if bench.remaining() < 100:
        configs["planner"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] planner skipped: remaining "
             f"{bench.remaining():.0f}s < 100s")
    else:
        try:
            configs["planner"] = _planner()
        except Exception as e:
            _log(f"[bench] planner failed: {e!r}")
            configs["planner"] = {"error": repr(e)[:400]}
    bench.emit()

    # 4d. Multichip scaling (the mesh-promotion proof): fitstats pass,
    #     CV sweep and engine scoring at 1 vs N devices — rows/s,
    #     scaling_efficiency, and single-device answer parity. Budget-
    #     gated like the other optional stages; trivially skipped on a
    #     single chip.
    if len(jax.devices()) < 2:
        configs["multichip_scaling"] = {
            "status": "skipped_single_device",
            "n_devices": len(jax.devices())}
    elif bench.remaining() < 150:
        configs["multichip_scaling"] = {
            "status": "skipped_budget",
            "remaining_budget_s": round(bench.remaining(), 1)}
        _log(f"[bench] multichip_scaling skipped: remaining "
             f"{bench.remaining():.0f}s < 150s")
    else:
        try:
            configs["multichip_scaling"] = _multichip_scaling()
        except Exception as e:
            _log(f"[bench] multichip_scaling failed: {e!r}")
            configs["multichip_scaling"] = {"error": repr(e)[:400]}
    bench.emit()

    # 5. Synthetic tree grid at scale (the BASELINE scale config: default
    #    2M rows single-chip). The warm pass runs under jax.profiler.trace
    #    so device-busy MFU + top-ops come from the SAME pass (VERDICT r4
    #    #1: no third sweep).
    synth_rows = int(os.environ.get("BENCH_SYNTH_ROWS", 2_000_000))
    from synthetic_trees import run as run_synth
    trace_dir = "/tmp/jaxtrace_bench"
    do_profile = (os.environ.get("BENCH_PROFILE", "1") != "0"
                  and backend == "tpu")
    c0 = _compile_s()
    t0 = time.time()
    cold = run_synth(n_rows=synth_rows, num_folds=3, seed=42)
    cold_s = time.time() - t0
    synth_compile_s = _compile_s() - c0
    _log(f"[bench] synthetic_trees cold {cold_s:.1f}s "
         f"(compile clock {synth_compile_s:.1f}s)")
    # warm rep 1: CLEAN (the official cv_warm_s — profiler capture adds
    # measurable overhead at 2M)
    f0 = _flops_total()
    t1 = time.time()
    warm = run_synth(n_rows=synth_rows, num_folds=3, seed=42)
    warm_s = time.time() - t1
    warm_flops = _flops_total() - f0
    _log(f"[bench] synthetic_trees warm {warm_s:.1f}s "
         f"({warm_flops / 1e9:.1f} GFLOP dispatched)")
    configs["synthetic_trees"] = {
        "rows": synth_rows,
        "AuPR": round(float(warm["metrics"]["AuPR"]), 4),
        "cv_warm_s": round(warm["train_time_s"], 2),
        "cv_cold_s": round(cold["train_time_s"], 2),
        "compile_clock_s": round(synth_compile_s, 2),
        "best_model": warm["summary"].best_model_name,
        "phases": warm.get("phases"),
        **_mfu_fields(warm_flops, warm["train_time_s"]),
    }
    bench.emit()

    # warm rep 2 runs under jax.profiler.trace (device-busy MFU + top
    # ops); its wall clock is recorded separately so profiler overhead
    # never contaminates the headline — and it doubles as the second
    # warm rep for the variance record. Budget-gated.
    if do_profile and bench.remaining() < warm_s * 1.4 + 60:
        do_profile = False
        _log("[bench] profile pass skipped (budget)")
    warm_prof_s = None
    if do_profile:
        import shutil
        shutil.rmtree(trace_dir, ignore_errors=True)
        f0 = _flops_total()
        t1 = time.time()
        with jax.profiler.trace(trace_dir):
            warm2 = run_synth(n_rows=synth_rows, num_folds=3, seed=42)
        warm_prof_s = time.time() - t1
        warm_flops = _flops_total() - f0
        _log(f"[bench] synthetic_trees warm(profiled) {warm_prof_s:.1f}s")
        configs["synthetic_trees"]["cv_warm_s_reps"] = [
            round(warm["train_time_s"], 2),
            round(warm2["train_time_s"], 2)]
        configs["synthetic_trees"]["profiled_rep_train_s"] = round(
            warm2["train_time_s"], 2)
        warm_s = warm_prof_s                  # profile window below
        bench.emit()

    if do_profile:
        sys.path.insert(0, os.path.join(here, "tools"))
        try:
            from xplane_top_ops import device_op_times, latest_xplane
            xp = latest_xplane(trace_dir)
            # scope to the profiled window: some libtpu builds dump every
            # op since process start into the trace
            planes = (device_op_times(xp, window_ps=int(warm_s * 1e12))
                      if xp else [])
            if planes:
                p = max(planes, key=lambda q: q["busy_ps"])
                busy_s = p["busy_ps"] / 1e12
                sum_ps = p["sum_ps"]
                top5 = [{"op": op[:80], "ms": round(t / 1e9, 2),
                         "pct_incl": round(100.0 * t / sum_ps, 1)}
                        for op, t in sorted(p["ops"].items(),
                                            key=lambda kv: -kv[1])[:5]]
                dev_fps = warm_flops / busy_s if busy_s > 0 else 0.0
                configs["synthetic_trees"]["profile"] = {
                    "wall_s": round(warm_s, 2),
                    "device_busy_s": round(busy_s, 2),
                    "device_util_pct": round(100.0 * busy_s / warm_s, 1),
                    "device_mfu_bf16_pct": round(
                        100.0 * dev_fps / V5E_PEAK_BF16, 3),
                    "top_ops": top5,
                }
                bench.emit()
        except Exception as e:          # profiling is best-effort
            _log(f"[bench] profile parse failed: {e!r}")

    # 5b. The FULL 10M-row BASELINE config — one pass. Two defenses: a
    #     coarse gate on remaining budget, and a hard SIGALRM bound at
    #     the remaining budget so an under-estimate records a structured
    #     timeout instead of blowing the external driver's clock (the
    #     estimate is genuinely uncertain: the sweep trains on the
    #     splitter's physically sampled rows — sub-linear in n — while
    #     binning/eval stay linear).
    full_rows = int(os.environ.get("BENCH_SYNTH_FULL_ROWS", 10_000_000))
    if full_rows > synth_rows and backend == "tpu":
        if bench.remaining() < 180:
            configs["synthetic_trees_full"] = {
                "rows": full_rows, "status": "skipped_budget",
                "remaining_budget_s": round(bench.remaining(), 1),
                "measured_max_rows": synth_rows,
                "note": "raise BENCH_BUDGET_S to run; the 2M config above "
                        "is the largest in-budget measurement"}
            _log(f"[bench] 10M skipped: remaining "
                 f"{bench.remaining():.0f}s < 180s")
        else:
            class _FullTimeout(Exception):
                pass

            def _full_alarm(*_a):
                raise _FullTimeout()
            old_alarm = signal.signal(signal.SIGALRM, _full_alarm)
            alarm_s = max(int(bench.remaining()) - 30, 60)
            try:
                f0 = _flops_total()
                t0 = time.time()
                signal.alarm(alarm_s)
                full_eval_rows = int(os.environ.get(
                    "BENCH_SYNTH_FULL_EVAL_ROWS", 2_000_000))
                out_full = run_synth(n_rows=full_rows, num_folds=3,
                                     seed=42, eval_rows=full_eval_rows)
                signal.alarm(0)
                full_total = time.time() - t0
                configs["synthetic_trees_full"] = {
                    "rows": full_rows,
                    "eval_rows": min(full_eval_rows, full_rows),
                    "AuPR": round(float(out_full["metrics"]["AuPR"]), 4),
                    "train_s_incl_compile": round(
                        out_full["train_time_s"], 2),
                    "total_s": round(full_total, 2),
                    "best_model": out_full["summary"].best_model_name,
                    "phases": out_full.get("phases"),
                    **_mfu_fields(_flops_total() - f0,
                                  out_full["train_time_s"]),
                }
            except _FullTimeout:
                configs["synthetic_trees_full"] = {
                    "rows": full_rows, "status": "timeout",
                    "alarm_s": alarm_s,
                    "elapsed_before_alarm_s": round(time.time() - t0, 1),
                    "measured_max_rows": synth_rows}
                _log(f"[bench] 10M config hit the {alarm_s}s alarm")
            except Exception as e:      # record instead of killing bench
                _log(f"[bench] 10M config failed: {e!r}")
                configs["synthetic_trees_full"] = {
                    "rows": full_rows, "error": repr(e)[:400]}
            finally:
                signal.alarm(0)
                signal.signal(signal.SIGALRM, old_alarm)
        bench.emit()

    # CPU-host denominator (VERDICT r3 #3): same code on the host CPU
    # backend as the Spark-local[8] proxy. Subprocess (the axon shim pins
    # the platform per process); budget-gated, small synthetic config,
    # linear extrapolation = conservative floor (CPU throughput degrades
    # with rows). BENCH_CPU=0 disables.
    cpu_budget = int(os.environ.get("BENCH_CPU_TIMEOUT_S", 300))
    if os.environ.get("BENCH_CPU", "1") != "0" and backend == "tpu":
        if bench.remaining() < cpu_budget + 30:
            cpu_budget = max(int(bench.remaining()) - 30, 0)
        if cpu_budget < 200:
            # below this, the child cannot finish even the ~65 s synth
            # stage plus a meaningful titanic alarm inside the parent's
            # kill budget (alarms + ~40 s interpreter/compile overhead)
            configs["cpu_host_denominator"] = {
                "status": "skipped_budget",
                "remaining_budget_s": round(bench.remaining(), 1)}
        else:
            import subprocess
            env = dict(os.environ)
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env["JAX_PLATFORMS"] = "cpu"
            # the child's per-stage alarms + ~40s of interpreter/compile
            # overhead must fit inside the parent's kill budget, or the
            # sanctioned work exceeds the timeout and the salvage path
            # becomes the EXPECTED path. The child runs the cheap synth
            # stage FIRST (~65 s measured at 5000 rows on one core) so a
            # bounded budget always captures a MEASURED tree-sweep
            # denominator; only titanic (cold+warm ≈ 600 s on one core)
            # degrades to a lower bound.
            synth_alarm = 100      # ~65 s measured + compile-slow margin
            env.setdefault("BENCH_CPU_SYNTH_TIMEOUT_S", str(synth_alarm))
            tit_s = cpu_budget - synth_alarm - 40    # >= 60 by the gate
            env.setdefault("BENCH_CPU_TITANIC_TIMEOUT_S", str(tit_s))
            try:
                t0 = time.time()
                proc = subprocess.run(
                    [sys.executable, os.path.join(here, "tools",
                                                  "bench_cpu.py")],
                    env=env, capture_output=True, text=True,
                    timeout=cpu_budget)
                line = [ln for ln in proc.stdout.strip().splitlines()
                        if ln.startswith("{")][-1]
                cpu = json.loads(line)
                cpu["wall_s"] = round(time.time() - t0, 1)
                configs["cpu_host_denominator"] = cpu
                _apply_cpu_denominator(cpu, configs, synth_rows)
            except subprocess.TimeoutExpired as te:
                # bench_cpu emits a cumulative JSON line per completed
                # stage precisely for this path — salvage the last one
                cpu = {"status": "timeout", "budget_s": cpu_budget}
                try:
                    txt = te.stdout or b""
                    if isinstance(txt, bytes):
                        txt = txt.decode("utf-8", "replace")
                    lines = [ln for ln in txt.strip().splitlines()
                             if ln.startswith("{")]
                    if lines:
                        cpu.update(json.loads(lines[-1]))
                        # derive every speedup the salvaged stages
                        # support (measured synth, titanic bound) — the
                        # helper keys off the stage's OWN alarm, never
                        # the whole-child budget, so bounds stay honest
                        _apply_cpu_denominator(cpu, configs, synth_rows)
                except Exception:
                    pass
                configs["cpu_host_denominator"] = cpu
            except Exception as e:
                _log(f"[bench] cpu denominator failed: {e!r}")
                configs["cpu_host_denominator"] = {"error": repr(e)[:200]}
        bench.emit()

    # fusion gate / compile clock / cache tallies ride on EVERY emit now
    bench.emit(final=True)


if __name__ == "__main__":
    main()
