"""Map-feature data prep — the RichMapFeature DSL surface end-to-end.

A support-ticket dataset where most signal lives in MAP-typed columns
(per-channel counts, free-text attributes): the walkthrough filters keys,
smart-vectorizes a text map (low-cardinality keys pivot, high-cardinality
keys hash), decision-tree-bucketizes a numeric map key against the label,
and trains the usual CV sweep on the combined vector.

Parity surface: ``RichMapFeature.vectorize`` white/blacklists,
``RichMapFeature.smartVectorize``, ``autoBucketize``
(``core/.../dsl/RichMapFeature.scala:91-664``).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.columns import ColumnStore
from transmogrifai_tpu.dsl import transmogrify
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      LogisticRegressionFamily)
from transmogrifai_tpu.types import feature_types as ft


def make_records(n=4000, seed=7):
    rng = np.random.default_rng(seed)
    channels = ["email", "phone", "chat"]
    plans = ["free", "pro", "enterprise"]
    recs = []
    for i in range(n):
        usage = {c: float(rng.poisson(3)) for c in channels
                 if rng.random() > 0.2}
        usage["internal_audit"] = float(i)          # leak-ish key to block
        attrs = {"plan": plans[int(rng.integers(0, 3))],
                 "agent_note": f"case {rng.integers(0, 10_000)} opened"}
        if rng.random() > 0.5:
            attrs["region"] = ["emea", "amer", "apac"][
                int(rng.integers(0, 3))]
        churn = float((usage.get("phone", 0) > 4)
                      or (attrs["plan"] == "free" and rng.random() < 0.4))
        # churners complain: their notes carry "cancel"-flavored terms
        terms = (["cancel", "refund", "slow"] if churn and rng.random() < .8
                 else ["thanks", "great", "question"])
        note = ["the", "customer", "said"] + [
            str(rng.choice(terms)) for _ in range(3)]
        wants = {str(rng.choice(["api", "sso", "export", "audit"]))
                 for _ in range(int(rng.integers(1, 3)))}
        has = {str(rng.choice(["api", "sso", "export"]))
               for _ in range(int(rng.integers(1, 3)))}
        recs.append({"usage": usage, "attrs": attrs, "churned": churn,
                     "note": note, "wants": wants, "has": has})
    return recs


def run(n=4000, seed=7):
    recs = make_records(n, seed)
    store = ColumnStore.from_dict({
        "usage": (ft.RealMap, [r["usage"] for r in recs]),
        "attrs": (ft.TextMap, [r["attrs"] for r in recs]),
        "churned": (ft.RealNN, [r["churned"] for r in recs]),
        "note": (ft.TextList, [r["note"] for r in recs]),
        "wants": (ft.MultiPickList, [r["wants"] for r in recs]),
        "has": (ft.MultiPickList, [r["has"] for r in recs]),
    })

    churned = FeatureBuilder.RealNN("churned").from_column().as_response()
    usage = FeatureBuilder.RealMap("usage").from_column().as_predictor()
    attrs = FeatureBuilder.TextMap("attrs").from_column().as_predictor()
    note = FeatureBuilder.TextList("note").from_column().as_predictor()
    wants = FeatureBuilder.MultiPickList("wants").from_column().as_predictor()
    has = FeatureBuilder.MultiPickList("has").from_column().as_predictor()

    # RichMapFeature surface: blacklist the leaky key, pivot the rest
    usage_vec = usage.vectorize(block_keys=["internal_audit"])
    # smartVectorize: 'plan'/'region' pivot (low cardinality),
    # 'agent_note' hashes (unique per row)
    attrs_vec = attrs.smart_vectorize(max_cardinality=10, num_features=64)
    # label-aware bucketing of one numeric key
    phone_buckets = usage.extract_key("phone").auto_bucketize(churned)
    # RichListFeature surface: stop-word removal → TF-IDF of the notes
    note_vec = note.remove_stop_words().tfidf(num_terms=32)
    # RichSetFeature surface: requested-vs-owned feature overlap
    fit_score = wants.jaccard_similarity(has)

    features = transmogrify([usage_vec, attrs_vec, phone_buckets,
                             note_vec, fit_score])
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, families=[LogisticRegressionFamily()], seed=seed)
    pred = churned.transform_with(selector, features)

    model = (Workflow().set_input_store(store)
             .set_result_features(pred).train())
    evaluator = Evaluators.BinaryClassification.auPR().set_columns(
        churned, pred)
    metrics = model.evaluate(store, evaluator)
    vec_meta = model.transform(store)[usage_vec.name].metadata
    blocked = [c for c in vec_meta.columns
               if c.grouping == "internal_audit"]
    return {"model": model, "metrics": metrics, "blocked_cols": blocked,
            "summary": model.fitted_stages[selector.uid].selector_summary}


if __name__ == "__main__":
    out = run()
    assert not out["blocked_cols"], "blacklisted key leaked into the vector"
    s = out["summary"]
    print(f"best: {s.best_model_name} {s.best_model_params}")
    print(f"full-data eval: { {k: round(float(v), 4) for k, v in out['metrics'].items() if isinstance(v, (int, float))} }")
