"""Boston housing regression — the reference's OpBoston, TPU-native.

Mirrors ``helloworld/src/main/scala/com/salesforce/hw/boston/OpBoston.scala``:
13 numeric predictors transmogrified, RegressionModelSelector (GBT + RF, as
the reference's ``modelTypesToUse``) with DataSplitter, RMSE selection.
``housing.data`` is whitespace-delimited fixed-width; the loader converts it
to records host-side (the reference's CustomReader analog).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.dsl import transmogrify
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.models import RegressionModelSelector
from transmogrifai_tpu.models.tuning import DataSplitter

BOSTON_SCHEMA = ["crim", "zn", "indus", "chas", "nox", "rm", "age", "dis",
                 "rad", "tax", "ptratio", "b", "lstat", "medv"]
DEFAULT_DATA = ("/root/reference/helloworld/src/main/resources/BostonDataset/"
                "housing.data")


def load_records(path: str = DEFAULT_DATA):
    records = []
    with open(path) as fh:
        for line in fh:
            parts = line.split()
            if len(parts) != len(BOSTON_SCHEMA):
                continue
            records.append({k: float(v) for k, v in zip(BOSTON_SCHEMA, parts)})
    return records


def build_features():
    medv = FeatureBuilder.RealNN("medv").from_column().as_response()
    nums = [FeatureBuilder.Real(n).from_column().as_predictor()
            for n in BOSTON_SCHEMA[:13]]
    features = transmogrify(nums)
    return medv, features


def run(data_path: str = DEFAULT_DATA, num_folds: int = 3, families=None,
        mesh=None, seed: int = 42):
    from transmogrifai_tpu.models.trees import GBTFamily, RandomForestFamily

    # mesh=None: Workflow.train resolves the process-default mesh
    # (PR 6 — multichip is the mainline substrate); mesh=False
    # forces single-device; an explicit Mesh pins the topology.
    medv, features = build_features()
    if families is None:
        families = [RandomForestFamily(task="regression"),
                    GBTFamily(task="regression")]

    selector = RegressionModelSelector.with_cross_validation(
        num_folds=num_folds, families=families,
        splitter=DataSplitter(reserve_test_fraction=0.1, seed=seed),
        seed=seed, mesh=mesh or None)
    prediction = medv.transform_with(selector, features)

    records = load_records(data_path)
    wf = (Workflow()
          .set_input_records(records)
          .set_result_features(prediction)
          .set_splitter(selector.splitter))
    if mesh is not None:
        wf.set_mesh(mesh)   # Mesh pins topology, False forces off

    t0 = time.time()
    model = wf.train()
    train_time = time.time() - t0

    evaluator = Evaluators.Regression().set_columns(medv, prediction)
    metrics = model.evaluate(records, evaluator)
    selected = model.fitted_stages[selector.uid]
    return {"model": model, "metrics": metrics,
            "summary": selected.selector_summary,
            "train_time_s": train_time}


if __name__ == "__main__":
    path = sys.argv[1] if len(sys.argv) > 1 else DEFAULT_DATA
    out = run(path)
    s = out["summary"]
    print(f"train wall-clock: {out['train_time_s']:.2f}s")
    print(f"best model: {s.best_model_name} {s.best_model_params}")
    print(f"full-data eval: { {k: round(float(v), 4) for k, v in out['metrics'].items() if isinstance(v, (int, float))} }")
