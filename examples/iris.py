"""Iris multiclass — the reference's OpIris, TPU-native.

Mirrors ``helloworld/src/main/scala/com/salesforce/hw/iris/OpIris.scala``:
four numeric predictors transmogrified, the string species label indexed
(``irisClass.indexed()`` → OpStringIndexerNoFilter), a
MultiClassificationModelSelector with DataCutter, F1 selection, and the
prediction deindexed back to species names (PredictionDeIndexer).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.dsl import transmogrify
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.models import MultiClassificationModelSelector
from transmogrifai_tpu.models.tuning import DataCutter
from transmogrifai_tpu.ops.indexers import (OpStringIndexerNoFilter,
                                            PredictionDeIndexer)
from transmogrifai_tpu.readers import DataReaders

IRIS_SCHEMA = ["sepalLength", "sepalWidth", "petalLength", "petalWidth",
               "irisClass"]
DEFAULT_CSV = ("/root/reference/helloworld/src/main/resources/IrisDataset/"
               "bezdekIris.data")


def _num(field):
    return lambda r: float(r[field]) if r.get(field) not in (None, "") else None


def build_features():
    iris_class = (FeatureBuilder.Text("irisClass")
                  .from_column().as_response())
    labels = iris_class.transform_with(OpStringIndexerNoFilter())

    nums = [FeatureBuilder.Real(n).extract(_num(n), n).as_predictor()
            for n in IRIS_SCHEMA[:4]]
    features = transmogrify(nums)
    return iris_class, labels, features


def run(csv_path: str = DEFAULT_CSV, num_folds: int = 3, families=None,
        mesh=None, seed: int = 42):
    # mesh=None: Workflow.train resolves the process-default mesh
    # (PR 6 — multichip is the mainline substrate); mesh=False
    # forces single-device; an explicit Mesh pins the topology.
    iris_class, labels, features = build_features()

    selector = MultiClassificationModelSelector.with_cross_validation(
        num_folds=num_folds, families=families,
        splitter=DataCutter(reserve_test_fraction=0.2, seed=seed),
        seed=seed, mesh=mesh or None)
    prediction = labels.transform_with(selector, features)
    # species names round-trip: indexed prediction → label strings
    deindexed = labels.transform_with(PredictionDeIndexer(), prediction)

    reader = DataReaders.simple.csv(csv_path, IRIS_SCHEMA)
    wf = (Workflow()
          .set_reader(reader)
          .set_result_features(prediction, deindexed)
          .set_splitter(selector.splitter))
    if mesh is not None:
        wf.set_mesh(mesh)   # Mesh pins topology, False forces off

    t0 = time.time()
    model = wf.train()
    train_time = time.time() - t0

    evaluator = Evaluators.MultiClassification.f1().set_columns(
        labels, prediction)
    store = reader.generate_store([f for f in prediction.raw_features()])
    metrics = model.evaluate(store, evaluator)
    scored = model.score(store)
    selected = model.fitted_stages[selector.uid]
    return {"model": model, "metrics": metrics,
            "summary": selected.selector_summary,
            "predicted_labels": scored[deindexed.name],
            "train_time_s": train_time}


if __name__ == "__main__":
    csv = sys.argv[1] if len(sys.argv) > 1 else DEFAULT_CSV
    out = run(csv)
    s = out["summary"]
    print(f"train wall-clock: {out['train_time_s']:.2f}s")
    print(f"best model: {s.best_model_name} {s.best_model_params}")
    print(f"full-data eval: { {k: round(float(v), 4) for k, v in out['metrics'].items() if isinstance(v, (int, float))} }")
    names = {out["predicted_labels"].get_raw(i) for i in range(10)}
    print(f"sample deindexed predictions: {sorted(names)}")
