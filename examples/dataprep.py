"""Event-time data prep — the reference's dataprep/conditional-aggregation
walkthrough (``docs/examples/Conditional-Aggregation.md``,
``helloworld/.../dataprep``), TPU-native.

Visit-log records aggregate per user with a PER-KEY cutoff fixed by an
event predicate ("first purchase"): predictor features fold events BEFORE
each user's cutoff through their type's monoid aggregators, the response
folds events AFTER it — the reader enforces the leak barrier, not the
modeler.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.readers import DataReaders
from transmogrifai_tpu.utils.aggregators import (LogicalOrAggregator,
                                                 SumAggregator)

VISITS = [
    # user a: browses, buys at t=300, returns after
    {"user": "a", "ts": 100, "page": "home", "minutes": 3.0, "purchase": 0},
    {"user": "a", "ts": 200, "page": "item", "minutes": 7.0, "purchase": 0},
    {"user": "a", "ts": 300, "page": "cart", "minutes": 2.0, "purchase": 1},
    {"user": "a", "ts": 400, "page": "item", "minutes": 9.0, "purchase": 0},
    # user b: browses, never buys → dropped (no condition event)
    {"user": "b", "ts": 150, "page": "home", "minutes": 1.0, "purchase": 0},
    # user c: buys immediately at t=50, heavy use after
    {"user": "c", "ts": 50, "page": "cart", "minutes": 1.0, "purchase": 1},
    {"user": "c", "ts": 90, "page": "item", "minutes": 20.0, "purchase": 1},
]


def build_reader():
    return DataReaders.conditional.records(
        VISITS,
        timestamp_fn=lambda r: r["ts"],
        condition_fn=lambda r: r["purchase"] == 1,
        key_fn=lambda r: r["user"])


def build_features():
    # predictors: behavior BEFORE the first purchase
    minutes_before = (FeatureBuilder.Real("minutes")
                      .from_column().aggregate(SumAggregator())
                      .as_predictor())
    # response: any repeat purchase AFTER the first one
    repeat_buyer = (FeatureBuilder.Binary("purchase")
                    .extract(lambda r: bool(r["purchase"]), "purchase")
                    .aggregate(LogicalOrAggregator())
                    .as_response())
    return minutes_before, repeat_buyer


def run():
    reader = build_reader()
    minutes_before, repeat_buyer = build_features()
    store = reader.generate_store([minutes_before, repeat_buyer])
    rows = {}
    for i in range(store.n_rows):
        rows[i] = {n: store[n].get_raw(i) for n in store.names()}
    return store, rows


if __name__ == "__main__":
    store, rows = run()
    print(f"{store.n_rows} users (condition-less users dropped):")
    for i, r in rows.items():
        print(" ", r)
