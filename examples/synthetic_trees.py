"""Large synthetic tree-grid sweep — the 10M-row BASELINE config.

Full AutoML tree grid (RF + GBT + XGB families) with k-fold CV over a
synthetic tabular dataset, mirroring BASELINE.json's fifth config. The
feature matrix is generated directly as a dense device-ready array (the
at-scale ingestion path: numeric columns need no host feature prep), so the
benchmark isolates the tree engine's (fold × grid) sweep throughput —
the exact workload Spark distributes over executors and we batch into one
XLA program per family (models/_treefit.py).

Row count is a parameter: the driver-facing bench uses SYNTH_ROWS (default
2M single-chip; 10M fits a v5e-8 via the data-sharded mesh).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

from transmogrifai_tpu import ColumnStore, FeatureBuilder, Workflow, column_from_values
from transmogrifai_tpu.columns import VectorColumn
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.models import BinaryClassificationModelSelector
from transmogrifai_tpu.models.tuning import DataBalancer
from transmogrifai_tpu.types import feature_types as ft


#: one-slot store cache: the bench's cold/warm/profiled passes reuse the
#: same synthetic data — regenerating it is data prep, not framework
#: work, and the reference bench likewise reads a fixed file
_STORE_CACHE: dict = {}


def synthesize_store(n_rows: int, n_features: int = 20, seed: int = 11):
    key = (n_rows, n_features, seed)
    if key in _STORE_CACHE:
        return _STORE_CACHE[key]
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    # tree-friendly target: axis-aligned interactions + noise
    logits = (1.5 * (X[:, 0] > 0.3) * (X[:, 1] < 0.0)
              + 1.0 * (X[:, 2] > 1.0)
              - 1.2 * (X[:, 3] < -0.5)
              + 0.3 * rng.normal(size=n_rows))
    y = (logits > 0.4).astype(np.float64)
    store = ColumnStore({
        "label": column_from_values(ft.RealNN, y),
        # f32 feature matrix end-to-end (the pipeline dtype): an f64 copy
        # held no extra information and doubled the host->device upload
        "features": VectorColumn(ft.OPVector, X),
    })
    _STORE_CACHE.clear()
    _STORE_CACHE[key] = store
    return store


def run(n_rows: int = 2_000_000, n_features: int = 20, num_folds: int = 5,
        families=None, mesh=None, seed: int = 42,
        eval_rows: int = 0):
    """``eval_rows > 0`` evaluates AuPR on that many rows instead of the
    full store — at the 10M config the full-store eval is ~3 minutes of
    pure link transfer for a quality anchor a 2M slice pins equally
    well; the bench records the slice size it used."""
    from transmogrifai_tpu.models.trees import (GBTFamily, RandomForestFamily,
                                                XGBoostFamily)

    # mesh=None: Workflow.train resolves the process-default mesh
    # (PR 6 — multichip is the mainline substrate); mesh=False
    # forces single-device; an explicit Mesh pins the topology.
    if families is None:
        # the BASELINE config's three tree families; reduced grid so the
        # sweep is (3 + 3 + 2) × num_folds ensemble fits
        families = [
            RandomForestFamily(grid=[
                {"maxDepth": d, "minInstancesPerNode": 10,
                 "minInfoGain": 0.001} for d in (3, 6, 9)]),
            GBTFamily(grid=[
                {"maxDepth": d, "minInstancesPerNode": 10,
                 "minInfoGain": 0.001} for d in (3, 6, 9)]),
            XGBoostFamily(grid=[
                {"maxDepth": d, "numRound": 20, "eta": 0.3,
                 "minChildWeight": 1.0} for d in (3, 6)]),
        ]

    label = FeatureBuilder.RealNN("label").from_column().as_response()
    feats = FeatureBuilder.OPVector("features").from_column().as_predictor()

    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=num_folds, validation_metric="AuPR", families=families,
        splitter=DataBalancer(sample_fraction=0.1,
                              reserve_test_fraction=0.1, seed=seed),
        seed=seed, mesh=mesh or None)
    prediction = label.transform_with(selector, feats)

    tp0 = time.time()
    store = synthesize_store(n_rows, n_features)
    wf = (Workflow()
          .set_input_store(store)
          .set_result_features(prediction)
          .set_splitter(selector.splitter))
    if mesh is not None:
        wf.set_mesh(mesh)   # Mesh pins topology, False forces off
    prep_s = time.time() - tp0

    t0 = time.time()
    model = wf.train()
    train_time = time.time() - t0

    te0 = time.time()
    evaluator = Evaluators.BinaryClassification.auPR().set_columns(
        label, prediction)
    eval_store = store
    if eval_rows and eval_rows < store.n_rows:
        eval_store = store.take(np.arange(eval_rows))
    metrics = model.evaluate(eval_store, evaluator)
    eval_s = time.time() - te0
    selected = model.fitted_stages[selector.uid]
    return {"model": model, "metrics": metrics,
            "summary": selected.selector_summary,
            "train_time_s": train_time,
            "phases": {"data_prep_s": round(prep_s, 2),
                       "train_s": round(train_time, 2),
                       "eval_s": round(eval_s, 2)}}


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    out = run(n)
    s = out["summary"]
    print(f"train wall-clock: {out['train_time_s']:.2f}s ({n} rows)")
    print(f"best model: {s.best_model_name} {s.best_model_params}")
    print(f"full-data eval: { {k: round(float(v), 4) for k, v in out['metrics'].items() if isinstance(v, (int, float))} }")
