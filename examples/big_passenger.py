"""SmartTextVectorizer-heavy workflow — the BigPassenger BASELINE config.

The reference's ``test-data/BigPassengerWithHeader.csv`` fixture is 10 rows;
its *schema* (free-text ``description`` beside numeric/categorical/date
fields) is what makes it the smart-text stress config, so this example
replays that schema at configurable scale with synthesized records. The
``description`` column's cardinality exceeds ``max_cardinality``, routing it
through the hashing path of SmartTextVectorizer
(``SmartTextVectorizer.scala:60-163`` semantics).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.dsl import transmogrify
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.models import BinaryClassificationModelSelector
from transmogrifai_tpu.models.linear import LogisticRegressionFamily
from transmogrifai_tpu.models.tuning import DataBalancer

_WORDS = ("travel cabin sea ocean deck luxury family crew storm rescue "
          "ticket meal night morning harbor voyage captain steward porter "
          "engine coal first second third class suite promenade").split()


#: pass line for the deterministic label rule below: the rule is exactly
#: recoverable from [has-"rescue"-token, gender one-hot, height] — all of
#: which survive vectorization — so a sound text path scores near-perfect
#: AuPR. Measured: LR reaches ~0.99 at 8k-200k rows; 0.95 leaves slack
#: for fold noise while still failing hard if the text signal is dropped
#: (without it the ceiling is ~0.8).
TARGET_AUPR = 0.95


#: one-slot record cache: cold/warm bench passes reuse the same records
#: (data generation is not framework work; the reference reads a CSV)
_RECORD_CACHE: dict = {}


def synthesize_records(n: int, seed: int = 7):
    key = (n, seed)
    if key in _RECORD_CACHE:
        return _RECORD_CACHE[key]
    rng = np.random.default_rng(seed)
    genders = np.array(["Male", "Female"], dtype=object)
    recs = []
    g_idx = rng.integers(0, 2, n)
    heights = rng.normal(170, 12, n)
    weights = rng.normal(70, 15, n)
    ages = rng.integers(1, 90, n)
    n_words = rng.integers(3, 12, n)
    word_idx = rng.integers(0, len(_WORDS), (n, 12))
    for i in range(n):
        words = [_WORDS[j] for j in word_idx[i, :n_words[i]]]
        # DETERMINISTIC label (VERDICT r2 #8): a text-dependent LINEAR
        # threshold rule over quantities the vectorizers expose — the
        # "rescue" token presence (hashed text path; bag-of-tokens, so
        # the rule uses presence anywhere in the WRITTEN text), gender
        # (pivot path), height (numeric path). A sound pipeline can
        # recover it almost exactly; dropping the text path caps AuPR
        # far below TARGET_AUPR.
        has_rescue = "rescue" in words
        score = (2.0 * has_rescue + 1.0 * (g_idx[i] == 1)
                 + 0.02 * (heights[i] - 170.0))
        recs.append({
            "age": float(ages[i]) if rng.random() > 0.05 else None,
            "gender": str(genders[g_idx[i]]),
            "height": float(heights[i]),
            "weight": float(weights[i]),
            "description": " ".join(words) + f" voyage{i % 997}",
            "boarded": 1471046600 + int(rng.integers(0, 3_000_000)),
            "anotherFloat": float(rng.random()),
            "survived": 1.0 if score > 1.2 else 0.0,
        })
    _RECORD_CACHE.clear()
    _RECORD_CACHE[key] = recs
    return recs


def build_features():
    survived = FeatureBuilder.RealNN("survived").from_column().as_response()
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    gender = FeatureBuilder.PickList("gender").from_column().as_predictor()
    height = FeatureBuilder.Real("height").from_column().as_predictor()
    weight = FeatureBuilder.Real("weight").from_column().as_predictor()
    description = FeatureBuilder.Text("description").from_column().as_predictor()
    boarded = FeatureBuilder.Date("boarded").from_column().as_predictor()
    another = FeatureBuilder.Real("anotherFloat").from_column().as_predictor()

    features = transmogrify([age, gender, height, weight, description,
                             boarded, another])
    checked = survived.sanity_check(features, remove_bad_features=True)
    return survived, checked


def run(n_rows: int = 30_000, num_folds: int = 3, families=None,
        mesh=None, seed: int = 42):
    # mesh=None: Workflow.train resolves the process-default mesh
    # (PR 6 — multichip is the mainline substrate); mesh=False
    # forces single-device; an explicit Mesh pins the topology.
    survived, checked = build_features()
    if families is None:
        families = [LogisticRegressionFamily()]

    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=num_folds, validation_metric="AuPR", families=families,
        splitter=DataBalancer(sample_fraction=0.1,
                              reserve_test_fraction=0.1, seed=seed),
        seed=seed, mesh=mesh or None)
    prediction = survived.transform_with(selector, checked)

    tp0 = time.time()
    records = synthesize_records(n_rows, seed=seed)
    wf = (Workflow()
          .set_input_records(records)
          .set_result_features(prediction)
          .set_splitter(selector.splitter))
    if mesh is not None:
        wf.set_mesh(mesh)   # Mesh pins topology, False forces off
    prep_s = time.time() - tp0

    t0 = time.time()
    model = wf.train()
    train_time = time.time() - t0

    te0 = time.time()
    evaluator = Evaluators.BinaryClassification.auPR().set_columns(
        survived, prediction)
    metrics = model.evaluate(records, evaluator)
    eval_s = time.time() - te0
    selected = model.fitted_stages[selector.uid]
    return {"model": model, "metrics": metrics,
            "summary": selected.selector_summary,
            "train_time_s": train_time,
            "phases": {"data_prep_s": round(prep_s, 2),
                       "train_s": round(train_time, 2),
                       "eval_s": round(eval_s, 2)}}


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 30_000
    out = run(n)
    s = out["summary"]
    aupr = float(out["metrics"]["AuPR"])
    print(f"train wall-clock: {out['train_time_s']:.2f}s ({n} rows)")
    print(f"best model: {s.best_model_name} {s.best_model_params}")
    print(f"full-data eval: { {k: round(float(v), 4) for k, v in out['metrics'].items() if isinstance(v, (int, float))} }")
    verdict = "PASS" if aupr >= TARGET_AUPR else "FAIL"
    print(f"AuPR {aupr:.4f} vs target {TARGET_AUPR} -> {verdict}")
    if verdict == "FAIL":
        raise SystemExit(1)
