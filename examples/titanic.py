"""Titanic survival — the reference's hello-world, TPU-native.

Mirrors ``helloworld/src/main/scala/com/salesforce/hw/OpTitanicSimple.scala:77-130``
feature-for-feature: same raw features, same derived features (familySize,
estimatedCostOfTickets, pivotedSex, ageGroup, normedAge), same transmogrify +
sanity check + BinaryClassificationModelSelector flow. The parity target is
the reference README's holdout AuPR 0.8225 / AuROC 0.8822 (README.md:85-90).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from transmogrifai_tpu import FeatureBuilder, Workflow
from transmogrifai_tpu.dsl import transmogrify
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.models import (BinaryClassificationModelSelector,
                                      DataBalancer)
from transmogrifai_tpu.readers import DataReaders
from transmogrifai_tpu.types import feature_types as ft

TITANIC_SCHEMA = ["id", "survived", "pClass", "name", "sex", "age", "sibSp",
                  "parCh", "ticket", "fare", "cabin", "embarked"]
_BUNDLED_CSV = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "tests", "resources",
                            "PassengerDataAll.csv")
_REFERENCE_CSV = "/root/reference/test-data/PassengerDataAll.csv"
#: the public Titanic dataset; prefer the reference checkout's copy, fall
#: back to the bundled one so the example (and dryrun_multichip) runs on
#: hosts without /root/reference
DEFAULT_CSV = (_REFERENCE_CSV if os.path.exists(_REFERENCE_CSV)
               else _BUNDLED_CSV)


def _num(field):
    return lambda r: float(r[field]) if r.get(field) not in (None, "") else None


def build_features(with_sanity_check: bool = True):
    """Raw + derived features, mirroring OpTitanicSimple."""
    survived = (FeatureBuilder.RealNN("survived")
                .extract(_num("survived"), "survived").as_response())
    p_class = FeatureBuilder.PickList("pClass").from_column().as_predictor()
    name = FeatureBuilder.Text("name").from_column().as_predictor()
    sex = FeatureBuilder.PickList("sex").from_column().as_predictor()
    age = FeatureBuilder.Real("age").extract(_num("age"), "age").as_predictor()
    sib_sp = (FeatureBuilder.Integral("sibSp")
              .extract(_num("sibSp"), "sibSp").as_predictor())
    par_ch = (FeatureBuilder.Integral("parCh")
              .extract(_num("parCh"), "parCh").as_predictor())
    ticket = FeatureBuilder.PickList("ticket").from_column().as_predictor()
    fare = (FeatureBuilder.Real("fare")
            .extract(_num("fare"), "fare").as_predictor())
    cabin = FeatureBuilder.PickList("cabin").from_column().as_predictor()
    embarked = FeatureBuilder.PickList("embarked").from_column().as_predictor()

    # derived features (OpTitanicSimple.scala:118-124)
    family_size = sib_sp + par_ch + 1
    estimated_cost = family_size * fare
    pivoted_sex = sex.pivot()
    normed_age = age.fill_missing_with_mean().z_normalize()
    age_group = age.map_to(
        lambda v: ("adult" if v > 18 else "child") if v is not None else None,
        ft.PickList, "ageGroup")

    passenger_features = transmogrify([
        p_class, name, age, sib_sp, par_ch, ticket, cabin, embarked,
        family_size, estimated_cost, pivoted_sex, age_group, normed_age,
    ])

    if with_sanity_check:
        checked = survived.sanity_check(passenger_features,
                                        remove_bad_features=True)
    else:
        checked = passenger_features
    return survived, checked


def run(csv_path: str = DEFAULT_CSV, num_folds: int = 3, families=None,
        with_sanity_check: bool = True, mesh=None, seed: int = 42):
    # mesh=None: Workflow.train resolves the process-default mesh itself
    # (PR 6 — multichip is the mainline substrate, so the example no
    # longer builds one by hand); mesh=False forces single-device; an
    # explicit Mesh pins the topology.
    survived, checked = build_features(with_sanity_check)

    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=num_folds, validation_metric="AuPR", families=families,
        splitter=DataBalancer(sample_fraction=0.1,
                              reserve_test_fraction=0.1, seed=seed),
        seed=seed, mesh=mesh or None)
    prediction = survived.transform_with(selector, checked)

    reader = DataReaders.simple.csv(csv_path, TITANIC_SCHEMA,
                                    key_fn=lambda r: r["id"])
    wf = (Workflow()
          .set_reader(reader)
          .set_result_features(prediction)
          .set_splitter(selector.splitter))
    if mesh is not None:
        wf.set_mesh(mesh)          # Mesh pins topology, False forces off

    t0 = time.time()
    model = wf.train()
    train_time = time.time() - t0

    evaluator = Evaluators.BinaryClassification.auPR().set_columns(
        survived, prediction)
    store = reader.generate_store(
        [f for f in prediction.raw_features()])
    metrics = model.evaluate(store, evaluator)
    selected = model.fitted_stages[selector.uid]
    return {"model": model, "metrics": metrics,
            "summary": selected.selector_summary,
            "train_time_s": train_time}


if __name__ == "__main__":
    csv = sys.argv[1] if len(sys.argv) > 1 else DEFAULT_CSV
    out = run(csv)
    s = out["summary"]
    print(f"train wall-clock: {out['train_time_s']:.2f}s")
    print(f"best model: {s.best_model_name} {s.best_model_params}")
    print(f"train eval: { {k: round(v, 4) for k, v in s.train_evaluation.items()} }")
    if s.holdout_evaluation:
        print(f"holdout eval: { {k: round(v, 4) for k, v in s.holdout_evaluation.items()} }")
    print(f"full-data eval: { {k: round(float(v), 4) for k, v in out['metrics'].items() if isinstance(v, (int, float))} }")
