"""AOT program bank — ahead-of-time compiled scoring executables.

BENCH_r05 measured ~449 s of XLA compile clock against ~33 s of warm CV
train: compilation, not compute, dominates the system, and every cold
process re-pays the scoring engine's bucket-ladder compile before its
first request. This module extends the persistent-compile-cache story
(PR 3) into a true ahead-of-time contract, following the
TFX/TensorFlow-Serving export-then-serve artifact model (PAPERS.md):

* **Export** (:func:`build_program_bank`, called by
  ``serving.export_scoring_fn``): lower + compile the fused
  transform→predict chain for the WHOLE power-of-two bucket ladder —
  through :meth:`ScoringEngine.program_callable`, so the attached
  ExecutionPlan's CSE/pruning rewrites are baked into the serialized
  programs — and ship the serialized executables
  (``jax.experimental.serialize_executable``) in the export directory
  alongside the StableHLO, under a manifest recording the bucket
  ladder, plan + fitted-state digests, jax/jaxlib versions, device
  kind, and a per-program blake2b digest.
* **Load** (:func:`load_program_bank`): probe the manifest, check
  environment compatibility (platform, device kind, jax/jaxlib
  versions) and engine identity (plan-rewrite digest, fitted-state
  digest, output set), then deserialize compatible executables straight
  into the ScoringEngine program cache via the public
  :meth:`ScoringEngine.preload` seam — ``compile_count`` stays 0, so a
  cold process answers its first request in milliseconds. Every
  failure mode (version skew, wrong device kind, tampered digest,
  truncated manifest, missing program file) degrades per-bucket to
  JIT-on-miss with a TMG5xx advisory finding — never a crash.

The always-on :func:`aot_stats` tallies follow the ``engine_cache_stats``
discipline: cheap enough to never turn off, stamped on bench docs.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry

logger = logging.getLogger(__name__)

__all__ = ["build_program_bank", "load_program_bank", "read_manifest",
           "environment_fingerprint", "bank_dir", "manifest_path",
           "load_flat_programs", "aot_stats", "reset_aot_stats",
           "FORMAT_VERSION", "BANK_DIRNAME", "BANK_MANIFEST"]

FORMAT_VERSION = 1
BANK_DIRNAME = "aot_bank"
BANK_MANIFEST = "aot_manifest.json"

# ---------------------------------------------------------------------------
# always-on tallies (bench docs stamp these; telemetry mirrors when enabled)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"banks_exported": 0, "programs_exported": 0,
          "banks_loaded": 0, "programs_loaded": 0,
          "programs_skipped": 0, "banks_incompatible": 0}


def aot_stats() -> Dict[str, int]:
    """Snapshot of the process-wide AOT-bank tallies (always on, the
    ``engine_cache_stats`` discipline): exports, loads, per-program
    skip counts and whole-bank incompatibility rejections."""
    with _TALLY_LOCK:
        return dict(_TALLY)


def reset_aot_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n
    telemetry.counter(f"aot.{key}").inc(n)  # lint: metric-name — keys are the fixed aot_stats tally catalog


# ---------------------------------------------------------------------------
# paths + fingerprints
# ---------------------------------------------------------------------------


def bank_dir(path: str) -> str:
    """The program-bank subdirectory of an export directory."""
    return os.path.join(path, BANK_DIRNAME)


def manifest_path(path: str) -> str:
    return os.path.join(bank_dir(path), BANK_MANIFEST)


def environment_fingerprint() -> Dict[str, Any]:
    """The compatibility fields a serialized executable is only valid
    under: jax/jaxlib versions, backend platform and device kind.
    Serialized XLA executables are NOT portable across any of these —
    the loader compares field-for-field and falls back to JIT on any
    mismatch."""
    import jax
    import jaxlib
    dev = jax.devices()[0]
    return {"jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": dev.platform,
            "deviceKind": dev.device_kind}


def _program_digest(blob: bytes) -> str:
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def _spec_blocks(blocks: List[Dict[str, Any]], bucket: int
                 ) -> Tuple[Dict[str, Dict[str, np.ndarray]],
                            Dict[str, np.ndarray]]:
    """(prepared, uploads) dummy pytrees at ``bucket`` rows from the
    export block manifest — zero-copy broadcast views, used both to
    lower the program at export and to recompute the exact cache key at
    load (shape/dtype are all the key reads)."""
    prepared: Dict[str, Dict[str, np.ndarray]] = {}
    uploads: Dict[str, np.ndarray] = {}
    for spec in blocks:
        shape = (bucket, *[int(t) for t in spec["tail"]])
        a = np.broadcast_to(np.zeros((), dtype=np.dtype(spec["dtype"])),
                            shape)
        if spec["kind"] == "prepared":
            prepared.setdefault(spec["uid"], {})[spec["name"]] = a
        else:
            uploads[spec["name"]] = a
    return prepared, uploads


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------


def build_program_bank(engine, blocks: List[Dict[str, Any]],
                       out_names: List[str], path: str,
                       ladder: Optional[List[int]] = None
                       ) -> Optional[Dict[str, Any]]:
    """Compile the engine's program for every ladder bucket and ship the
    serialized executables under ``path``'s ``aot_bank/`` directory.

    ``blocks`` is the export block manifest (``engine.export_manifest``
    output); ``ladder`` defaults to the full power-of-two ladder up to
    the engine's bucket cap. Returns the written bank manifest, or
    ``None`` when this backend's executables do not support
    serialization (export still succeeds without a bank — an advisory,
    not an error)."""
    import jax
    from jax.experimental import serialize_executable as se

    from .scoring import bucket_ladder

    ladder = sorted({int(b) for b in (ladder
                     or bucket_ladder(engine.bucket_cap))})
    run = engine.program_callable(out_names)
    bdir = bank_dir(path)
    os.makedirs(bdir, exist_ok=True)
    programs: Dict[str, Dict[str, Any]] = {}
    with telemetry.span("aot:build_program_bank", buckets=len(ladder)):
        for bucket in ladder:
            prepared, uploads = _spec_blocks(blocks, bucket)
            compiled = jax.jit(run).lower(prepared, uploads).compile()
            try:
                payload, in_tree, out_tree = se.serialize(compiled)
            except (ValueError, TypeError) as e:
                # this backend's executables don't serialize (no
                # unloaded-executable support): the export ships
                # without a bank, JIT serves — advisory, never fatal
                logger.warning(
                    "AOT bank disabled: executable serialization "
                    "unsupported on this backend (%s)", e)
                return None
            blob = pickle.dumps((payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
            fname = f"bucket_{bucket}.xbin"
            with open(os.path.join(bdir, fname), "wb") as fh:
                fh.write(blob)
            programs[str(bucket)] = {"file": fname, "bytes": len(blob),
                                     "digest": _program_digest(blob)}
            _tally("programs_exported")
    manifest = {
        "formatVersion": FORMAT_VERSION,
        "bucketLadder": ladder,
        "bucketCap": int(engine.bucket_cap),
        "outNames": list(out_names),
        "blocks": blocks,
        "planDigest": engine.rewrite_digest(),
        "stateDigest": engine.state_digest(),
        "environment": environment_fingerprint(),
        "programs": programs,
    }
    tmp = manifest_path(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1)
    os.replace(tmp, manifest_path(path))
    _tally("banks_exported")
    logger.info("AOT program bank: %d executable(s) at %s "
                "(%d bytes total)", len(programs), bdir,
                sum(p["bytes"] for p in programs.values()))
    return manifest


def remove_bank(path: str) -> None:
    """Delete any program bank under export dir ``path``. Called by
    ``export_scoring_fn`` whenever it does NOT write a fresh bank
    (``aot=False`` or a non-serializing backend): a stale bank from a
    previous export would otherwise survive next to new StableHLO/meta
    and serve the OLD model's weights."""
    import shutil
    shutil.rmtree(bank_dir(path), ignore_errors=True)


def bank_bytes(manifest: Optional[Dict[str, Any]]) -> int:
    """Total serialized-program bytes a bank manifest describes (the
    model server's LRU weight)."""
    if not manifest:
        return 0
    try:
        return sum(int(p.get("bytes", 0))
                   for p in manifest.get("programs", {}).values())
    except (TypeError, ValueError, AttributeError):
        return 0


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------


def read_manifest(path: str) -> Tuple[Optional[Dict[str, Any]], List[Any]]:
    """(manifest, findings) for the bank under export dir ``path``.
    A missing bank is ``(None, [])`` — not an error (pre-bank exports
    stay loadable); a truncated/corrupt manifest is ``(None,
    [TMG502 finding])``."""
    from .lint import Finding
    mp = manifest_path(path)
    try:
        with open(mp) as fh:
            manifest = json.load(fh)
    except FileNotFoundError:
        return None, []
    except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
        return None, [Finding(
            "TMG502", f"AOT bank manifest unreadable ({e}); the whole "
            "bank is ignored and scoring JIT-compiles per bucket",
            location=mp)]
    if not isinstance(manifest, dict) \
            or not isinstance(manifest.get("programs"), dict) \
            or not isinstance(manifest.get("blocks"), list):
        return None, [Finding(
            "TMG502", "AOT bank manifest is missing its programs/blocks "
            "tables (truncated or hand-edited); the whole bank is "
            "ignored and scoring JIT-compiles per bucket", location=mp)]
    return manifest, []


def _compat_findings(manifest: Dict[str, Any], path: str,
                     engine=None) -> List[Any]:
    """Environment (+ optional engine-identity) compatibility findings.
    Non-empty means the bank must not serve — JIT-on-miss takes over."""
    from .lint import Finding
    out: List[Any] = []
    loc = manifest_path(path)
    if manifest.get("formatVersion") != FORMAT_VERSION:
        out.append(Finding(
            "TMG501", "AOT bank format version "
            f"{manifest.get('formatVersion')!r} != {FORMAT_VERSION} — "
            "re-export the bank with this build", location=loc))
        return out
    env = environment_fingerprint()
    want = manifest.get("environment") or {}
    for k in ("platform", "deviceKind", "jax", "jaxlib"):
        if want.get(k) != env[k]:
            out.append(Finding(
                "TMG501", f"AOT bank {k} mismatch: exported under "
                f"{want.get(k)!r}, this process runs {env[k]!r} — "
                "serialized executables are environment-bound, scoring "
                "falls back to per-bucket JIT", location=loc))
    if engine is not None and not out:
        if manifest.get("planDigest") != engine.rewrite_digest():
            out.append(Finding(
                "TMG501", "AOT bank plan-rewrite digest mismatch (the "
                "serve-time ExecutionPlan differs from the exported "
                "one; banked gathers would compute different columns) — "
                "per-bucket JIT serves", location=loc))
        if manifest.get("stateDigest") != engine.state_digest():
            out.append(Finding(
                "TMG501", "AOT bank fitted-state digest mismatch (the "
                "banked executables close over DIFFERENT weights than "
                "this model carries) — per-bucket JIT serves",
                location=loc))
        if list(manifest.get("outNames") or []) \
                != list(engine._out_names(results_only=True)):
            out.append(Finding(
                "TMG501", "AOT bank output set differs from the "
                "serve-time engine's result features — per-bucket JIT "
                "serves", location=loc))
        if int(manifest.get("bucketCap", 0)) != int(engine.bucket_cap):
            out.append(Finding(
                "TMG501", f"AOT bank bucket cap "
                f"{manifest.get('bucketCap')!r} != engine cap "
                f"{engine.bucket_cap} — per-bucket JIT serves",
                location=loc))
    return out


def _load_program(path: str, manifest: Dict[str, Any], bucket: int):
    """Deserialize one banked executable; raises ``ValueError`` with a
    descriptive reason on any integrity failure (caller converts to a
    per-bucket advisory + JIT fallback)."""
    from jax.experimental import serialize_executable as se
    rec = manifest["programs"][str(bucket)]
    fpath = os.path.join(bank_dir(path), str(rec.get("file", "")))
    try:
        with open(fpath, "rb") as fh:
            blob = fh.read()
    except OSError as e:
        raise ValueError(f"program file unreadable ({e})") from None
    expect = rec.get("bytes")
    if expect is not None and len(blob) != int(expect):
        raise ValueError(
            f"truncated program: {len(blob)} bytes on disk, manifest "
            f"recorded {expect}")
    digest = rec.get("digest")
    if digest is not None and _program_digest(blob) != digest:
        raise ValueError(
            "program digest mismatch (bytes altered since export)")
    try:
        payload, in_tree, out_tree = pickle.loads(blob)
        return se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception as e:  # lint: broad-except — any deserialize failure degrades to JIT, never crashes serving
        raise ValueError(
            f"executable deserialization failed "
            f"({type(e).__name__}: {e})") from e


def load_program_bank(engine, path: str,
                      emit: bool = True) -> Dict[str, Any]:
    """Probe the bank under export dir ``path`` and preload every
    compatible executable into ``engine``'s program cache.

    Returns a report: ``{"present", "compatible", "loaded": [buckets],
    "skipped": {bucket: reason}, "findings": [lint.Finding]}``. All
    failure modes are advisories (TMG501 whole-bank incompatibility,
    TMG502 per-artifact corruption) — the engine always remains
    servable via JIT-on-miss. ``emit`` mirrors the findings into
    telemetry (``lint.*`` counters + ``on_lint``).

    The engine should be built with ``mesh=False`` (the server and
    ``aot``-aware loaders do): banked executables are unsharded, and a
    multi-device dispatch keys on the mesh shape so a preloaded program
    would never be found."""
    from . import lint
    report: Dict[str, Any] = {"present": False, "compatible": False,
                              "loaded": [], "skipped": {},
                              "findings": []}
    manifest, findings = read_manifest(path)
    report["findings"].extend(findings)
    if manifest is None:
        report["present"] = bool(findings)
        if findings:
            _tally("banks_incompatible")
        _finish_report(report, emit)
        return report
    report["present"] = True
    compat = _compat_findings(manifest, path, engine=engine)
    if compat:
        report["findings"].extend(compat)
        _tally("banks_incompatible")
        _finish_report(report, emit)
        return report
    out_names = list(manifest["outNames"])
    with telemetry.span("aot:load_program_bank",
                        buckets=len(manifest["programs"])):
        for bucket_s in sorted(manifest["programs"], key=int):
            bucket = int(bucket_s)
            try:
                fn = _load_program(path, manifest, bucket)
            except ValueError as e:
                report["skipped"][bucket] = str(e)
                report["findings"].append(lint.Finding(
                    "TMG502", f"AOT bank bucket {bucket}: {e} — this "
                    "bucket JIT-compiles on first use",
                    location=manifest_path(path)))
                _tally("programs_skipped")
                continue
            prepared, uploads = _spec_blocks(manifest["blocks"], bucket)
            key = engine.program_key(prepared, uploads, out_names,
                                     mesh_key=None)
            engine.preload(key, fn)
            report["loaded"].append(bucket)
            _tally("programs_loaded")
    report["compatible"] = bool(report["loaded"])
    if report["compatible"]:
        _tally("banks_loaded")
    _finish_report(report, emit)
    return report


def _finish_report(report: Dict[str, Any], emit: bool) -> None:
    for f in report["findings"]:
        logger.warning("aot: %s", f.format())
    if emit and report["findings"]:
        from . import lint
        lint.emit_findings(report["findings"])


def load_flat_programs(path: str,
                       expect_digests: Optional[Dict[str, Any]] = None
                       ) -> Tuple[Optional[Dict[str, Any]],
                                  Dict[int, Any], List[Any]]:
    """The package-light load path for ``serving.load_scoring_fn``:
    ``(manifest, {bucket: callable}, findings)``. Environment checks
    plus — when ``expect_digests`` carries the export metadata's
    ``planDigest``/``stateDigest`` — an identity cross-check against
    the bank manifest, so a stale bank left beside a re-exported
    StableHLO (different weights!) is rejected instead of silently
    serving the old model. Corrupt or missing programs are skipped
    per-bucket with TMG502 advisories. An absent bank returns
    ``(None, {}, [])``."""
    manifest, findings = read_manifest(path)
    if manifest is None:
        if findings:
            _tally("banks_incompatible")
        return None, {}, findings
    compat = _compat_findings(manifest, path, engine=None)
    if not compat:
        from .lint import Finding
        for key in ("planDigest", "stateDigest"):
            want = (expect_digests or {}).get(key)
            if want is not None and manifest.get(key) != want:
                compat.append(Finding(
                    "TMG501", f"AOT bank {key} does not match the "
                    "StableHLO export metadata — the bank is STALE "
                    "(left over from a previous export of a different "
                    "model); the StableHLO path serves",
                    location=manifest_path(path)))
    if compat:
        _tally("banks_incompatible")
        return manifest, {}, findings + compat
    from .lint import Finding
    programs: Dict[int, Any] = {}
    for bucket_s in sorted(manifest["programs"], key=int):
        bucket = int(bucket_s)
        try:
            programs[bucket] = _load_program(path, manifest, bucket)
            _tally("programs_loaded")
        except ValueError as e:
            findings.append(Finding(
                "TMG502", f"AOT bank bucket {bucket}: {e} — this bucket "
                "serves through the StableHLO JIT path",
                location=manifest_path(path)))
            _tally("programs_skipped")
    if programs:
        _tally("banks_loaded")
    return manifest, programs, findings
