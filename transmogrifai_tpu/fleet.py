"""Horizontal serving fleet — supervised workers, failover router.

PR 8's :class:`~transmogrifai_tpu.server.ModelServer` and PR 10's
lifecycle tier are one process; millions of users need a fleet, and a
fleet's hard problem is robustness: workers die, requests must fail
over, and the registry pointer must survive any crash. This module is
the layer *between* processes — the TensorFlow-paper jump from one
device set to a fault-tolerant service, and the serving-time analog of
the Spark executor fleet the paper's runtime replaced (PAPERS.md):

* :class:`FleetSupervisor` — spawns N worker processes (each a full
  ``python -m transmogrifai_tpu serve`` loading from the shared AOT
  bank and resolving models through the shared registry — cold start
  is already milliseconds), monitors liveness via per-worker
  ``/healthz`` → ``/readyz`` probes *and* process exit codes, and
  respawns crashed workers with jittered exponential backoff
  (:class:`~transmogrifai_tpu.resilience.RetryPolicy` supplies the
  delay schedule) up to a respawn budget. Registry-pointer integrity
  costs the supervisor nothing: the lifecycle tier's kernel ``flock``
  releases a dead holder's lock automatically (no staleness heuristic,
  chaos-tested with a real SIGKILL), so a crashed worker can never
  wedge a sibling's promote.
* :class:`serve_fleet_http` — the stdlib front-door router. It
  consistent-hash routes ``POST /v1/models/<name>:score`` across READY
  workers (rendezvous hashing on a blake2b key of the request's first
  record — the same stable-hash discipline as canary routing), retries
  idempotent scores on a sibling when a worker is down, draining or
  times out (each worker carries its own
  :class:`~transmogrifai_tpu.resilience.CircuitBreaker`; an open
  breaker routes around the worker without attempting it), sheds load
  with 429/503 when the whole fleet is saturated or empty, and
  aggregates fleet-wide ``/stats``. Canary routing needs NO router
  support: the lifecycle tier's deterministic blake2b hash-fraction
  routing means every worker routes a given request identically, so a
  fleet-wide canary stays consistent no matter which worker a request
  lands on (asserted cross-process in tests).
* **Rolling operations** — :meth:`FleetSupervisor.rolling_restart`
  drains-then-restarts one worker at a time: the router stops sending
  first (the worker leaves the ready set), SIGTERM lets the worker
  finish every accepted request (``shutdown(drain=True)``), and the
  next worker is only touched once the respawn is ready — a fleet-wide
  deploy/promote loses zero requests.

Fault sites: ``fleet.forward`` (one routed forward attempt) and
``fleet.spawn`` (one worker spawn) are registered in
``resilience.FAULT_SITES`` so chaos plans can score the fleet path
deterministically — on top of which the acceptance suite SIGKILLs real
worker processes mid-load (tests/test_fleet.py).

The always-on :func:`fleet_stats` tallies follow the
``engine_cache_stats`` discipline: stamped on every runner/bench
metrics doc, telemetry on or off.

Run it with ``python -m transmogrifai_tpu fleet params.json`` (knobs:
``customParams.fleetWorkers`` / ``fleetBasePort`` /
``workerRespawnMax`` / ``routerRetryBudget`` — see docs/fleet.md).
"""
from __future__ import annotations

import hashlib
import http.client
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import resilience, telemetry, workload
from .utils import locks

logger = logging.getLogger(__name__)

__all__ = ["FleetSupervisor", "WorkerHandle", "FleetError",
           "serve_fleet_http", "fleet_stats", "reset_fleet_stats",
           "DEFAULT_WORKERS", "DEFAULT_RESPAWN_MAX",
           "DEFAULT_RETRY_BUDGET", "DEFAULT_PROBE_INTERVAL_S",
           "DEFAULT_FORWARD_TIMEOUT_S"]

#: worker processes a fleet runs when the knob is unset
DEFAULT_WORKERS = 2

#: consecutive respawns of ONE worker before the supervisor gives up on
#: it (a worker that dies this many times in a row is broken, not
#: unlucky — respawning it forever would hide the defect)
DEFAULT_RESPAWN_MAX = 5

#: sibling retries the router may spend on one request beyond the first
#: attempt (idempotent scores only — the request either failed over or
#: the fleet sheds it loudly)
DEFAULT_RETRY_BUDGET = 2

#: supervisor probe cadence (process exit codes + /healthz → /readyz)
DEFAULT_PROBE_INTERVAL_S = 0.25

#: per-forward socket timeout; past it the router fails over to a
#: sibling (the worker may still complete — scoring is idempotent, so a
#: duplicate dispatch is waste, never corruption)
DEFAULT_FORWARD_TIMEOUT_S = 30.0

#: respawn backoff schedule: jittered exponential via RetryPolicy
#: (resilience.py) — delay_s(attempt) gives 0.1s, 0.2s, 0.4s ... ×
#: jitter, capped at 5s, so a crash-looping worker never spins the
#: supervisor hot and two supervisors never thundering-herd a port
_RESPAWN_BACKOFF = resilience.RetryPolicy(
    max_attempts=DEFAULT_RESPAWN_MAX + 1, base_delay_s=0.1,
    max_delay_s=5.0, multiplier=2.0, jitter=0.5)

#: per-worker breaker thresholds: 3 consecutive forward failures open
#: the breaker; the supervisor's ready-probe flips the worker back long
#: before the reset timeout in the common respawn case
_BREAKER_THRESHOLD = 3
_BREAKER_RESET_S = 5.0


# ---------------------------------------------------------------------------
# always-on tallies (runner/bench docs stamp these; telemetry mirrors)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"workers_spawned": 0, "workers_respawned": 0,
          "worker_crashes": 0, "workers_gave_up": 0,
          "routed_requests": 0, "routed_failed": 0,
          "forwards": 0, "failovers": 0, "breaker_routed_around": 0,
          "shed_429": 0, "shed_503": 0,
          "probe_failures": 0, "rolling_restarts": 0,
          "drained_restarts": 0,
          "worker_deadline_increases": 0, "worker_deadline_decreases": 0,
          "worker_deadline_clamped": 0, "worker_deadline_advisories": 0}


def fleet_stats() -> Dict[str, Any]:
    """Process-wide fleet tallies (always on, the ``engine_cache_stats``
    discipline) plus the derived ``failover_rate`` (failovers per routed
    request; None before any traffic)."""
    with _TALLY_LOCK:
        out: Dict[str, Any] = dict(_TALLY)
    out["failover_rate"] = (
        round(out["failovers"] / out["routed_requests"], 4)
        if out["routed_requests"] else None)
    return out


def reset_fleet_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n
    telemetry.counter(f"fleet.{key}").inc(n)  # lint: metric-name — keys are the fixed fleet_stats tally catalog


def _note_worker_deadline_counters(agg: Dict[str, Any]) -> None:
    """Mirror the latest fleet-wide online-adaptation totals the
    router's ``/stats`` aggregation summed out of its workers into the
    always-on tallies (PR 18): each key holds the HIGHEST total seen,
    so ``fleet_stats()`` reports the controllers' fleet-wide activity
    even after a worker respawn resets its own counters."""
    with _TALLY_LOCK:
        for src, dst in (("deadline_increases", "worker_deadline_increases"),
                         ("deadline_decreases", "worker_deadline_decreases"),
                         ("deadline_clamped", "worker_deadline_clamped"),
                         ("deadline_advisories",
                          "worker_deadline_advisories")):
            v = agg.get(src)
            if isinstance(v, int) and v > _TALLY[dst]:
                _TALLY[dst] = v


class FleetError(Exception):
    """Fleet misuse or a fleet that cannot start (no params, no port,
    every worker failed its spawn budget)."""


# ---------------------------------------------------------------------------
# worker handle
# ---------------------------------------------------------------------------

#: worker lifecycle states (docs/fleet.md probe-semantics table)
STARTING, READY, DRAINING, DEAD, FAILED = (
    "starting", "ready", "draining", "dead", "failed")


class WorkerHandle:
    """One supervised worker process: its Popen, bound port, probe
    state, respawn count and failover breaker. Mutated only by the
    supervisor's monitor thread (spawn/probe/respawn) and read by the
    router; ``state`` transitions are plain attribute writes of interned
    strings (atomic under the GIL)."""

    def __init__(self, wid: int, log_path: str):
        self.wid = wid
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.port_file: Optional[str] = None
        self.state = STARTING
        self.restarts = 0            # consecutive respawns (sustained-
                                     # healthy interval resets — _probe)
        self.spawns = 0              # lifetime spawns
        self.next_spawn_at = 0.0     # monotonic deadline for the respawn
        self.ready_since: Optional[float] = None   # monotonic READY entry
        self.awaiting_ready = False  # a respawn not yet probed READY
        self.last_exit: Optional[int] = None
        #: per-worker failover breaker: open ⇒ the router routes around
        #: this worker without attempting it
        self.breaker = resilience.CircuitBreaker(
            f"fleet.worker[{wid}]", failure_threshold=_BREAKER_THRESHOLD,
            reset_timeout_s=_BREAKER_RESET_S)

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        return ("127.0.0.1", self.port) if self.port else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def status(self) -> Dict[str, Any]:
        return {"worker": self.wid, "state": self.state,
                "port": self.port, "pid":
                (self.proc.pid if self.proc else None),
                "alive": self.alive(), "spawns": self.spawns,
                "restarts": self.restarts, "lastExit": self.last_exit,
                "breaker": self.breaker.state, "log": self.log_path}


# ---------------------------------------------------------------------------
# FleetSupervisor
# ---------------------------------------------------------------------------


class FleetSupervisor:
    """Spawn, probe and respawn N serve-worker processes.

    Each worker is a full ``python -m transmogrifai_tpu serve
    <params> --port <p> --port-file <f>`` — the SAME entry point a
    single-process deployment uses, so a fleet worker and a solo server
    can never diverge in behavior. Workers share the params file's
    registry + AOT bank on disk (both were built process-shareable:
    atomic version records, flocked CURRENT pointer, read-only bank).

    ``base_port`` pins worker ports to ``base_port + wid``; None lets
    each worker bind an ephemeral port and report it through its port
    file (the test-safe default). ``respawn_max`` bounds CONSECUTIVE
    respawns per worker; a worker that comes back ready resets its
    count. ``spawn_env`` overlays the inherited environment."""

    def __init__(self, params_path: str, workers: int = DEFAULT_WORKERS,
                 base_port: Optional[int] = None,
                 respawn_max: int = DEFAULT_RESPAWN_MAX,
                 probe_interval_s: float = DEFAULT_PROBE_INTERVAL_S,
                 backoff: Optional[resilience.RetryPolicy] = None,
                 log_dir: Optional[str] = None,
                 python: str = sys.executable,
                 spawn_env: Optional[Dict[str, str]] = None):
        if workers < 1:
            raise FleetError(f"workers must be >= 1, got {workers}")
        self.params_path = str(params_path)
        self.n_workers = int(workers)
        self.base_port = None if base_port is None else int(base_port)
        self.respawn_max = max(int(respawn_max), 0)
        self.probe_interval_s = max(float(probe_interval_s), 0.01)
        self.backoff = backoff or _RESPAWN_BACKOFF
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="tmog_fleet_")
        self.python = python
        self.spawn_env = dict(spawn_env) if spawn_env else None
        os.makedirs(self.log_dir, exist_ok=True)
        self.workers: List[WorkerHandle] = [
            WorkerHandle(i, os.path.join(self.log_dir,
                                         f"worker-{i}.log"))
            for i in range(self.n_workers)]
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # guards spawn/quiesce; order-witnessed under chaos tests
        self._lock = locks.witness_lock("fleet.FleetSupervisor._lock")
        #: workers the router must not send to (rolling restart quiesce)
        self._quiesced: set = set()

    # -- spawn -------------------------------------------------------------
    def _worker_env(self) -> Dict[str, str]:
        """The worker's environment: inherited, overlaid with
        ``spawn_env``, and with THIS package's parent directory on
        PYTHONPATH — a fleet started from a checkout must work from any
        cwd, not only the repo root (`-m transmogrifai_tpu` resolves in
        the child the same way it resolved in the parent)."""
        env = dict(os.environ)
        if self.spawn_env:
            env.update(self.spawn_env)
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        pp = env.get("PYTHONPATH", "")
        if pkg_parent not in pp.split(os.pathsep):
            env["PYTHONPATH"] = (pkg_parent + os.pathsep + pp
                                 if pp else pkg_parent)
        return env

    def _spawn(self, h: WorkerHandle) -> None:
        """(Re)spawn one worker. ``fleet.spawn`` fires first so chaos
        plans can fail a spawn deterministically; a failed spawn counts
        as a crash and re-enters the backoff schedule."""
        resilience.inject("fleet.spawn", worker=h.wid,
                          restarts=h.restarts)
        h.port_file = os.path.join(self.log_dir,
                                   f"worker-{h.wid}.port")
        try:
            os.unlink(h.port_file)
        except FileNotFoundError:
            pass
        port = (self.base_port + h.wid if self.base_port else 0)
        cmd = [self.python, "-m", "transmogrifai_tpu", "serve",
               self.params_path, "--port", str(port),
               "--port-file", h.port_file]
        # the worker's output is the SUPERVISOR's to own: an inherited
        # stdout ties worker logs to whatever terminal started the
        # fleet, and a PIPE nobody drains deadlocks the child (TMG309)
        with open(h.log_path, "ab") as log_fh:
            h.proc = subprocess.Popen(cmd, stdout=log_fh,
                                      stderr=subprocess.STDOUT,
                                      env=self._worker_env())
        h.spawns += 1
        h.state = STARTING
        h.port = port or None
        h.last_exit = None
        # a respawn (restarts>0) tallies workers_respawned exactly once,
        # at its FIRST ready probe — readiness flicker after that must
        # not re-count it now that the restarts counter resets lazily
        h.awaiting_ready = h.restarts > 0
        _tally("workers_spawned")
        logger.info("fleet: worker %d spawned (pid %d, port %s)",
                    h.wid, h.proc.pid, port or "ephemeral")

    def start(self) -> None:
        """Spawn every worker and start the monitor thread. Returns
        immediately; use :meth:`wait_ready` to block until the fleet
        serves."""
        for h in self.workers:
            try:
                self._spawn(h)
            except Exception as e:  # lint: broad-except — a failed first spawn enters the respawn/backoff path instead of killing the fleet
                logger.exception("fleet: spawn of worker %d failed",
                                 h.wid)
                self._note_crash(h, error=repr(e))
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="fleet-monitor",
                                         daemon=True)
        self._monitor.start()

    def wait_ready(self, min_workers: Optional[int] = None,
                   timeout_s: float = 120.0) -> List[WorkerHandle]:
        """Block until at least ``min_workers`` (default: all) workers
        are READY; raises :class:`FleetError` on timeout with each
        worker's status (and log path) in the message."""
        need = self.n_workers if min_workers is None else int(min_workers)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            ready = self.ready_workers()
            if len(ready) >= need:
                return ready
            if all(h.state == FAILED for h in self.workers):
                break
            time.sleep(0.05)
        raise FleetError(
            f"fleet not ready after {timeout_s:g}s (need {need}): "
            + json.dumps([h.status() for h in self.workers]))

    # -- monitor -----------------------------------------------------------
    def _note_crash(self, h: WorkerHandle, error: str = "") -> None:
        h.state = DEAD
        h.ready_since = None
        _tally("worker_crashes")
        h.restarts += 1  # lint: thread-escape — every caller holds FleetSupervisor._lock across _note_crash
        if h.restarts > self.respawn_max:
            h.state = FAILED
            _tally("workers_gave_up")
            telemetry.emit("fleet_worker", worker=h.wid, action="gave_up",
                           restarts=h.restarts)
            logger.error("fleet: worker %d exceeded respawn budget "
                         "(%d) — giving up%s", h.wid, self.respawn_max,
                         f": {error}" if error else "")
            return
        delay = self.backoff.delay_s(h.restarts - 1)
        h.next_spawn_at = time.monotonic() + delay
        telemetry.emit("fleet_worker", worker=h.wid, action="crashed",
                       exit=h.last_exit, respawn_in_s=round(delay, 3))
        logger.warning("fleet: worker %d died (exit %s)%s — respawn "
                       "%d/%d in %.2fs", h.wid, h.last_exit,
                       f" [{error}]" if error else "", h.restarts,
                       self.respawn_max, delay)

    def _probe(self, h: WorkerHandle) -> None:
        """liveness (/healthz) → readiness (/readyz) for one live
        worker. A draining worker (healthz 503) leaves the ready set
        immediately so the router stops sending BEFORE the process
        exits; a ready probe resets the consecutive-respawn count and
        closes the failover breaker."""
        if h.port is None and h.port_file:
            # ephemeral port: the worker writes it once bound
            try:
                with open(h.port_file) as fh:
                    h.port = int(fh.read().strip() or 0) or None
            except (OSError, ValueError):
                h.port = None
        if h.port is None:
            return                       # still booting
        def get(path: str) -> int:
            # one connection per probe: the stdlib front end is
            # HTTP/1.0 (no keep-alive), a reused connection would
            # CannotSendRequest on the second round-trip
            conn = http.client.HTTPConnection("127.0.0.1", h.port,
                                              timeout=2.0)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                resp.read()
                return resp.status
            finally:
                conn.close()

        try:
            live = get("/healthz")
            if live != 200:
                if h.state == READY:
                    logger.info("fleet: worker %d draining "
                                "(healthz %d)", h.wid, live)
                h.state = DRAINING
                return
            rdy = get("/readyz")
        except OSError:
            _tally("probe_failures")
            if h.state == READY:
                h.state = STARTING       # unreachable: not routable
            return
        if rdy == 200:
            self._note_ready(h)
        elif h.state == READY:
            h.state = STARTING           # lost readiness (queues full)
            h.ready_since = None

    def _note_ready(self, h: WorkerHandle) -> None:
        """One successful readiness probe. The consecutive-crash budget
        resets only after a SUSTAINED-healthy interval — READY for at
        least the backoff schedule's max delay (was: reset on the FIRST
        ready probe, which let a flicker-ready crash loop evade the
        budget forever, while the budget's original never-resetting
        draft meant a worker crashing once a day eventually exhausted
        ``workerRespawnMax``). After the interval, the next crash is a
        NEW incident, not the same crash loop."""
        now = time.monotonic()
        if h.state != READY:
            logger.info("fleet: worker %d ready on port %s (spawn %d)",
                        h.wid, h.port, h.spawns)
            h.ready_since = now
            if h.awaiting_ready:
                _tally("workers_respawned")
                h.awaiting_ready = False
        h.state = READY
        h.breaker.reset()
        if h.restarts and h.ready_since is not None \
                and now - h.ready_since >= self.backoff.max_delay_s:
            logger.info("fleet: worker %d healthy for %.1fs — "
                        "consecutive-crash budget reset", h.wid,
                        now - h.ready_since)
            with self._lock:   # restart_worker writes restarts under it
                h.restarts = 0

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            for h in self.workers:
                if self._stop.is_set():
                    return
                if h.state == FAILED:
                    continue
                with self._lock:
                    # a quiesced worker is under a DELIBERATE
                    # drain-then-restart: its exit is not a crash and
                    # restart_worker owns the respawn — the monitor
                    # only keeps probing it (the probe flips READY)
                    quiesced = h.wid in self._quiesced
                    if not quiesced and h.proc is not None \
                            and h.proc.poll() is not None \
                            and h.state != DEAD:
                        h.last_exit = h.proc.returncode
                        self._note_crash(h)
                    if not quiesced and h.state == DEAD \
                            and time.monotonic() >= h.next_spawn_at:
                        try:
                            self._spawn(h)  # lint: lock-blocking — the DEAD check and handle flip must be atomic with the spawn; probes never take _lock, so the stall is bounded by fork/exec
                        except Exception as e:  # lint: broad-except — a failed respawn re-enters the backoff schedule, the monitor survives
                            logger.exception(
                                "fleet: respawn of worker %d failed",
                                h.wid)
                            self._note_crash(h, error=repr(e))
                if h.alive() and h.state not in (DEAD, FAILED):
                    self._probe(h)
            self._stop.wait(self.probe_interval_s)

    # -- routing view ------------------------------------------------------
    def ready_workers(self) -> List[WorkerHandle]:
        return [h for h in self.workers
                if h.state == READY and h.wid not in self._quiesced
                and h.alive()]

    def status(self) -> Dict[str, Any]:
        return {"workers": [h.status() for h in self.workers],
                "ready": len(self.ready_workers()),
                "quiesced": sorted(self._quiesced),
                "fleet": fleet_stats()}

    # -- rolling operations ------------------------------------------------
    def restart_worker(self, h: WorkerHandle,
                       ready_timeout_s: float = 120.0) -> None:
        """Drain-then-restart ONE worker with zero dropped requests:
        quiesce it (the router stops sending first), SIGTERM it (the
        serve entry point drains every accepted request before exit),
        wait for the exit, respawn, wait READY, unquiesce."""
        with self._lock:
            self._quiesced.add(h.wid)
        try:
            if h.alive():
                h.state = DRAINING
                h.proc.send_signal(signal.SIGTERM)
                h.proc.wait(timeout=ready_timeout_s)
                h.last_exit = h.proc.returncode
            with self._lock:
                h.restarts = 0          # deliberate restart, not a crash
                self._spawn(h)  # lint: lock-blocking — quiesce/spawn must flip atomically or the monitor would respawn the same worker concurrently
            deadline = time.monotonic() + ready_timeout_s
            while time.monotonic() < deadline:
                if h.state == READY:
                    _tally("drained_restarts")
                    return
                time.sleep(0.05)
            raise FleetError(
                f"worker {h.wid} not ready after drained restart "
                f"({ready_timeout_s:g}s): {h.status()}")
        finally:
            with self._lock:
                self._quiesced.discard(h.wid)

    def rolling_restart(self, ready_timeout_s: float = 120.0) -> None:
        """Drain-then-restart every worker, ONE at a time — the
        fleet-wide deploy/promote primitive (a promoted CURRENT pointer
        is picked up by each worker as it reloads)."""
        _tally("rolling_restarts")
        telemetry.emit("fleet", action="rolling_restart",
                       workers=self.n_workers)
        for h in self.workers:
            if h.state == FAILED:
                continue
            self.restart_worker(h, ready_timeout_s=ready_timeout_s)

    # -- shutdown ----------------------------------------------------------
    def stop(self, drain: bool = True, timeout_s: float = 60.0) -> None:
        """Stop the fleet. ``drain`` SIGTERMs every worker (each drains
        its accepted requests); otherwise SIGKILL. Idempotent."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=timeout_s)
        for h in self.workers:
            if not h.alive():
                continue
            try:
                h.proc.send_signal(
                    signal.SIGTERM if drain else signal.SIGKILL)
            except OSError:
                continue
        deadline = time.monotonic() + timeout_s
        for h in self.workers:
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=max(deadline - time.monotonic(),
                                        0.1))
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(timeout=10)
            h.state = DEAD


# ---------------------------------------------------------------------------
# front-door router
# ---------------------------------------------------------------------------


def _route_key(name: str, records: Sequence[Any]) -> bytes:
    """Stable routing key: model name + the request's FIRST record,
    blake2b-hashed — the same O(1), deterministic discipline as canary
    routing (server._canaried), so the SAME request routes the same way
    across router restarts. Unserializable payloads key on the model
    name alone (routing must never fail a request)."""
    try:
        blob = json.dumps(records[0] if records else None,
                          sort_keys=True, default=str).encode()
    except (TypeError, ValueError):
        blob = b"?"
    return hashlib.blake2b(name.encode() + b"\0" + blob,
                           digest_size=8).digest()


def _rendezvous(key: bytes, workers: List[WorkerHandle]
                ) -> List[WorkerHandle]:
    """Highest-random-weight order of ``workers`` for ``key``: the
    first entry owns the request; the rest are the failover sequence.
    Adding/removing one worker remaps only that worker's share of the
    keyspace (consistent hashing without a ring)."""
    def score(h: WorkerHandle) -> int:
        return int.from_bytes(
            hashlib.blake2b(key + str(h.wid).encode(),
                            digest_size=8).digest(), "big")
    return sorted(workers, key=score, reverse=True)


def _forward(h: WorkerHandle, method: str, path: str,
             body: Optional[bytes], timeout_s: float,
             headers: Optional[Dict[str, str]] = None
             ) -> Tuple[int, bytes]:
    """One forward attempt to one worker; raises OSError on transport
    failure (the failover trigger). ``fleet.forward`` fires first so
    chaos plans can fail forwards deterministically. ``headers``
    overlay the defaults — the router's minted ``X-Tmog-Trace`` rides
    here (docs/observability.md "Distributed tracing")."""
    resilience.inject("fleet.forward", worker=h.wid, path=path)
    if h.port is None:
        # mid-respawn: the new process has not reported its port yet
        raise OSError(f"worker {h.wid} has no bound port")
    conn = http.client.HTTPConnection("127.0.0.1", h.port,
                                      timeout=timeout_s)
    try:
        hdrs = ({"Content-Type": "application/json"}
                if body is not None else {})
        if headers:
            hdrs.update(headers)
        conn.request(method, path, body, hdrs)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def serve_fleet_http(supervisor: FleetSupervisor,
                     host: str = "127.0.0.1", port: int = 8000,
                     retry_budget: int = DEFAULT_RETRY_BUDGET,
                     forward_timeout_s: float = DEFAULT_FORWARD_TIMEOUT_S):
    """Start the fleet front door on a daemon thread; returns the
    ``ThreadingHTTPServer`` (``.server_address`` carries the bound
    port, ``.shutdown()`` stops it). Stdlib only, like ``serve_http``.

    Routing table::

        POST /v1/models/<name>:score  consistent-hash + sibling failover
        POST /v1/models/<name>:*      any ready worker (shared registry;
                                      transport failures NOT retried —
                                      deploy/rollback are not idempotent)
        GET  /stats                   fleet aggregate + per-worker stats
        GET  /healthz                 router liveness + worker states
        GET  /readyz                  200 iff >= 1 worker is ready
        GET  <anything else>          proxied to any ready worker
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    #: statuses that mean "this worker cannot serve the request right
    #: now" — retry the idempotent score on a sibling. 429 retries too
    #: (ONE saturated queue is not fleet saturation); every sibling
    #: saturated sheds 429 to the client.
    _RETRY_STATUSES = frozenset({429, 503})

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):   # route through logging
            logger.debug("fleet-http: " + fmt, *args)

        def _send(self, code: int, doc: Any,
                  raw: Optional[bytes] = None) -> None:
            body = raw if raw is not None else json.dumps(
                doc, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # -- routed forward with failover ----------------------------------
        def _route(self, method: str, key: bytes,
                   body: Optional[bytes],
                   idempotent: bool = True,
                   wl_model: Optional[str] = None,
                   wl_records: Optional[list] = None) -> None:
            """``idempotent=False`` (deploy/rollback — they MUTATE the
            shared registry) never retries a transport failure: an
            OSError after the request was sent cannot prove the worker
            did not apply it, and a blind sibling retry would
            double-apply the pointer mutation. A worker-ANSWERED
            429/503 means the request was refused before it was
            applied, so the sibling retry stays safe either way.

            The router is the fleet's trace entry point: every routed
            request carries an ``X-Tmog-Trace`` traceparent — the
            client's if it sent one, MINTED here otherwise — so the
            router span, the worker's request span and the
            micro-batcher's batch span all share one trace id
            (docs/observability.md "Distributed tracing"). A failover
            retry reuses the same traceparent: one request, one trace,
            however many workers it visited.

            With the workload flight recorder installed
            (``customParams.workloadDir``), every routed :score
            request (``wl_model`` set by ``do_POST``) leaves one
            record carrying the ROUTING DECISION — owning worker,
            attempt count, failover count — and the client-visible
            outcome; the worker's own record (same trace id)
            contributes the payload and phase decomposition, and
            ``workload merge`` combines the two."""
            _tally("routed_requests")
            wl_t0 = time.perf_counter()
            trace_hdr = self.headers.get(telemetry.TRACE_HEADER)
            ctx = telemetry.parse_traceparent(trace_hdr)
            if ctx is None:
                ctx = telemetry.mint_trace()
                trace_hdr = telemetry.format_traceparent(*ctx)
            fwd_headers = {telemetry.TRACE_HEADER: trace_hdr}

            def _wl_record(status: int, worker: Optional[int],
                           attempts: int) -> None:
                if wl_model is None or not workload.recording_enabled():
                    return
                # no payload here: the worker's record (same trace id)
                # carries it via zero-copy splice, and merge folds the
                # two — the router's writer never serializes bodies
                workload.record_request(
                    model=wl_model,
                    rows=len(wl_records or ()),
                    trace_id=ctx[0],
                    t_arrival=wl_t0,
                    outcome={"status": status, "ok": status == 200},
                    phases={"e2e": time.perf_counter() - wl_t0},
                    route={"worker": worker, "attempts": attempts,
                           "failovers": max(attempts - 1, 0)})
            candidates = _rendezvous(key, supervisor.ready_workers())
            if not candidates:
                _tally("shed_503")
                _tally("routed_failed")
                _wl_record(503, None, 0)
                return self._send(503, {
                    "error": "no ready worker (fleet empty or all "
                             "draining)"})
            attempts = 0
            last: Optional[Tuple[int, bytes]] = None
            for h in candidates:
                if attempts > retry_budget:
                    break
                if not h.breaker.allow():
                    # open breaker: route AROUND without attempting —
                    # a known-bad worker must not eat the retry budget
                    _tally("breaker_routed_around")
                    continue
                attempts += 1
                if attempts > 1:
                    _tally("failovers")
                try:
                    _tally("forwards")
                    with telemetry.trace_scope(ctx):
                        with telemetry.span(
                                "fleet:route", worker=h.wid,
                                path=self.path, attempt=attempts):
                            status, payload = _forward(
                                h, method, self.path, body,
                                forward_timeout_s,
                                headers=fwd_headers)
                except OSError as e:
                    h.breaker.record_failure()
                    logger.warning("fleet: forward to worker %d "
                                   "failed (%r); %s", h.wid, e,
                                   "failing over" if idempotent
                                   else "NOT retried (non-idempotent)")
                    last = (503 if idempotent else 502, json.dumps(
                        {"error": f"worker {h.wid} unreachable: "
                                  f"{e!r}"
                                  + ("" if idempotent else
                                     " — not retried: the request "
                                     "mutates shared state and may "
                                     "already have applied")}).encode())
                    if not idempotent:
                        break
                    continue
                if status in _RETRY_STATUSES:
                    # the worker answered but cannot serve (draining /
                    # saturated) — transport is fine, don't trip the
                    # breaker, do try a sibling
                    last = (status, payload)
                    continue
                h.breaker.record_success()
                _wl_record(status, h.wid, attempts)
                return self._send(status, None, raw=payload)
            status = last[0] if last else 503
            _tally("routed_failed")
            _tally("shed_429" if status == 429 else "shed_503")
            _wl_record(status, None, attempts)
            self._send(status, None,
                       raw=last[1] if last else json.dumps(
                           {"error": "fleet saturated"}).encode())

        # -- aggregation ---------------------------------------------------
        def _stats(self) -> Dict[str, Any]:
            doc: Dict[str, Any] = {"fleet": supervisor.status(),
                                   "workers": {}, "aggregate": {}}
            agg: Dict[str, float] = {}
            for h in supervisor.workers:
                if h.state != READY or h.port is None:
                    doc["workers"][h.wid] = {"state": h.state}
                    continue
                try:
                    status, payload = _forward(h, "GET", "/stats", None,
                                               forward_timeout_s)
                    wdoc = json.loads(payload)
                except (OSError, ValueError) as e:
                    doc["workers"][h.wid] = {"state": h.state,
                                             "error": repr(e)}
                    continue
                doc["workers"][h.wid] = wdoc
                for k, v in (wdoc.get("server") or {}).items():
                    # counters only: the per-worker DERIVED ratios
                    # (coalescing factor, bank hit rate, slo
                    # attainment) are floats and must not be summed
                    if isinstance(v, int) and not isinstance(v, bool):
                        agg[k] = agg.get(k, 0) + v
            # fleet-wide ratios recomputed from the summed counters
            if agg.get("batches"):
                agg["batch_coalescing_factor"] = round(
                    agg.get("requests", 0) / agg["batches"], 3)
                agg["bank_hit_rate"] = round(
                    agg.get("bank_hit_batches", 0) / agg["batches"], 3)
            tracked = agg.get("slo_met", 0) + agg.get("slo_missed", 0)
            if tracked:
                agg["slo_attainment"] = round(
                    agg.get("slo_met", 0) / tracked, 4)
            _note_worker_deadline_counters(agg)
            doc["aggregate"] = agg
            return doc

        def _metrics(self) -> None:
            """The router's live Prometheus plane: its OWN registry
            (fleet.* counters) plus every READY worker's ``/metrics``
            scrape, merged by SUMMING samples with the same name+labels
            and re-rendering (`telemetry.render_prometheus_sum`) — the
            fleet-wide scrape surface ``/stats`` never was. Unreachable
            workers are skipped (scrape-time liveness is the probe
            loop's job, not the scraper's); the worker count that
            actually answered rides in ``fleet_metrics_workers``."""
            docs = [telemetry.parse_prometheus(
                telemetry.render_prometheus())]
            answered = 0
            for h in supervisor.ready_workers():
                try:
                    status, payload = _forward(h, "GET", "/metrics",
                                               None, forward_timeout_s)
                    if status != 200:
                        continue
                    # one parse per worker: it both validates (a bad
                    # scrape is skipped, not summed) and feeds the
                    # merge directly
                    docs.append(telemetry.parse_prometheus(
                        payload.decode("utf-8", "replace")))
                    answered += 1
                except (OSError, ValueError) as e:
                    logger.warning("fleet: /metrics scrape of worker "
                                   "%d failed: %r", h.wid, e)
            body = telemetry.merge_parsed_prometheus(docs)
            body += (f"# TYPE fleet_metrics_workers gauge\n"
                     f"fleet_metrics_workers {answered}\n")
            raw = body.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def do_GET(self):
            if self.path == "/metrics":
                return self._metrics()
            if self.path == "/healthz":
                return self._send(200, {
                    "status": "ok",
                    "workers": [h.status()
                                for h in supervisor.workers]})
            if self.path == "/readyz":
                n = len(supervisor.ready_workers())
                return self._send(200 if n else 503,
                                  {"ready": bool(n), "readyWorkers": n})
            if self.path == "/stats":
                return self._send(200, self._stats())
            ready = supervisor.ready_workers()
            if not ready:
                _tally("shed_503")
                return self._send(503, {"error": "no ready worker"})
            key = _route_key(self.path, [])
            return self._route("GET", key, None)

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b"{}"
            if self.path.startswith("/v1/models/") \
                    and self.path.endswith(":score"):
                name = self.path[len("/v1/models/"):-len(":score")]
                try:
                    records = (json.loads(body) or {}).get("records")
                except ValueError:
                    records = None
                key = _route_key(name, records
                                 if isinstance(records, list) else [])
                return self._route(
                    "POST", key, body, wl_model=name,
                    wl_records=(records if isinstance(records, list)
                                else None))
            # non-score POSTs (deploy/rollback) MUTATE the shared
            # registry: any ready worker serves them, but a transport
            # failure is NOT retried (idempotent=False above)
            key = _route_key(self.path, [])
            return self._route("POST", key, body, idempotent=False)

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever,
                         name="fleet-http", daemon=True)
    t.start()
    logger.info("fleet front door on %s:%d (%d workers)",
                *httpd.server_address, supervisor.n_workers)
    return httpd
