"""Continuous training — drift-triggered, crash-safe, storm-controlled.

PR 10's :class:`~transmogrifai_tpu.lifecycle.DriftSentinel` *detects*
drift and its shadow/canary rollout *deploys* models, but a human sat
between them: a drifting live stream degraded until someone noticed.
This module closes the loop — the TFX/continuous-pipeline story the
lifecycle tier was built for (PAPERS.md) — and treats robustness as the
spec, not a feature, because an unattended retrain loop has exactly two
failure modes that matter: doing nothing (a dead thread while the model
rots) and doing too much (a retrain-crash-retrain hot loop eating the
cluster). Every mechanism here exists to pin one of those down:

* :class:`RetrainController` subscribes to a tenant's drift windows
  (``DriftSentinel.subscribe`` via ``ModelServer.subscribe_drift``) and,
  after ``arm_windows`` CONSECUTIVE drifted windows (hysteresis — one
  noisy window never trains), launches a **supervised retrain job**:
  a subprocess run with the fleet.py discipline — explicit
  stdout/stderr into a per-job log, exit-code monitoring, heartbeat
  staleness detection (log/heartbeat-file mtime), kill-on-timeout and
  :class:`~transmogrifai_tpu.resilience.RetryPolicy` backoff between
  failures.
* The **job record** is crash-safe: one JSON file per job under the job
  directory, every write atomic (tmp + ``os.replace``), and the ACTIVE
  slot guarded by a kernel ``flock`` so two controllers — one per fleet
  worker, or a controller racing a manual ``registry promote`` — can
  never double-retrain or fight over the pointer (a SIGKILLed holder's
  lock releases automatically, the registry's own pointer flock guards
  the promote itself). A controller that died mid-job leaves a
  ``running`` record a fresh process's :meth:`RetrainController.recover`
  marks ``interrupted`` — replayable via :meth:`RetrainController.replay`
  when the trainer finished its export, with the CURRENT pointer
  untouched either way (fresh-interpreter SIGKILL test,
  tests/test_continual.py).
* **Warm start**: the trainer is handed the stable model dir whose
  persisted train-time sufficient statistics
  (:class:`~transmogrifai_tpu.fitstats.SufficientStats` monoids saved in
  ``model.json``) merge with the fresh slice's stats — the refit is a
  Chan merge plus ONE pass over the fresh data, not a rescan
  (``Workflow.with_warm_fit_stats``). Missing/corrupt stats degrade to
  a full refit with a TMG604 advisory (:func:`load_warm_stats`), never
  a failed job.
* **Evidence-gated promotion**: a successful job registers the new
  version (``continual.register`` fault site — a crash here leaves the
  record replayable and the pointer untouched) and hands it to the
  existing shadow/canary controller; the rollout's clean-window
  machinery promotes, and a failed candidate auto-rolls back while the
  stable version never stops serving. A candidate whose holdout metric
  is WORSE than the stable version's is rejected before any traffic
  touches it.
* **Storm control**: cooldown after ANY job (success or failure),
  jittered backoff stacked on failures, and a consecutive-failure
  budget after which the controller goes LOUDLY ``FAILED`` (TMG605
  advisory) and disarms — a broken trainer is paged about, not looped.

Fleet-wide (fleet.py): every serve worker may run a controller
(``customParams.retrainOnDrift``); the shared ACTIVE flock in the shared
registry's job directory guarantees exactly ONE retrains, and the other
workers observe the promote through the registry pointer they already
re-resolve. Always-on :func:`continual_stats` tallies ride on every
runner/bench metrics doc; state changes mirror through the
``on_retrain`` RunListener hook and ``continual.*`` counters.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from . import resilience, telemetry
from .utils import locks

logger = logging.getLogger(__name__)

__all__ = ["RetrainController", "ContinualError", "load_warm_stats",
           "validate_retrain_cmd",
           "continual_stats", "reset_continual_stats",
           "DEFAULT_ARM_WINDOWS", "DEFAULT_COOLDOWN_S",
           "DEFAULT_MAX_FAILURES", "DEFAULT_TIMEOUT_S",
           "DEFAULT_HEARTBEAT_TIMEOUT_S"]

#: consecutive drifted comparison windows before the controller arms a
#: retrain — hysteresis: one noisy window must never cost a train job
DEFAULT_ARM_WINDOWS = 2

#: seconds after ANY finished job (success or failure) during which new
#: triggers are suppressed — the floor of the storm-control schedule
DEFAULT_COOLDOWN_S = 300.0

#: consecutive failed/killed/rejected jobs before the controller goes
#: LOUDLY FAILED (TMG605) and disarms
DEFAULT_MAX_FAILURES = 3

#: hard wall-clock bound on one retrain job; past it the trainer is
#: SIGKILLed and the job counts as a failure
DEFAULT_TIMEOUT_S = 3600.0

#: staleness bound on the job's heartbeat (its log file's — or the
#: TMOG_RETRAIN_HEARTBEAT file's — mtime): a trainer silent for this
#: long is stuck, not slow, and is killed rather than waited on
DEFAULT_HEARTBEAT_TIMEOUT_S = 600.0

#: backoff stacked ON TOP of the cooldown after failed jobs (jittered
#: exponential, the fleet respawn discipline): failures 1, 2, 3 wait
#: cooldown + ~30s, ~60s, ~120s ... capped at 10 min
_FAILURE_BACKOFF = resilience.RetryPolicy(
    max_attempts=DEFAULT_MAX_FAILURES + 1, base_delay_s=30.0,
    max_delay_s=600.0, multiplier=2.0, jitter=0.25)

#: drift advisory rules that count as a drifted window
_DRIFT_RULES = frozenset({"TMG601", "TMG602"})

JOBS_DIR = "jobs"
ACTIVE_LOCK = "ACTIVE.lock"

#: job record states (docs/lifecycle.md state machine)
PENDING, RUNNING, REGISTERED, DEPLOYED, SUCCEEDED = (
    "pending", "running", "registered", "deployed", "succeeded")
FAILED, KILLED, REJECTED, INTERRUPTED = (
    "failed", "killed", "rejected", "interrupted")

_TERMINAL_BAD = frozenset({FAILED, KILLED, REJECTED, INTERRUPTED})


# ---------------------------------------------------------------------------
# always-on tallies (runner/bench docs stamp these; telemetry mirrors)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"windows_seen": 0, "drifted_windows": 0, "triggers": 0,
          "suppressed_cooldown": 0, "suppressed_active": 0,
          "suppressed_disarmed": 0, "jobs_started": 0,
          "jobs_succeeded": 0, "jobs_failed": 0, "jobs_killed": 0,
          "jobs_recovered": 0, "jobs_replayed": 0,
          "candidates_rejected": 0, "orphans_killed": 0, "gave_up": 0,
          "warm_starts": 0, "full_refit_fallbacks": 0}


def continual_stats() -> Dict[str, int]:
    """Snapshot of the process-wide continuous-training tallies (always
    on, the ``engine_cache_stats`` discipline): drift windows seen,
    triggers armed vs storm-suppressed, job outcomes, holdout
    rejections, recovery/replay traffic and the warm-start vs
    full-refit split."""
    with _TALLY_LOCK:
        return dict(_TALLY)


def reset_continual_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n
    telemetry.counter(f"continual.{key}").inc(n)  # lint: metric-name — keys are the fixed continual_stats tally catalog


class ContinualError(Exception):
    """Controller misuse: no registry, malformed trainer command,
    replay of a non-replayable job."""


def validate_retrain_cmd(cmd) -> List[str]:
    """The ONE trainer-command shape check (`cli check`'s TMG001, the
    serve wiring and the controller constructor all call it — one
    predicate, no drift): a non-empty list of argv strings."""
    if (not isinstance(cmd, (list, tuple)) or not cmd
            or not all(isinstance(c, str) for c in cmd)):
        raise ContinualError(
            f"retrain command must be a non-empty list of argv "
            f"strings, got {cmd!r}")
    return [str(c) for c in cmd]


# ---------------------------------------------------------------------------
# warm-start loading (the graceful-degradation seam)
# ---------------------------------------------------------------------------


def load_warm_stats(model_dir: Optional[str]):
    """The stable model's persisted sufficient statistics for
    ``Workflow.with_warm_fit_stats`` — or ``None`` with a TMG604
    advisory when the dir is missing, predates the persistence, or the
    block is corrupt. The retrain then runs a FULL refit over the fresh
    window: warm start is an optimization, never a dependency."""
    from . import fitstats, lint
    stats = None
    if model_dir:
        stats = fitstats.load_sufficient_stats(model_dir)
    if stats:
        _tally("warm_starts")
        return stats
    _tally("full_refit_fallbacks")
    f = lint.Finding(
        "TMG604", "warm-start sufficient statistics unavailable at "
        f"{model_dir!r} — the retrain runs a full refit over the "
        "fresh window")
    lint.emit_findings([f])
    logger.warning("continual: %s", f.format())
    return None


def _pid_alive(pid: Optional[int]) -> bool:
    if not pid:
        return False
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError):
        return False
    return True


def _metric_of(doc: Any, key: str) -> Optional[float]:
    """Depth-first search for a numeric metric named ``key`` in a
    nested metrics document (train summaries nest the evaluation under
    stages/trainEvaluation/holdoutEvaluation)."""
    if isinstance(doc, dict):
        v = doc.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        for sub in doc.values():
            found = _metric_of(sub, key)
            if found is not None:
                return found
    elif isinstance(doc, (list, tuple)):
        for sub in doc:
            found = _metric_of(sub, key)
            if found is not None:
                return found
    return None


# ---------------------------------------------------------------------------
# RetrainController
# ---------------------------------------------------------------------------


class RetrainController:
    """Drift → supervised retrain job → register → evidence-gated
    rollout, safe to run unattended.

    ``retrain_cmd`` is the trainer: any command (typically a project
    training script) that reads its contract from the environment —

    ========================  =============================================
    ``TMOG_RETRAIN_MODEL``    the model name being retrained
    ``TMOG_RETRAIN_OUT``      output dir: the trainer MUST save the new
                              model under ``<out>/model`` and MAY ship an
                              AOT export under ``<out>/export`` and a
                              metrics doc at ``<out>/metrics.json``
    ``TMOG_RETRAIN_STABLE``   the stable version's model dir (warm-start
                              source: :func:`load_warm_stats`)
    ``TMOG_RETRAIN_TRIGGER``  JSON file with the drift window that armed
                              this job (the sentinel's last report)
    ``TMOG_RETRAIN_HEARTBEAT``  a file the trainer may touch to prove
                              liveness; the job log's mtime counts too
    ========================  =============================================

    The controller monitors exit code + heartbeat, kills on timeout or
    staleness, and on success registers the export
    (``continual.register`` fault site) then hands it to the attached
    server's shadow/canary rollout — promotion stays evidence-gated and
    a failed candidate auto-rolls back with the stable version serving
    throughout. See the module docstring for the crash-safety and
    storm-control contracts."""

    def __init__(self, name: str, registry, retrain_cmd: Sequence[str],
                 job_dir: Optional[str] = None, server=None,
                 arm_windows: int = DEFAULT_ARM_WINDOWS,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 max_failures: int = DEFAULT_MAX_FAILURES,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 heartbeat_timeout_s: float = DEFAULT_HEARTBEAT_TIMEOUT_S,
                 backoff: Optional[resilience.RetryPolicy] = None,
                 deploy_mode: str = "canary",
                 canary_fraction: Optional[float] = None,
                 window_requests: Optional[int] = None,
                 promote_windows: Optional[int] = None,
                 holdout_metric: str = "AuPR",
                 holdout_tolerance: float = 0.0,
                 spawn_env: Optional[Dict[str, str]] = None,
                 trace_dir: Optional[str] = None):
        if registry is None:
            raise ContinualError("RetrainController needs a registry")
        cmd = validate_retrain_cmd(retrain_cmd)
        if deploy_mode not in ("canary", "shadow"):
            raise ContinualError(
                f"deploy_mode must be 'canary' or 'shadow', "
                f"got {deploy_mode!r}")
        self.name = str(name)
        self.registry = registry
        self.retrain_cmd = cmd
        self.server = server
        self.job_dir = str(job_dir) if job_dir else os.path.join(
            registry.root, self.name, "retrain")
        self.arm_windows = max(int(arm_windows), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.max_failures = max(int(max_failures), 1)
        self.timeout_s = float(timeout_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.backoff = backoff or _FAILURE_BACKOFF
        self.deploy_mode = deploy_mode
        self.canary_fraction = canary_fraction
        self.window_requests = window_requests
        self.promote_windows = promote_windows
        self.holdout_metric = str(holdout_metric)
        self.holdout_tolerance = float(holdout_tolerance)
        self.spawn_env = dict(spawn_env) if spawn_env else None
        #: shared trace-shard directory (customParams.traceDir): the
        #: retrain subprocess inherits it (TMOG_TRACE_DIR) so its
        #: runner writes a shard into the SAME merge set as the fleet
        #: (docs/observability.md "Distributed tracing")
        self.trace_dir = str(trace_dir) if trace_dir else None
        os.makedirs(os.path.join(self.job_dir, JOBS_DIR), exist_ok=True)
        self._lock = locks.witness_lock("continual.RetrainController._lock")
        self._streak = 0
        self._failures = 0
        self._disarmed = False
        self._cooldown_until = 0.0           # monotonic deadline
        self._thread: Optional[threading.Thread] = None
        self.last_job: Optional[Dict[str, Any]] = None

    # -- job record IO (atomic, one file per job) --------------------------
    def _job_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir, JOBS_DIR, f"{job_id}.json")

    def _write_job(self, job: Dict[str, Any]) -> None:
        path = self._job_path(job["jobId"])
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(job, fh, indent=1, default=str)
        os.replace(tmp, path)

    def jobs(self) -> List[Dict[str, Any]]:
        """Every job record, oldest first (createdAt order)."""
        d = os.path.join(self.job_dir, JOBS_DIR)
        out: List[Dict[str, Any]] = []
        try:
            files = [f for f in os.listdir(d) if f.endswith(".json")]
        except FileNotFoundError:
            return out
        for fn in files:
            try:
                with open(os.path.join(d, fn)) as fh:
                    doc = json.load(fh)
            except (OSError, ValueError):
                logger.warning("continual: unreadable job record %s", fn)
                continue
            # the per-job trigger-evidence sidecar is JSON too — only
            # documents with a jobId are job records
            if isinstance(doc, dict) and doc.get("jobId"):
                out.append(doc)
        out.sort(key=lambda j: (j.get("createdAt", 0.0),
                                j.get("jobId", "")))
        return out

    def job(self, job_id: str) -> Dict[str, Any]:
        with open(self._job_path(job_id)) as fh:
            return json.load(fh)

    # -- the drift trigger (sentinel window callback) ----------------------
    def attach(self) -> "RetrainController":
        """Subscribe to the attached server's drift windows for this
        tenant (``ModelServer.subscribe_drift`` — the subscription
        survives sentinel rebuilds across promotes/reloads)."""
        if self.server is None:
            raise ContinualError("attach() needs a server "
                                 "(RetrainController(server=...))")
        self.server.subscribe_drift(self.name, self.on_window)
        return self

    def on_window(self, findings: List[Any],
                  report: Optional[Dict[str, Any]]) -> None:
        """One completed drift-comparison window: advance the hysteresis
        streak (drifted) or reset it (clean); arm a retrain once
        ``arm_windows`` consecutive drifted windows accumulate and the
        storm controls (cooldown, active job, failure budget) allow.
        Cheap and non-blocking — it runs on the sentinel thread."""
        drifted = any(getattr(f, "rule", None) in _DRIFT_RULES
                      for f in findings)
        _tally("windows_seen")
        if drifted:
            _tally("drifted_windows")
        with self._lock:
            self._streak = self._streak + 1 if drifted else 0
            if self._streak < self.arm_windows:
                return
            if self._disarmed:
                _tally("suppressed_disarmed")
                return
            if time.monotonic() < self._cooldown_until:
                _tally("suppressed_cooldown")
                return
            if self._thread is not None and self._thread.is_alive():
                _tally("suppressed_active")
                return
            self._streak = 0
            job = self._new_job(report)
            self._thread = threading.Thread(
                target=self._run_job, args=(job,),
                name=f"continual-{self.name}", daemon=True)
            self._thread.start()
        _tally("triggers")
        telemetry.emit("retrain", model=self.name, action="trigger",
                       job=job["jobId"])
        logger.warning("continual: %s armed a retrain after %d drifted "
                       "window(s) (job %s)", self.name, self.arm_windows,
                       job["jobId"])

    def trigger(self, reason: str = "manual") -> Optional[str]:
        """Operator entry point: arm a retrain NOW (storm controls still
        apply). Returns the job id, or None when suppressed."""
        with self._lock:
            if self._disarmed:
                _tally("suppressed_disarmed")
                return None
            if time.monotonic() < self._cooldown_until:
                _tally("suppressed_cooldown")
                return None
            if self._thread is not None and self._thread.is_alive():
                _tally("suppressed_active")
                return None
            job = self._new_job({"reason": reason})
            self._thread = threading.Thread(
                target=self._run_job, args=(job,),
                name=f"continual-{self.name}", daemon=True)
            self._thread.start()
        _tally("triggers")
        telemetry.emit("retrain", model=self.name, action="trigger",
                       job=job["jobId"])
        return job["jobId"]

    def wait_idle(self, timeout_s: float = 300.0) -> bool:
        """Block until no job thread is running (tests/benches)."""
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            return not t.is_alive()
        return True

    # -- the supervised job ------------------------------------------------
    def _new_job(self, trigger: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        # wall-clock by design: job timestamps are compared across
        # processes and displayed, never used as durations
        now = time.time()   # lint: wall-clock
        job_id = f"job-{int(now * 1000):013d}-{os.getpid()}"
        out_dir = os.path.join(self.job_dir, JOBS_DIR, job_id + ".out")
        # the triggering window's trace context (or a fresh root when
        # the trigger ran untraced): persisted in the record so the
        # retrain SUBPROCESS joins the same trace via TMOG_TRACE_PARENT
        # — and so replay()/recover() keep the original identity
        ctx = telemetry.current_trace() or telemetry.mint_trace()
        return {"jobId": job_id, "model": self.name, "state": PENDING,
                "trigger": trigger, "cmd": list(self.retrain_cmd),
                "outDir": out_dir,
                "log": self._job_path(job_id)[:-5] + ".log",
                "traceparent": telemetry.format_traceparent(*ctx),
                "createdAt": now, "controllerPid": os.getpid(),
                "pid": None, "exitCode": None, "version": None,
                "error": None, "replayable": False}

    def _acquire_slot(self) -> Optional[int]:
        """Non-blocking kernel flock on the ACTIVE job slot — at most
        ONE retrain across every controller sharing this job dir (one
        per fleet worker). A SIGKILLed holder's lock releases
        automatically; a busy slot suppresses the trigger, it never
        queues a second job."""
        import fcntl
        path = os.path.join(self.job_dir, ACTIVE_LOCK)
        fd = os.open(path, os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return None
        locks.witness_acquire("continual.active_slot.flock")
        return fd

    def _spawn_env(self, job: Dict[str, Any],
                   stable_dir: Optional[str]) -> Dict[str, str]:
        env = dict(os.environ)
        if self.spawn_env:
            env.update(self.spawn_env)
        # a controller started from a checkout must spawn trainers that
        # can import the package from any cwd (the fleet discipline)
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        pp = env.get("PYTHONPATH", "")
        if pkg_parent not in pp.split(os.pathsep):
            env["PYTHONPATH"] = (pkg_parent + os.pathsep + pp
                                 if pp else pkg_parent)
        env["TMOG_RETRAIN_MODEL"] = self.name
        env["TMOG_RETRAIN_OUT"] = job["outDir"]
        env["TMOG_RETRAIN_STABLE"] = stable_dir or ""
        env["TMOG_RETRAIN_TRIGGER"] = job["outDir"] + ".trigger.json"
        env["TMOG_RETRAIN_HEARTBEAT"] = job["outDir"] + ".heartbeat"
        # trace inheritance: the trainer's spans join the triggering
        # window's trace, its merged-trace row is named "retrain", and
        # (when the fleet shares a shard directory) its shard lands in
        # the same trace merge set
        if job.get("traceparent"):
            env[telemetry.TRACE_ENV] = job["traceparent"]
            env[telemetry.TRACE_ROLE_ENV] = "retrain"
        if self.trace_dir:
            env["TMOG_TRACE_DIR"] = self.trace_dir
        return env

    def _run_job(self, job: Dict[str, Any]) -> None:
        """The job thread: slot flock → record → spawn → supervise →
        register → deploy. Never raises (its own never-raises boundary —
        an exception anywhere marks the job failed and feeds the storm
        controls)."""
        slot = self._acquire_slot()
        if slot is None:
            # a sibling controller (another fleet worker) is already
            # retraining: this trigger is redundant, not queued
            _tally("suppressed_active")
            logger.info("continual: %s retrain slot held elsewhere; "
                        "trigger dropped (job %s never started)",
                        self.name, job["jobId"])
            return
        import fcntl
        try:
            try:
                # the controller's own spans ride the job's trace: one
                # trace covers drift window → controller → trainer
                # subprocess → register/deploy
                with telemetry.trace_scope(job.get("traceparent")):
                    with telemetry.span("continual:job",
                                        model=self.name,
                                        job=job["jobId"]):
                        self._execute_job(job)
            except Exception as e:  # lint: broad-except — the job thread is a never-raises boundary; any failure feeds the storm controls
                logger.exception("continual: job %s failed",
                                 job["jobId"])
                self._fail(job, repr(e))
        finally:
            self.last_job = job
            with self._lock:
                self._cooldown_until = max(
                    self._cooldown_until,
                    time.monotonic() + self.cooldown_s)
            locks.witness_release("continual.active_slot.flock")
            try:
                fcntl.flock(slot, fcntl.LOCK_UN)
            except OSError:
                pass
            os.close(slot)

    def _execute_job(self, job: Dict[str, Any]) -> None:
        resilience.inject("continual.retrain", model=self.name,
                          job=job["jobId"])
        stable_dir = None
        try:
            stable_dir = self.registry.resolve(self.name)["modelDir"]
        except Exception:  # lint: broad-except — no promoted stable version: the trainer cold-fits
            logger.info("continual: %s has no stable version to "
                        "warm-start from", self.name)
        os.makedirs(job["outDir"], exist_ok=True)
        env = self._spawn_env(job, stable_dir)
        # the drift evidence that armed this job rides beside it
        trig_tmp = env["TMOG_RETRAIN_TRIGGER"] + ".tmp"
        with open(trig_tmp, "w") as fh:
            json.dump(job.get("trigger") or {}, fh, default=str)
        os.replace(trig_tmp, env["TMOG_RETRAIN_TRIGGER"])
        with open(job["log"], "ab") as log_fh:
            proc = subprocess.Popen(self.retrain_cmd, stdout=log_fh,
                                    stderr=subprocess.STDOUT, env=env)
        job.update(state=RUNNING, pid=proc.pid,
                   startedAt=time.time())   # lint: wall-clock
        self._write_job(job)
        _tally("jobs_started")
        telemetry.emit("retrain", model=self.name, action="start",
                       job=job["jobId"])
        logger.info("continual: job %s running (pid %d): %s",
                    job["jobId"], proc.pid, " ".join(self.retrain_cmd))
        self._supervise(job, proc, env["TMOG_RETRAIN_HEARTBEAT"])

    def _supervise(self, job: Dict[str, Any], proc: subprocess.Popen,
                   hb_path: str) -> None:
        """Exit-code + heartbeat monitoring with kill-on-timeout: the
        trainer proves liveness by writing (log mtime) or touching its
        heartbeat file; a silent or overlong job is SIGKILLed and
        counted as a failure — a stuck trainer must never hold the
        retrain slot forever."""
        deadline = time.monotonic() + self.timeout_s
        spawn_wall = time.time()   # lint: wall-clock — compared to file mtimes
        while proc.poll() is None:
            try:
                now = time.monotonic()
                if now > deadline:
                    self._kill(job, proc,
                               f"timeout after {self.timeout_s:g}s")
                    return
                hb = spawn_wall
                for p in (job["log"], hb_path):
                    try:
                        hb = max(hb, os.path.getmtime(p))
                    except OSError:
                        pass
                stale = time.time() - hb   # lint: wall-clock — mtime delta
                if stale > self.heartbeat_timeout_s:
                    self._kill(job, proc,
                               f"stalled: no heartbeat for "
                               f"{stale:.0f}s (> "
                               f"{self.heartbeat_timeout_s:g}s)")
                    return
            except Exception:  # lint: broad-except — a probe hiccup must not kill supervision (TMG310: the monitor loop catches and lives)
                logger.exception("continual: heartbeat probe failed "
                                 "for job %s", job["jobId"])
            time.sleep(0.1)
        rc = proc.returncode
        job["exitCode"] = rc
        if rc != 0:
            self._fail(job, f"trainer exited {rc} (log: {job['log']})")
            return
        self._register_and_deploy(job)

    def _kill(self, job: Dict[str, Any], proc: subprocess.Popen,
              reason: str) -> None:
        logger.error("continual: killing job %s: %s", job["jobId"],
                     reason)
        try:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            pass
        job["exitCode"] = proc.returncode
        _tally("jobs_killed")
        self._fail(job, reason, state=KILLED)

    # -- completion: register → holdout gate → deploy ----------------------
    def _register_and_deploy(self, job: Dict[str, Any]) -> None:
        model_dir = os.path.join(job["outDir"], "model")
        from .model_io import MODEL_JSON
        if not os.path.exists(os.path.join(model_dir, MODEL_JSON)):
            self._fail(job, f"trainer exited 0 but produced no model at "
                            f"{model_dir!r}")
            return
        bank_dir = os.path.join(job["outDir"], "export")
        if not os.path.isdir(bank_dir):
            bank_dir = None
        metrics: Optional[Dict[str, Any]] = None
        mpath = os.path.join(job["outDir"], "metrics.json")
        try:
            with open(mpath) as fh:
                metrics = json.load(fh)
        except (OSError, ValueError):
            pass
        if not self._holdout_ok(job, metrics):
            return
        resilience.inject("continual.register", model=self.name,
                          job=job["jobId"])
        vid = self.registry.register(self.name, model_dir,
                                     bank_dir=bank_dir,
                                     train_metrics=metrics)
        job.update(state=REGISTERED, version=vid, replayable=True)
        self._write_job(job)
        telemetry.emit("retrain", model=self.name, action="registered",
                       job=job["jobId"], version=vid)
        logger.info("continual: job %s registered %s@%s", job["jobId"],
                    self.name, vid)
        self._deploy(job, vid)

    def _holdout_ok(self, job: Dict[str, Any],
                    metrics: Optional[Dict[str, Any]]) -> bool:
        """Reject a candidate measurably WORSE than the stable version
        on the holdout metric — before any live traffic touches it.
        Missing metrics on either side skip the gate (the rollout's
        clean-window evidence still gates promotion)."""
        cand = _metric_of(metrics, self.holdout_metric)
        stable = None
        try:
            cur = self.registry.current(self.name)
            if cur:
                stable = _metric_of(
                    self.registry.record(self.name, cur)
                    .get("trainMetrics"), self.holdout_metric)
        except Exception:  # lint: broad-except — an unreadable stable record skips the gate, never fails the job
            logger.exception("continual: stable metrics unreadable")
        if cand is None or stable is None:
            logger.info("continual: holdout gate skipped for job %s "
                        "(%s: candidate=%s stable=%s)", job["jobId"],
                        self.holdout_metric, cand, stable)
            return True
        if cand + self.holdout_tolerance < stable:
            _tally("candidates_rejected")
            job.update(state=REJECTED,
                       error=f"holdout {self.holdout_metric} "
                             f"{cand:.4f} < stable {stable:.4f}",
                       finishedAt=time.time())   # lint: wall-clock
            self._write_job(job)
            telemetry.emit("retrain", model=self.name, action="rejected",
                           job=job["jobId"], error=job["error"])
            logger.warning("continual: job %s REJECTED before deploy: "
                           "%s", job["jobId"], job["error"])
            # a rejection spends failure budget: a trainer that keeps
            # producing worse models must eventually go LOUD, not loop
            self._count_failure()
            return False
        logger.info("continual: holdout gate passed for job %s "
                    "(%s: %.4f >= stable %.4f)", job["jobId"],
                    self.holdout_metric, cand, stable)
        return True

    def _deploy(self, job: Dict[str, Any], vid: str) -> None:
        if self.server is None:
            # no serving tier attached: registered, awaiting a manual
            # (or registry-CLI) promote — still a successful job
            job.update(state=SUCCEEDED,
                       finishedAt=time.time())   # lint: wall-clock
            self._write_job(job)
            self._succeed(job)
            return
        kw: Dict[str, Any] = {}
        if self.canary_fraction is not None:
            kw["fraction"] = float(self.canary_fraction)
        if self.window_requests is not None:
            kw["window_requests"] = int(self.window_requests)
        if self.promote_windows is not None:
            kw["promote_windows"] = int(self.promote_windows)
        # drift_gate=False: the stable baseline keeps flagging the very
        # window this candidate was trained on — that advisory is the
        # rollout's CAUSE, not evidence against the candidate (the
        # failure/SLO/parity evidence still gates, and the sentinel
        # rebuilds on the candidate's own baseline at promote)
        self.server.deploy(self.name, vid, mode=self.deploy_mode,
                           drift_gate=False, **kw)
        job.update(state=DEPLOYED,
                   finishedAt=time.time())   # lint: wall-clock
        self._write_job(job)
        telemetry.emit("retrain", model=self.name, action="deployed",
                       job=job["jobId"], version=vid)
        logger.info("continual: job %s deployed %s@%s as a %s rollout "
                    "(evidence-gated promotion from here)",
                    job["jobId"], self.name, vid, self.deploy_mode)
        self._succeed(job)

    # -- storm-control bookkeeping -----------------------------------------
    def _succeed(self, job: Dict[str, Any]) -> None:
        _tally("jobs_succeeded")
        with self._lock:
            self._failures = 0

    def _count_failure(self) -> None:
        with self._lock:
            self._failures += 1
            failures = self._failures
            self._cooldown_until = max(
                self._cooldown_until,
                time.monotonic() + self.cooldown_s
                + self.backoff.delay_s(min(failures - 1,
                                           self.backoff.max_attempts - 1)))
            if failures >= self.max_failures and not self._disarmed:
                self._disarmed = True
                disarm = True
            else:
                disarm = False
        if disarm:
            _tally("gave_up")
            from . import lint
            f = lint.Finding(
                "TMG605", f"retrain controller for {self.name!r} FAILED: "
                f"{failures} consecutive job failure(s) >= budget "
                f"{self.max_failures} — retraining DISARMED; inspect "
                f"the job records under {self.job_dir!r} and re-arm "
                "(docs/lifecycle.md runbook)")
            lint.emit_findings([f])
            telemetry.emit("retrain", model=self.name, action="gave_up",
                           error=f.message)
            logger.error("continual: %s", f.format())

    def _fail(self, job: Dict[str, Any], error: str,
              state: str = FAILED) -> None:
        job.update(state=state, error=error,
                   finishedAt=time.time())   # lint: wall-clock
        try:
            self._write_job(job)
        except OSError:
            logger.exception("continual: job record write failed")
        _tally("jobs_failed")
        telemetry.emit("retrain", model=self.name, action="failed",
                       job=job["jobId"], error=error)
        logger.error("continual: job %s %s: %s", job["jobId"], state,
                     error)
        self._count_failure()

    def rearm(self) -> None:
        """Operator reset after a FAILED (disarmed) controller: clears
        the failure budget and the disarm flag. The job records stay —
        they are the audit trail."""
        with self._lock:
            self._failures = 0
            self._disarmed = False
            self._streak = 0
        logger.warning("continual: %s re-armed by operator", self.name)

    # -- crash recovery ----------------------------------------------------
    def recover(self) -> List[Dict[str, Any]]:
        """Replay the on-disk job history after a controller restart:

        * ``running``/``pending`` records whose controller pid is dead
          are marked ``interrupted`` (``replayable`` when the trainer
          finished its export — :meth:`replay` completes the
          register+deploy half without retraining); a still-alive
          orphan trainer is killed (nothing supervises it anymore).
        * The consecutive-failure budget and the cooldown clock are
          restored from the trailing records, so a crash-looping
          controller cannot reset its own storm controls by dying.

        Returns the records it repaired."""
        repaired: List[Dict[str, Any]] = []
        records = self.jobs()
        for job in records:
            if job.get("state") not in (RUNNING, PENDING):
                continue
            # pid liveness is only meaningful while the job could still
            # legitimately be running: past its own kill bound (timeout
            # + heartbeat + slack) a matching pid is almost certainly
            # REUSED by an unrelated process (reboot, long downtime) —
            # treat the record as dead and never SIGKILL a stranger
            age = time.time() - job.get("createdAt", 0.0)   # lint: wall-clock
            stale = age > (self.timeout_s + self.heartbeat_timeout_s
                           + 600.0)
            if not stale and _pid_alive(job.get("controllerPid")):
                continue                    # a live sibling owns it
            if not stale and _pid_alive(job.get("pid")):
                try:
                    os.kill(int(job["pid"]), signal.SIGKILL)
                    _tally("orphans_killed")
                    logger.warning(
                        "continual: killed orphan trainer pid %s of "
                        "job %s (its controller died)", job["pid"],
                        job["jobId"])
                except OSError:
                    pass
            from .model_io import MODEL_JSON
            job["replayable"] = os.path.exists(os.path.join(
                job.get("outDir") or "", "model", MODEL_JSON))
            job.update(state=INTERRUPTED,
                       error="controller died mid-job",
                       finishedAt=time.time())   # lint: wall-clock
            self._write_job(job)
            repaired.append(job)
            _tally("jobs_recovered")
            telemetry.emit("retrain", model=self.name,
                           action="recovered", job=job["jobId"])
            logger.warning("continual: job %s interrupted by a dead "
                           "controller (replayable=%s)", job["jobId"],
                           job["replayable"])
        # storm controls survive the crash: trailing bad outcomes
        # restore the failure budget, the last job restarts the cooldown
        trailing = 0
        records = self.jobs()
        for job in reversed(records):
            if job.get("state") in _TERMINAL_BAD:
                trailing += 1
            else:
                break
        with self._lock:
            self._failures = max(self._failures, trailing)
            if self._failures >= self.max_failures:
                self._disarmed = True
            if records:
                # wall-clock by design: createdAt crosses processes
                since = time.time() - records[-1].get("createdAt", 0.0)   # lint: wall-clock
                remaining = self.cooldown_s - since
                if remaining > 0:
                    self._cooldown_until = max(
                        self._cooldown_until,
                        time.monotonic() + remaining)
        return repaired

    def replay(self, job_id: str) -> Dict[str, Any]:
        """Complete an ``interrupted`` job's register+deploy half from
        its record — the trainer's finished export is on disk, so no
        retrain is needed. Raises :class:`ContinualError` for a job
        that is not replayable."""
        job = self.job(job_id)
        if job.get("state") != INTERRUPTED or not job.get("replayable"):
            raise ContinualError(
                f"job {job_id!r} is not replayable "
                f"(state={job.get('state')!r}, "
                f"replayable={job.get('replayable')})")
        _tally("jobs_replayed")
        logger.info("continual: replaying job %s (register+deploy from "
                    "the persisted record)", job_id)
        self._register_and_deploy(job)
        self.last_job = job
        return job

    # -- status ------------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            cooldown = max(self._cooldown_until - time.monotonic(), 0.0)
            running = (self._thread is not None
                       and self._thread.is_alive())
            out = {"model": self.name, "armWindows": self.arm_windows,
                   "streak": self._streak, "failures": self._failures,
                   "maxFailures": self.max_failures,
                   "disarmed": self._disarmed,
                   "cooldownRemainingS": round(cooldown, 3),
                   "jobRunning": running,
                   "jobDir": self.job_dir}
        out["lastJob"] = ({k: self.last_job.get(k) for k in
                           ("jobId", "state", "version", "error",
                            "exitCode")}
                          if self.last_job else None)
        return out
