"""Resilience layer — fault injection, retries, breakers, quarantine.

TransmogrifAI inherits its fault tolerance from Spark: task retries,
lineage re-execution and micro-batch recovery all come free from the
executor fleet. The TPU-native runtime has no such substrate — one bad
Avro file, one device-tier failure, one preemption mid-``_fit_dag`` used
to kill (or silently degrade) the whole run. This module makes failure a
first-class, observable, recoverable state, following the TensorFlow
paper's checkpoint-recovery model and tf.data's contract that input
pipelines degrade gracefully on malformed records (PAPERS.md):

* **Fault-injection harness** — :class:`FaultPlan` + :func:`inject`:
  product code declares named *fault sites* (``stream.read_file``,
  ``avro.decode``, ``fitstats.device_pass``, ``checkpoint.rename`` … see
  docs/robustness.md for the catalog); a seeded plan installed via
  :func:`fault_plan` decides deterministically which calls raise, so
  chaos tests replay bit-identically. ``inject`` is a no-op attribute
  check when no plan is installed — zero cost on production paths.
* **RetryPolicy** — jittered exponential backoff with a max-attempt
  budget and a retryable-exception filter, applied to reader IO,
  checkpoint writes and stream polling. Deterministic when seeded.
* **CircuitBreaker** — per-site consecutive-failure breaker
  (closed → open → half-open) that formalizes the ad-hoc
  ``except Exception: fall back`` blocks around the device tier: after
  N consecutive device failures the host tier is used *without* paying
  the failing dispatch each call, until the reset timeout lets one
  probe through.
* **Poison-record quarantine** — a JSONL dead-letter sink
  (:func:`set_quarantine` / :func:`quarantine`): malformed files,
  batches and records route there with a reason instead of being
  silently dropped or crashing ``stream_score``; counts ride in every
  run doc via the always-on :func:`resilience_stats` tallies (the
  ``fitstats_stats`` discipline — cheap enough to never turn off) and
  mirror into ``resilience.*`` telemetry counters when telemetry is on.

Resumable fits live in ``workflow.Workflow.fit(resume_from=...)`` on top
of the existing ``_atomic_checkpoint`` discipline; this module supplies
the fault sites and the checkpoint-write retry policy they use.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import random
import threading
import time
from typing import (Any, Callable, Dict, Iterable, List, Optional, Tuple,
                    Type, Union)

from . import telemetry

logger = logging.getLogger(__name__)

__all__ = [
    "FAULT_SITES",
    "FaultPlan", "inject", "install_plan", "clear_plan", "fault_plan",
    "active_plan",
    "RetryPolicy", "READER_RETRY", "CHECKPOINT_RETRY",
    "CircuitBreaker", "breaker", "reset_breakers",
    "Quarantine", "set_quarantine", "get_quarantine", "quarantine",
    "quarantine_batch_or_raise", "resolve_on_error", "record_resumed_fit",
    "resilience_stats", "reset_resilience_stats",
]


# ---------------------------------------------------------------------------
# always-on tallies (run docs stamp these; telemetry mirrors when enabled)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"faults_injected": 0, "retries": 0, "retry_exhausted": 0,
          "breaker_trips": 0, "breaker_open_skips": 0,
          "quarantined_files": 0, "quarantined_batches": 0,
          "quarantined_records": 0, "resumed_fits": 0}


def resilience_stats() -> Dict[str, int]:
    """Snapshot of the process-wide resilience tallies. Always on (the
    ``fitstats_stats`` discipline) so the runner can stamp quarantine /
    retry / breaker evidence on every metrics doc without full
    telemetry."""
    with _TALLY_LOCK:
        return dict(_TALLY)


def reset_resilience_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


# ---------------------------------------------------------------------------
# fault-injection harness
# ---------------------------------------------------------------------------

#: the catalog of registered fault sites — every ``inject(site)`` marker in
#: product code MUST name one of these (enforced statically by
#: ``tools/tmoglint.py`` rule TMG303: a typo'd site is a chaos test that
#: silently never fires). Adding a site = adding it here, placing the
#: ``inject`` marker, and documenting it in docs/robustness.md.
FAULT_SITES = frozenset({
    "stream.poll",               # directory-stream listing (streaming.py)
    "stream.read_file",          # per-file stream read (streaming.py)
    "stream.score_batch",        # per-batch scoring (data_readers/scoring)
    "avro.decode",               # avro container decode (readers/avro.py)
    "csv.decode",                # csv decode (readers/data_readers.py)
    "fitstats.device_pass",      # fused fit-stats device tier (fitstats.py)
    "scoring.device_dispatch",   # compiled engine dispatch (scoring.py)
    "pipeline.upload",           # staged double-buffered device_put
                                 # (scoring.ScoringEngine.stage_batch —
                                 # an upload failure is a tier failure:
                                 # breaker-reported, host-path retry)
    "server.dispatch",           # model-server micro-batch dispatch
                                 # (server.py — batch AND per-request
                                 # fallback attempts pass through it)
    "fleet.forward",             # router→worker forward attempt
                                 # (fleet.serve_fleet_http — fires per
                                 # attempt, so a fault models a dead or
                                 # unreachable worker and the sibling
                                 # retry is the recovery under test)
    "fleet.spawn",               # worker process spawn/respawn
                                 # (fleet.FleetSupervisor._spawn —
                                 # fires before Popen, so a fault
                                 # models a spawn failure and re-enters
                                 # the jittered respawn backoff)
    "lifecycle.promote",         # registry current-pointer swap
                                 # (lifecycle.ModelRegistry.promote —
                                 # fires BEFORE the atomic os.replace,
                                 # so an injected fault models a crash
                                 # mid-promote: pointer untouched)
    "continual.retrain",         # drift-triggered retrain job launch
                                 # (continual.RetrainController — fires
                                 # after the active-job flock, before
                                 # the trainer subprocess spawns, so a
                                 # fault models a job that dies at t=0
                                 # and exercises the failure budget)
    "continual.register",        # post-retrain registry registration
                                 # (continual.RetrainController — fires
                                 # before registry.register, so a fault
                                 # models a crash mid-register: the job
                                 # record stays replayable, the CURRENT
                                 # pointer untouched)
    "continual.merge_stats",     # warm-start sufficient-stats merge
                                 # (fitstats.LayerStatsPlan.run — fires
                                 # before the Chan merge of persisted
                                 # train-time moments with the fresh
                                 # slice, so a fault degrades the refit
                                 # to fresh-only stats, never a crash)
    "temporal.aggregate",        # columnar temporal aggregation pass
                                 # (temporal.route_aggregate /
                                 # aggregate_tables — fires before the
                                 # vectorized group/fold, so a fault
                                 # models a columnar-tier failure: the
                                 # breaker reports it and the row-wise
                                 # fold serves, bit-identical)
    "temporal.join",             # streaming hash-join build/probe
                                 # (TemporalJoinReader /
                                 # join_aggregate_directory — fires
                                 # inside the retried build step, so a
                                 # transient fault rides READER_RETRY
                                 # instead of killing the read)
    "checkpoint.write",          # layer-checkpoint save (workflow.py)
    "checkpoint.rename",         # layer-checkpoint swap (workflow.py)
})


class _SiteFault:
    """One site's injection rule inside a :class:`FaultPlan`."""

    __slots__ = ("error", "at", "probability", "times", "calls", "fired",
                 "rng")

    def __init__(self, error, at, probability, times, rng):
        self.error = error
        self.at = at                  # frozenset of 0-based call indices
        self.probability = probability
        self.times = times            # max fires (None = unlimited)
        self.calls = 0
        self.fired = 0
        self.rng = rng


class FaultPlan:
    """Seeded, deterministic chaos plan: which :func:`inject` calls raise.

    Selection per site is (in precedence order) an explicit set of call
    indices (``at=[2]`` → only the third call fires), a probability drawn
    from a per-site ``random.Random(f"{seed}:{site}")`` stream (the same
    seed replays the same faults regardless of other sites' traffic), or
    every call. ``times`` caps total fires either way — ``times=1`` makes
    a transient fault, the retry-policy happy path.

    >>> plan = FaultPlan(seed=7).on("stream.read_file", error=OSError,
    ...                             at=[0])
    >>> with fault_plan(plan):
    ...     run_the_stream()
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._sites: Dict[str, _SiteFault] = {}
        self._lock = threading.Lock()

    def on(self, site: str,
           error: Union[Type[BaseException], BaseException] = OSError,
           at: Optional[Iterable[int]] = None,
           probability: Optional[float] = None,
           times: Optional[int] = None) -> "FaultPlan":
        """Arm ``site``; returns self for chaining."""
        self._sites[site] = _SiteFault(
            error=error,
            at=frozenset(int(i) for i in at) if at is not None else None,
            probability=probability,
            times=times,
            rng=random.Random(f"{self.seed}:{site}"))
        return self

    def sites(self) -> List[str]:
        return sorted(self._sites)

    def calls(self, site: str) -> int:
        """How many times ``inject(site)`` ran under this plan."""
        f = self._sites.get(site)
        return f.calls if f else 0

    def fired(self, site: str) -> int:
        """How many of those calls raised."""
        f = self._sites.get(site)
        return f.fired if f else 0

    def check(self, site: str) -> Optional[BaseException]:
        """Advance the site's call counter; return the exception to raise
        for this call, or None. Thread-safe (streaming prep workers hit
        sites concurrently with the consumer)."""
        f = self._sites.get(site)
        if f is None:
            return None
        with self._lock:
            idx = f.calls
            f.calls += 1
            if f.times is not None and f.fired >= f.times:
                return None
            if f.at is not None:
                fire = idx in f.at
            elif f.probability is not None:
                fire = f.rng.random() < f.probability
            else:
                fire = True
            if not fire:
                return None
            f.fired += 1
        err = f.error
        if isinstance(err, BaseException):
            return err
        return err(f"injected fault at {site!r} (call {idx})")


#: the installed plan; None (the default) short-circuits inject() to a
#: single attribute read — production paths pay nothing for the sites
_PLAN: Optional[FaultPlan] = None


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan`` process-wide; returns the previous plan."""
    global _PLAN
    prev = _PLAN
    _PLAN = plan
    return prev


def clear_plan() -> None:
    install_plan(None)


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


@contextlib.contextmanager
def fault_plan(plan: FaultPlan):
    """Scoped install — the chaos-test entry point."""
    prev = install_plan(plan)
    try:
        yield plan
    finally:
        install_plan(prev)


def inject(site: str, **ctx: Any) -> None:
    """Fault site marker. No-op without an installed plan; under a plan,
    deterministically raises the configured exception. ``ctx`` is logged
    with the injection so chaos-test failures are debuggable."""
    plan = _PLAN
    if plan is None:
        return
    exc = plan.check(site)
    if exc is None:
        return
    _tally("faults_injected")
    telemetry.counter("resilience.faults_injected").inc()
    logger.warning("fault injected at %s %s: %r", site, ctx or "", exc)
    raise exc


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


class RetryPolicy:
    """Jittered exponential backoff with a retryable-exception filter.

    ``call(site, fn, *args, **kw)`` runs ``fn`` up to ``max_attempts``
    times, sleeping ``base_delay_s * multiplier**attempt`` (capped at
    ``max_delay_s``) scaled by a jitter factor in ``[1-jitter, 1+jitter]``
    between attempts. Only exceptions matching ``retryable`` are retried
    — a decode error (corrupt data) is not transient and re-raises
    immediately, an ``OSError`` (flaky filesystem, vanished file) gets
    the backoff. Seeded policies produce deterministic delay sequences
    for tests; the default draws from the module RNG.
    """

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5,
                 retryable: Tuple[Type[BaseException], ...] = (OSError,),
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retryable = tuple(retryable)
        self._rng = random.Random(seed) if seed is not None else random
        self._sleep = sleep

    def delay_s(self, attempt: int) -> float:
        """Backoff before retrying after failed attempt ``attempt``
        (0-based)."""
        raw = min(self.base_delay_s * (self.multiplier ** attempt),
                  self.max_delay_s)
        if self.jitter <= 0:
            return raw
        lo = max(1.0 - self.jitter, 0.0)
        hi = 1.0 + self.jitter
        return raw * (lo + (hi - lo) * self._rng.random())

    def call(self, site: str, fn: Callable[..., Any], *args: Any,
             **kwargs: Any) -> Any:
        """Run ``fn(*args, **kwargs)`` under this policy. The last
        failure re-raises unchanged (callers see the real exception, not
        a wrapper)."""
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.retryable as e:
                if attempt + 1 >= self.max_attempts:
                    _tally("retry_exhausted")
                    telemetry.counter("resilience.retry_exhausted").inc()
                    logger.warning(
                        "%s: giving up after %d attempt(s): %r",
                        site, self.max_attempts, e)
                    raise
                d = self.delay_s(attempt)
                _tally("retries")
                telemetry.counter("resilience.retries").inc()
                telemetry.emit("retry", site=site, attempt=attempt,
                               error=repr(e), delay_s=d)
                logger.warning(
                    "%s: attempt %d/%d failed (%r); retrying in %.3fs",
                    site, attempt + 1, self.max_attempts, e, d)
                self._sleep(d)
        raise AssertionError("unreachable")   # pragma: no cover

    def wrap(self, site: str, fn: Callable[..., Any]) -> Callable[..., Any]:
        """Decorator form of :meth:`call`."""
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            return self.call(site, fn, *args, **kwargs)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped


#: reader IO — file reads and stream polling (a vanished/locked file on
#: network storage is the transient case this exists for). Short base
#: delay: the directory stream already sleeps its own poll interval.
READER_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.05,
                           max_delay_s=0.5, retryable=(OSError,))

#: checkpoint writes — a failed layer checkpoint must not kill a
#: multi-hour fit over a transient shared-filesystem hiccup.
CHECKPOINT_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.1,
                               max_delay_s=2.0, retryable=(OSError,))


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Consecutive-failure breaker for the device-tier fallbacks.

    closed → (N consecutive failures) → open → (reset timeout) →
    half-open → one probe: success closes, failure re-opens. The point
    is not to *hide* device failures (each one is still logged and
    counted) but to stop paying a failing compile/dispatch on every
    single call once the tier is known-bad — the formalization of the
    ad-hoc ``except Exception: fall back to host`` blocks that used to
    live in ``workflow.py``.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, name: str, failure_threshold: int = 3,
                 reset_timeout_s: float = 60.0):
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._failures = 0
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        return self._state

    @property
    def consecutive_failures(self) -> int:
        return self._failures

    def allow(self) -> bool:
        """May the protected path run? Open + elapsed reset timeout lets
        ONE half-open probe through; its outcome decides the state."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = time.monotonic()
            if self._state == self.OPEN:
                if now - self._opened_at >= self.reset_timeout_s:
                    self._state = self.HALF_OPEN
                    self._opened_at = now
                    logger.info("breaker %s: half-open probe", self.name)
                    return True
                _tally("breaker_open_skips")
                telemetry.counter("resilience.breaker_open_skips").inc()
                return False
            # HALF_OPEN: the probe is in flight; hold further traffic on
            # the fallback until it reports. A probe that was handed out
            # but never reported back (its caller bailed on another
            # gate) must not wedge the tier forever — after another
            # reset period the next caller becomes the probe.
            if now - self._opened_at >= self.reset_timeout_s:
                self._opened_at = now
                logger.info("breaker %s: half-open probe (previous probe "
                            "never reported)", self.name)
                return True
            _tally("breaker_open_skips")
            telemetry.counter("resilience.breaker_open_skips").inc()
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != self.CLOSED:
                logger.info("breaker %s: closed (probe succeeded)",
                            self.name)
            self._state = self.CLOSED

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            self._failures += 1
            if (self._state == self.HALF_OPEN
                    or (self._state == self.CLOSED
                        and self._failures >= self.failure_threshold)):
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                tripped = True
        if tripped:
            _tally("breaker_trips")
            telemetry.counter("resilience.breaker_trips").inc()
            telemetry.emit("breaker_trip", name=self.name,
                           failures=self._failures)
            logger.warning(
                "breaker %s: OPEN after %d consecutive failure(s) — "
                "fallback path serves for the next %.0fs",
                self.name, self._failures, self.reset_timeout_s)

    def reset(self) -> None:
        with self._lock:
            self._failures = 0
            self._state = self.CLOSED


_BREAKERS: Dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = threading.Lock()


def breaker(name: str, failure_threshold: int = 3,
            reset_timeout_s: float = 60.0) -> CircuitBreaker:
    """Get-or-create the named process-wide breaker (the first caller's
    thresholds win — call sites agree by convention, tests override via
    :func:`reset_breakers` + re-create)."""
    b = _BREAKERS.get(name)
    if b is None:
        with _BREAKERS_LOCK:
            b = _BREAKERS.get(name)
            if b is None:
                b = _BREAKERS[name] = CircuitBreaker(
                    name, failure_threshold, reset_timeout_s)
    return b


def reset_breakers() -> None:
    """Drop every registered breaker (tests)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()


# ---------------------------------------------------------------------------
# poison-record quarantine (dead-letter sink)
# ---------------------------------------------------------------------------


class Quarantine:
    """Append-only JSONL dead-letter sink.

    One line per quarantined item::

        {"ts": 1725000000.0, "site": "stream.read_file",
         "kind": "files", "reason": "AvroDecodeError('...')",
         "path": "/data/in/batch-07.avro"}

    Writes are best-effort: a failing sink logs and drops (the pipeline
    being observed must never die because its dead-letter disk did), but
    the counters still count — the run doc's quarantine totals are
    authoritative even when the sink is absent."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def write(self, entry: Dict[str, Any]) -> None:
        try:
            line = json.dumps(entry, default=str)
        except (TypeError, ValueError):
            line = json.dumps({k: repr(v) for k, v in entry.items()})
        try:
            with self._lock:
                with open(self.path, "a") as fh:
                    fh.write(line + "\n")
        except OSError:
            logger.exception("quarantine sink write failed (%s)", self.path)

    def entries(self) -> List[Dict[str, Any]]:
        """Read the sink back (tests / inspection)."""
        out: List[Dict[str, Any]] = []
        try:
            with open(self.path) as fh:
                for line in fh:
                    if line.strip():
                        out.append(json.loads(line))
        except FileNotFoundError:
            pass
        return out


_SINK: Optional[Quarantine] = None


def set_quarantine(sink: Union[Quarantine, str, None]
                   ) -> Optional[Quarantine]:
    """Install the process-wide dead-letter sink (a path builds a
    :class:`Quarantine`; None uninstalls). Returns the previous sink so
    run-scoped installs (the runner's ``quarantineLocation``) can
    restore it."""
    global _SINK
    prev = _SINK
    _SINK = Quarantine(sink) if isinstance(sink, str) else sink
    return prev


def get_quarantine() -> Optional[Quarantine]:
    return _SINK


def quarantine(site: str, reason: str, kind: str = "records",
               count: int = 1, **payload: Any) -> None:
    """Route a poison item to the dead-letter sink and count it.

    ``kind`` is one of ``files`` / ``batches`` / ``records`` (it picks
    the tally and the ``resilience.quarantined_<kind>`` counter);
    ``payload`` carries item identity (path, batch index, row count) and
    — for in-memory batches that exist nowhere else — the ``records``
    themselves, so the dead letter is replayable, not just a tombstone.
    Counting always happens; the JSONL line lands only when a sink is
    installed."""
    key = f"quarantined_{kind}"
    if key not in _TALLY:           # unknown kind still counts somewhere
        key = "quarantined_records"
    _tally(key, count)
    telemetry.counter(f"resilience.{key}").inc(count)  # lint: metric-name — keys are the fixed resilience_stats tally catalog
    telemetry.emit("quarantine", site=site, kind=kind, count=count,
                   reason=reason)
    logger.warning("quarantined %d %s at %s: %s %s",
                   count, kind, site, reason,
                   {k: v for k, v in payload.items() if k != "records"}
                   or "")
    sink = _SINK
    if sink is not None:
        # dead-letter timestamps are epoch wall-clock BY CONTRACT (the
        # JSONL is read by humans/replayers, not compared to perf_counter)
        sink.write({"ts": time.time(), "site": site, "kind": kind,  # lint: wall-clock
                    "count": count, "reason": reason, **payload})


def record_resumed_fit() -> None:
    """Count one ``Workflow.fit(resume_from=...)`` that actually
    warm-started from a checkpoint (tally + telemetry mirror stay
    paired here, like every other resilience count)."""
    _tally("resumed_fits")
    telemetry.counter("resilience.resumed_fits").inc()


def resolve_on_error(on_error: Optional[str]) -> str:
    """The ONE sink-aware default shared by every streaming entry point
    (``stream_score``, ``stream_score_overlapped``, the runner):
    ``None`` resolves to ``"quarantine"`` when a dead-letter sink is
    installed and ``"raise"`` when none is — a quarantined batch whose
    records land nowhere would be silent data loss, so without a sink
    the failure stays loud. Explicit values are validated."""
    if on_error is None:
        return "quarantine" if _SINK is not None else "raise"
    if on_error not in ("quarantine", "raise"):
        raise ValueError(
            f"on_error must be 'quarantine' or 'raise', got {on_error!r}")
    return on_error


def quarantine_batch_or_raise(on_error: str, index: int,
                              error: BaseException, records,
                              rows: Optional[int] = None,
                              site: str = "stream.score_batch") -> None:
    """The ONE poison-batch policy every streaming scorer path shares
    (plain, overlapped prep, overlapped device, no-engine fallback):
    re-raise when quarantine is off or at the head of the stream — a
    first-batch failure is a configuration error (wrong features,
    missing model state), not data poison, and quarantining every batch
    of a misconfigured stream would be silence at scale — otherwise
    route the batch, records included, to the dead-letter sink."""
    if on_error == "raise" or index == 0:
        raise error
    records = list(records)
    quarantine(site, repr(error), kind="batches", index=index,
               rows=len(records) if rows is None else rows,
               records=records)
