"""TPU-native run telemetry — span tracer, metrics registry, RunListener.

The reference ships a dedicated observability layer: ``OpSparkListener``
(``utils/.../spark/OpSparkListener.scala:56``) subscribes to Spark's event
bus and folds per-stage timings into an ``AppMetrics`` document the runner
writes next to its results. This module is that layer for the TPU-native
runtime, where the interesting events are not Spark stages but XLA
compiles, bucketed device dispatches, host↔device transfers and the
host-prep/device-compute overlap of the streaming scorer:

* **Span tracer** — ``with span("fit:stage", uid=...)``: thread-safe,
  nested, per-thread track ids, exported as Chrome trace-event JSON
  (``write_trace``) loadable in Perfetto / ``chrome://tracing``. The
  overlapped streaming scorer's worker thread shows up as its own track,
  so the overlap is *visible*, not just summarized.
* **Metrics registry** — counters / gauges / histograms
  (``counter("scoring.cache_hits").inc()``) with JSON
  (``metrics_json``) and Prometheus text-exposition
  (``render_prometheus``) export. See docs/observability.md for the
  metric name catalog.
* **RunListener protocol** — ``on_run_start / on_mesh / on_layer_start /
  on_stage_fit / on_score_batch / on_compile / on_run_end`` mirroring
  OpSparkListener's callbacks; :class:`CollectingRunListener` folds them
  into an AppMetrics-style summary the runner embeds in its metrics doc.

Telemetry is **off by default and near-zero-cost when off**: every entry
point checks the module-level ``_ENABLED`` flag before allocating
anything — ``span()`` returns a shared no-op singleton, ``counter()`` /
``gauge()`` / ``histogram()`` return shared null instruments, and
``emit()`` returns immediately. Enable with :func:`enable`, via
``OpParams`` (``customParams.telemetry`` / ``traceLocation`` /
``metricsFormat``) or the runner CLI (``--trace-out`` /
``--metrics-format prometheus``).

This module also owns two probes that predate it (absorbed from
``workflow.py``, which keeps thin re-exports):

* the process-wide **XLA compile clock** fed by ``jax.monitoring``
  duration events (``compile_clock_s``). Exactly ONE monitoring listener
  is ever registered per process, whether telemetry is on or off — the
  same callback feeds the clock always and the registry/listeners only
  when enabled;
* the **host↔device bandwidth probe** (``probe_device_roundtrip_mbps``)
  behind the layer-fusion and scoring-engine gates.

Multi-host: every process computes identical state, so trace/metrics
files follow the one-writer rule — ``write_trace`` / ``write_metrics``
no-op on non-coordinator processes (same discipline as checkpoints and
the runner's metrics sink).
"""
from __future__ import annotations

import bisect
import json
import logging
import os
import re
import threading
import time

from .utils import locks
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "enable", "disable", "enabled", "reset",
    "span", "trace_events", "write_trace",
    "counter", "gauge", "histogram", "metrics_json", "render_prometheus",
    "write_metrics",
    "RunListener", "CollectingRunListener",
    "add_listener", "remove_listener", "listeners", "emit",
    "compile_clock_s", "probe_device_roundtrip_mbps", "peak_rss_mb",
    # cross-process tracing (docs/observability.md "Distributed tracing")
    "TRACE_HEADER", "TRACE_ENV", "mint_trace", "parse_traceparent",
    "format_traceparent", "current_trace", "trace_scope",
    "set_trace_role", "trace_role",
    "write_trace_shard", "merge_trace_shards", "write_merged_trace",
    # Prometheus exposition helpers (the /metrics plane)
    "parse_prometheus", "render_prometheus_sum",
    "merge_parsed_prometheus",
    # executed-FLOP attribution (the MFU block)
    "record_device_work", "device_cost_stats", "reset_device_cost",
    "telemetry_stats",
]

# ---------------------------------------------------------------------------
# enabled flag — checked before ANY allocation on every hot path
# ---------------------------------------------------------------------------

_ENABLED = False

#: relative-time epoch for trace timestamps (monotonic; NTP steps cannot
#: corrupt recorded durations — the reason every timer here is
#: ``perf_counter``, never ``time.time``)
_EPOCH = time.perf_counter()

_PID = os.getpid()

_LOCK = locks.witness_lock("telemetry._LOCK", reentrant=True)

#: dedicated event-buffer lock: span exits (every traced hot path, on
#: every thread) append here, so sharing the registry RLock with every
#: counter inc and histogram observe measurably convoys the serving
#: workers (trace_overhead bench) — the buffer gets its own lock
_EVENTS_LOCK = locks.witness_lock("telemetry._EVENTS_LOCK")

#: recorded Chrome trace events (dicts, ph "X" for spans + "M" metadata)
_EVENTS: List[Dict[str, Any]] = []

#: hard cap so a forgotten enable() in a long-lived server cannot eat the
#: heap; overflow is counted, never silent
_MAX_EVENTS = 1_000_000
_DROPPED_EVENTS = [0]

#: thread ident -> small stable track id for the trace
_TRACKS: Dict[int, int] = {}

_TLS = threading.local()


def enabled() -> bool:
    """True when telemetry is recording."""
    return _ENABLED


def enable() -> None:
    """Turn telemetry on (spans recorded, metrics counted, listeners
    dispatched). Idempotent; does NOT register any ``jax.monitoring``
    listener — the single shared compile-clock listener is installed
    lazily by the workflow/bench paths whether telemetry is on or off."""
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    """Stop recording. Already-recorded events/metrics stay exportable."""
    global _ENABLED
    _ENABLED = False


def reset(keep_listeners: bool = False) -> None:
    """Drop all recorded events and metrics — and, unless
    ``keep_listeners``, the listener registry too (tests, a long-lived
    server rotating its trace files, or the runner's run-scoped teardown,
    which keeps user-registered listeners alive)."""
    with _LOCK:
        with _EVENTS_LOCK:
            _EVENTS.clear()
            _DROPPED_EVENTS[0] = 0
            # forget track assignments so live threads re-announce
            # their thread_name metadata in the NEXT trace file too
            _TRACKS.clear()
        _REGISTRY.clear()
        if not keep_listeners:
            del _LISTENERS[:]


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


def _track_id() -> int:
    ident = threading.get_ident()
    tid = _TRACKS.get(ident)
    if tid is None:
        with _EVENTS_LOCK:
            tid = _TRACKS.setdefault(ident, len(_TRACKS))
            _EVENTS.append({
                "name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
                "args": {"name": threading.current_thread().name}})
    return tid


def _span_stack() -> List[str]:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


class _NullSpan:
    """Shared no-op context manager returned while telemetry is off."""

    __slots__ = ()
    trace_id: Optional[str] = None
    span_id: Optional[str] = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "_t0", "trace_id", "span_id",
                 "parent_id")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        #: W3C-style identity, populated on __enter__ when a trace
        #: context is active on this thread (docs/observability.md
        #: "Distributed tracing"); None otherwise — zero cost for
        #: in-process-only tracing
        self.trace_id: Optional[str] = None
        self.span_id: Optional[str] = None
        self.parent_id: Optional[str] = None

    def __enter__(self) -> "_Span":
        ctx = current_trace()
        if ctx is not None:
            self.trace_id, self.parent_id = ctx
            self.span_id = _new_span_id()
            _trace_stack().append((self.trace_id, self.span_id))
        _span_stack().append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        stack = _span_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        if self.span_id is not None:
            tstack = _trace_stack()
            if tstack and tstack[-1][1] == self.span_id:
                tstack.pop()
            # attrs is span-owned (built fresh from **attrs) — mutate
            # in place, no defensive copy on the hot path
            self.attrs["trace_id"] = self.trace_id
            self.attrs["span_id"] = self.span_id
            if self.parent_id:
                self.attrs["parent_span_id"] = self.parent_id
        tid = _track_id()
        with _EVENTS_LOCK:
            if len(_EVENTS) >= _MAX_EVENTS:
                _DROPPED_EVENTS[0] += 1
                return False
            _EVENTS.append({
                "name": self.name, "ph": "X", "pid": _PID, "tid": tid,
                "ts": round((self._t0 - _EPOCH) * 1e6, 3),
                "dur": round((t1 - self._t0) * 1e6, 3),
                "args": self.attrs})
        return False


def span(name: str, **attrs: Any):
    """Context manager timing a named span; no-op singleton when off.

    Spans nest (the per-thread stack tracks the current path) and land on
    the calling thread's own track in the exported trace, so concurrent
    work — the streaming scorer's prep worker, CV threads — renders as
    parallel lanes in Perfetto. Under an active :func:`trace_scope` the
    span additionally carries ``trace_id`` / ``span_id`` /
    ``parent_span_id`` args, so cross-process traces stitch in the
    merged file; pass ``links=[span_id, ...]`` as a plain attr to
    reference other spans (the micro-batcher links its member request
    spans this way)."""
    if not _ENABLED:
        return _NULL_SPAN
    return _Span(name, attrs)


def current_span_stack() -> Tuple[str, ...]:
    """Names of the calling thread's open spans, outermost first."""
    return tuple(_span_stack())


def trace_events() -> List[Dict[str, Any]]:
    """Copy of the recorded Chrome trace events."""
    with _EVENTS_LOCK:
        return list(_EVENTS)


def write_trace(path: str) -> bool:
    """Write the Chrome trace-event JSON (open in Perfetto or
    ``chrome://tracing``). Multi-host one-writer rule: only the
    coordinator writes (every process records identical structure);
    returns False when skipped."""
    if not _is_coordinator():
        return False
    doc = {"traceEvents": trace_events(), "displayTimeUnit": "ms"}
    if _DROPPED_EVENTS[0]:
        doc["droppedEvents"] = _DROPPED_EVENTS[0]
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    return True


def _is_coordinator() -> bool:
    try:
        from .parallel.multihost import is_coordinator
        return is_coordinator()
    except Exception:  # lint: broad-except — no jax runtime yet: single process by definition
        return True      # no jax runtime yet — single process by definition


# ---------------------------------------------------------------------------
# distributed tracing — W3C-traceparent-style context + trace shards
# ---------------------------------------------------------------------------

#: HTTP header carrying the trace context between fleet processes
#: (router → worker), W3C-traceparent-shaped:
#: ``00-<32 hex trace id>-<16 hex span id>-01``
TRACE_HEADER = "X-Tmog-Trace"

#: env var carrying the trace context into subprocesses (the continual
#: tier's retrain jobs inherit the triggering window's trace this way)
TRACE_ENV = "TMOG_TRACE_PARENT"

#: env var naming this process's role in merged traces (router / worker
#: / retrain / ...) — one Perfetto process row per (role, pid)
TRACE_ROLE_ENV = "TMOG_TRACE_ROLE"

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")

_TRACE_ROLE = [os.environ.get(TRACE_ROLE_ENV, "proc")]

#: always-on tracing tallies (never cleared by reset() — the
#: engine_cache_stats discipline; see telemetry_stats())
_TRACE_TALLY_LOCK = locks.witness_lock("telemetry._TRACE_TALLY_LOCK")
_TRACE_TALLY = {"traces_minted": 0, "traces_adopted": 0,
                "shards_written": 0, "shards_merged": 0}


def _trace_tally(key: str, n: int = 1) -> None:
    with _TRACE_TALLY_LOCK:
        _TRACE_TALLY[key] += n


def _id_rng():
    """Per-thread PRNG for trace/span ids, seeded ONCE from the OS
    entropy pool (+ pid + thread id, so forked workers and sibling
    threads can never share a stream). Ids need uniqueness, not
    cryptographic strength — and ``os.urandom`` is a syscall per call
    (measured ~200µs on containerized kernels), which at one trace id
    + two span ids per routed request would, alone, blow the
    trace_overhead bench's 5% gate."""
    r = getattr(_TLS, "id_rng", None)
    if r is None:
        import random
        seed = (int.from_bytes(os.urandom(16), "big")
                ^ (os.getpid() << 64) ^ threading.get_ident())
        r = _TLS.id_rng = random.Random(seed)
    return r


def _new_trace_id() -> str:
    return f"{_id_rng().getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_id_rng().getrandbits(64):016x}"


def _trace_stack() -> List[Tuple[str, str]]:
    """Per-thread stack of (trace_id, span_id) for the OPEN traced
    spans — the innermost entry is the parent of the next child."""
    st = getattr(_TLS, "trace_stack", None)
    if st is None:
        st = _TLS.trace_stack = []
    return st


def mint_trace() -> Tuple[str, str]:
    """A fresh (trace_id, span_id) root context — the fleet router (or
    any other entry point) mints one per request and propagates it via
    :data:`TRACE_HEADER` / :data:`TRACE_ENV`."""
    _trace_tally("traces_minted")
    return _new_trace_id(), _new_span_id()


def format_traceparent(trace_id: str, span_id: str) -> str:
    """``00-<trace_id>-<span_id>-01`` (W3C traceparent shape)."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) from a traceparent string; None when the
    value is missing or malformed — a corrupt header must never fail a
    request, it just starts an unlinked trace."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    return m.group(1), m.group(2)


def _env_trace() -> Optional[Tuple[str, str]]:
    """The process-level parent context inherited via TMOG_TRACE_PARENT
    (retrain subprocesses join the triggering window's trace this
    way). Parsed lazily and cached — the env cannot change under us."""
    cached = getattr(_env_trace, "_cached", False)
    if cached is False:
        ctx = parse_traceparent(os.environ.get(TRACE_ENV))
        if ctx is not None:
            _trace_tally("traces_adopted")
        _env_trace._cached = ctx          # type: ignore[attr-defined]
        return ctx
    return cached


def current_trace() -> Optional[Tuple[str, str]]:
    """The calling thread's active (trace_id, parent_span_id): the
    innermost open traced span, else the thread's trace_scope context,
    else the process-level TMOG_TRACE_PARENT. None = untraced."""
    st = _trace_stack()
    if st:
        return st[-1]
    ctx = getattr(_TLS, "trace_ctx", None)
    if ctx is not None:
        return ctx
    return _env_trace()


class _TraceScope:
    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: Optional[Tuple[str, str]]):
        self.ctx = ctx
        self._prev: Any = None

    def __enter__(self) -> Optional[Tuple[str, str]]:
        self._prev = getattr(_TLS, "trace_ctx", None)
        if self.ctx is not None:
            _TLS.trace_ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc) -> bool:
        if self.ctx is not None:
            _TLS.trace_ctx = self._prev
        return False


def trace_scope(ctx):
    """Install a (trace_id, span_id) parent context — or a traceparent
    string, parsed tolerantly — for the calling thread's spans. A None
    context is a no-op scope, so call sites never need to branch::

        with telemetry.trace_scope(request_header):
            with telemetry.span("server:request") as sp:
                ...   # sp.trace_id / sp.span_id carry the identity
    """
    if isinstance(ctx, str):
        ctx = parse_traceparent(ctx)
    return _TraceScope(ctx)


def set_trace_role(role: str) -> None:
    """Name this process's row in merged traces (router / worker /
    retrain / runner...)."""
    _TRACE_ROLE[0] = str(role)


def trace_role() -> str:
    return _TRACE_ROLE[0]


def write_trace_shard(dir_path: str,
                      role: Optional[str] = None) -> Optional[str]:
    """Write THIS process's recorded events as one atomic trace shard
    under ``dir_path`` (``shard-<role>-<pid>.trace.json``), for
    ``python -m transmogrifai_tpu trace merge`` to stitch into one
    Perfetto file. Every fleet process writes its own shard — pid+role
    naming means no cross-process write races, so the multi-host
    one-writer rule deliberately does NOT apply here. The shard records
    ``epochUnixS`` — the wall-clock instant of this process's monotonic
    trace epoch — so the merger can align clocks across processes.
    Returns the shard path (None when nothing was recorded)."""
    events = trace_events()
    if not events:
        return None
    role = role or trace_role()
    os.makedirs(dir_path, exist_ok=True)
    # wall-clock anchor of the monotonic epoch: merge-time alignment
    # needs ONE cross-process time base, and the wall clock is the only
    # one the processes share (a small NTP skew shifts a whole process
    # row, never a duration — durations stay perf_counter-true)
    epoch_unix = time.time() - (time.perf_counter() - _EPOCH)  # lint: wall-clock — cross-process clock-offset anchor, not a duration
    doc = {"role": role, "pid": _PID,
           "epochUnixS": round(epoch_unix, 6),
           "traceEvents": events}
    if _DROPPED_EVENTS[0]:
        doc["droppedEvents"] = _DROPPED_EVENTS[0]
    safe_role = re.sub(r"[^A-Za-z0-9_.-]", "_", role)
    path = os.path.join(dir_path, f"shard-{safe_role}-{_PID}.trace.json")
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    _trace_tally("shards_written")
    return path


def merge_trace_shards(dir_path: str) -> Dict[str, Any]:
    """Stitch every ``shard-*.trace.json`` shard under ``dir_path``
    into one Chrome trace-event document with clock-offset alignment
    and a per-process row layout: each shard's events keep their own
    pid, get a ``process_name`` metadata row (``<role>-<pid>``), and
    their timestamps shift onto a common axis anchored at the earliest
    shard's epoch. Only the ``shard-`` prefix ``write_trace_shard``
    produces is ingested — a previous merge's own output
    (``merged.trace.json``) in the same directory must never be
    re-ingested as a shard (it has no epoch anchor and would shift the
    whole axis). Unreadable shards are skipped with a note in
    ``mergeErrors`` — a torn shard must never lose the rest of the
    fleet's trace."""
    shards: List[Dict[str, Any]] = []
    errors: List[str] = []
    try:
        names = sorted(os.listdir(dir_path))
    except OSError as e:
        raise ValueError(f"trace merge: cannot list {dir_path!r}: {e}")
    for fn in names:
        if not (fn.startswith("shard-") and fn.endswith(".trace.json")):
            continue
        p = os.path.join(dir_path, fn)
        try:
            with open(p) as fh:
                doc = json.load(fh)
            doc["traceEvents"]  # shape check
        except (OSError, ValueError, KeyError) as e:
            errors.append(f"{fn}: {e!r}")
            continue
        shards.append(doc)
    if not shards:
        raise ValueError(
            f"trace merge: no readable shard-*.trace.json shards in "
            f"{dir_path!r}" + (f" ({errors})" if errors else ""))
    t0 = min(float(s.get("epochUnixS", 0.0)) for s in shards)
    out_events: List[Dict[str, Any]] = []
    dropped = 0
    for sort_idx, s in enumerate(shards):
        pid = int(s.get("pid", sort_idx))
        role = str(s.get("role", "proc"))
        off_us = (float(s.get("epochUnixS", 0.0)) - t0) * 1e6
        out_events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": f"{role}-{pid}"}})
        out_events.append({"name": "process_sort_index", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"sort_index": sort_idx}})
        dropped += int(s.get("droppedEvents", 0))
        for ev in s["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + off_us, 3)
            out_events.append(ev)
    doc = {"traceEvents": out_events, "displayTimeUnit": "ms",
           "mergedShards": len(shards)}
    if dropped:
        doc["droppedEvents"] = dropped
    if errors:
        doc["mergeErrors"] = errors
    _trace_tally("shards_merged", len(shards))
    return doc


def write_merged_trace(dir_path: str, out_path: str) -> Dict[str, Any]:
    """:func:`merge_trace_shards` + atomic write of the merged Perfetto
    file; returns the merged document."""
    doc = merge_trace_shards(dir_path)
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{out_path}.tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, out_path)
    return doc


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

#: Prometheus-style default histogram ladder (seconds-ish scale)
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_REGISTRY: "OrderedDict[str, Any]" = OrderedDict()


class Counter:
    """Monotonic counter. Each instrument carries its OWN lock — the
    serving workers inc dozens of counters per request, and funnelling
    them all through the module registry lock convoys the hot path
    (trace_overhead bench)."""

    __slots__ = ("name", "_v", "_lock")
    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        return self._v

    def to_json(self) -> float:
        return self._v


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_v", "_lock")
    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._v

    def to_json(self) -> float:
        return self._v


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations ``<= le``; ``+Inf`` equals ``count``).

    Internally the counts are per-BIN (non-cumulative, with one
    overflow bin past the last bound) so ``observe()`` is one bisect
    plus one increment under the instrument's own lock, not an O(#
    buckets) cumulative walk under the registry lock; the cumulative
    view is materialized at scrape time (:meth:`snapshot`)."""

    __slots__ = ("name", "buckets", "_bins", "_sum", "_count", "_lock")
    kind = "histogram"

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._bins = [0] * (len(self.buckets) + 1)   # +1 = overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        # first bound >= v (Prometheus: a bucket counts v <= le);
        # index len(buckets) is the overflow bin (only +Inf holds it)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._sum += v
            self._count += 1
            self._bins[i] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def bucket_counts(self) -> Dict[float, int]:
        """Cumulative count per upper bound (``le``)."""
        counts, _total, _count = self.snapshot()
        return dict(zip(self.buckets, counts))

    def snapshot(self) -> Tuple[Tuple[int, ...], float, int]:
        """(cumulative bucket counts, sum, count) captured atomically
        under the instrument lock — the ONE read path scrapes may use.
        Reading the fields unlocked while ``observe()`` mutates them
        can tear: a scrape could emit a ``_count`` inconsistent with
        its cumulative buckets (``+Inf`` must equal ``_count`` in
        valid Prometheus exposition)."""
        with self._lock:
            bins = list(self._bins)
            total, count = self._sum, self._count
        cum: List[int] = []
        running = 0
        for c in bins[:-1]:
            running += c
            cum.append(running)
        return tuple(cum), total, count

    def to_json(self) -> Dict[str, Any]:
        counts, total, count = self.snapshot()
        return {"count": count, "sum": total,
                "buckets": {str(le): c for le, c
                            in zip(self.buckets, counts)}}


class _NullInstrument:
    """Shared no-op instrument returned while telemetry is off."""

    __slots__ = ()
    name = ""
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL_INSTRUMENT = _NullInstrument()


def _instrument(name: str, cls, **kw):
    if not _ENABLED:
        return _NULL_INSTRUMENT
    inst = _REGISTRY.get(name)
    if inst is None:
        with _LOCK:
            inst = _REGISTRY.get(name)
            if inst is None:
                inst = _REGISTRY[name] = cls(name, **kw)
    if not isinstance(inst, cls):
        raise TypeError(f"metric {name!r} already registered as "
                        f"{type(inst).__name__}, not {cls.__name__}")
    return inst


def counter(name: str) -> Counter:
    """Get-or-create the named counter (null instrument when off)."""
    return _instrument(name, Counter)


def gauge(name: str) -> Gauge:
    """Get-or-create the named gauge (null instrument when off)."""
    return _instrument(name, Gauge)


def histogram(name: str,
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    """Get-or-create the named histogram (null instrument when off)."""
    return _instrument(name, Histogram, buckets=buckets)


def metrics_json() -> Dict[str, Any]:
    """Registry snapshot: ``{name: value}`` for counters/gauges,
    ``{name: {count, sum, buckets}}`` for histograms."""
    with _LOCK:
        return {name: inst.to_json() for name, inst in _REGISTRY.items()}


_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _PROM_NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(extra: Optional[Dict[str, float]] = None) -> str:
    """Registry in Prometheus text exposition format (0.0.4). ``extra``
    appends scalar gauges (the runner folds its run doc numerics in).

    Histograms are snapshotted atomically (:meth:`Histogram.snapshot`)
    so a scrape racing ``observe()`` can never emit a ``_count``
    inconsistent with its cumulative buckets — the torn-scrape fix."""
    lines: List[str] = []
    with _LOCK:
        items = list(_REGISTRY.items())
    for name, inst in items:
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} {inst.kind}")
        if isinstance(inst, Histogram):
            counts, total, count = inst.snapshot()
            for le, c in zip(inst.buckets, counts):
                lines.append(f'{pn}_bucket{{le="{_prom_value(le)}"}} {c}')
            lines.append(f'{pn}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{pn}_sum {_prom_value(total)}")
            lines.append(f"{pn}_count {count}")
        else:
            lines.append(f"{pn} {_prom_value(inst.value)}")
    for name, v in (extra or {}).items():
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_prom_value(float(v))}")
    return "\n".join(lines) + "\n"


def write_metrics(path: str, fmt: str = "json",
                  extra: Optional[Dict[str, float]] = None) -> bool:
    """Write the registry to ``path`` as JSON or Prometheus text.
    Coordinator-only (one-writer rule); atomic (temp + replace)."""
    if fmt not in ("json", "prometheus"):
        raise ValueError(f"unknown metrics format {fmt!r}")
    if not _is_coordinator():
        return False
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        if fmt == "json":
            doc = metrics_json()
            if extra:
                doc.update(extra)
            json.dump(doc, fh, indent=1, default=str)
        else:
            fh.write(render_prometheus(extra))
    os.replace(tmp, path)
    return True


# ---------------------------------------------------------------------------
# Prometheus exposition aggregation (the fleet router's /metrics plane)
# ---------------------------------------------------------------------------

_PROM_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$")


def parse_prometheus(text: str) -> "OrderedDict[str, Dict[str, Any]]":
    """Minimal Prometheus 0.0.4 text parser:
    ``{family: {"type": kind, "samples": OrderedDict[(name, labels)
    -> float]}}``. Sample keys keep their full name (``_bucket`` /
    ``_sum`` / ``_count`` suffixes included) and raw label string, so a
    re-render round-trips. Raises ValueError on a malformed line — the
    router must not silently sum garbage."""
    fams: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                raise ValueError(f"line {lineno}: malformed TYPE: "
                                 f"{line!r}")
            fams.setdefault(parts[2], {"type": parts[3],
                                       "samples": OrderedDict()})
            continue
        if line.startswith("#"):
            continue                     # HELP / comments
        m = _PROM_SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample: "
                             f"{line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and fams.get(base, {}).get("type") == "histogram":
                fam = base
                break
        fams.setdefault(fam, {"type": "untyped",
                              "samples": OrderedDict()})
        try:
            v = float(value)
        except ValueError:
            raise ValueError(f"line {lineno}: non-numeric value "
                             f"{value!r} for {name!r}")
        fams[fam]["samples"][(name, labels)] = v
    return fams


def merge_parsed_prometheus(
        docs: Sequence["OrderedDict[str, Dict[str, Any]]"]) -> str:
    """Merge already-parsed expositions (:func:`parse_prometheus`
    output) by SUMMING samples with the same (name, labels) and
    re-rendering — the fleet router's ``/metrics`` aggregation, split
    from the parse so the router's per-worker validation pass is also
    the only parse. Correct for counters and histograms (the workers
    share one bucket ladder by construction, so per-``le`` sums stay
    cumulative); gauges sum too, which is the right fleet semantic
    for the gauges this registry exposes (queue depths, in-flight
    depths) — documented in docs/observability.md."""
    merged: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    for parsed in docs:
        for fam, doc in parsed.items():
            tgt = merged.setdefault(
                fam, {"type": doc["type"], "samples": OrderedDict()})
            if tgt["type"] == "untyped":
                tgt["type"] = doc["type"]
            for key, v in doc["samples"].items():
                tgt["samples"][key] = tgt["samples"].get(key, 0.0) + v
    lines: List[str] = []
    for fam, doc in merged.items():
        lines.append(f"# TYPE {fam} {doc['type']}")
        for (name, labels), v in doc["samples"].items():
            lines.append(f"{name}{labels} {_prom_value(v)}")
    return "\n".join(lines) + "\n"


def render_prometheus_sum(texts: Sequence[str]) -> str:
    """:func:`merge_parsed_prometheus` over raw exposition texts."""
    return merge_parsed_prometheus([parse_prometheus(t)
                                    for t in texts])


# ---------------------------------------------------------------------------
# executed-FLOP device cost attribution (the MFU block)
# ---------------------------------------------------------------------------

#: per-chip peak FLOP/s by device kind substring — the v5e numbers the
#: bench has always assumed (public spec; f32 runs through the same MXU
#: at ~1/4 rate). Unknown platforms (CPU containers) report achieved
#: FLOP/s with mfu percentages None rather than inventing a peak.
PEAK_FLOPS = {"v5e": {"bf16": 197e12, "f32": 49e12},
              "v5p": {"bf16": 459e12, "f32": 115e12},
              "v4": {"bf16": 275e12, "f32": 69e12}}

_DEVICE_COST_LOCK = locks.witness_lock("telemetry._DEVICE_COST_LOCK")
#: phase -> {"flops", "seconds", "dispatches"} — fed by the scoring
#: engine, the fitstats device fold and the tuning/tree sweep
#: executables (models/tuning.DEVICE_FLOPS generalized); always on,
#: like every other tally the bench stamps
_DEVICE_COST: Dict[str, Dict[str, float]] = {}


def record_device_work(phase: str, flops: float = 0.0,
                       seconds: float = 0.0,
                       dispatches: int = 1) -> None:
    """Account one device dispatch's executed FLOPs (XLA cost analysis
    where available, documented analytic lower bound otherwise) and its
    measured device-side seconds under ``phase`` (scoring / fitstats /
    tuning / ...). Always on — the tallies are a few float adds."""
    with _DEVICE_COST_LOCK:
        d = _DEVICE_COST.setdefault(
            phase, {"flops": 0.0, "seconds": 0.0, "dispatches": 0.0})
        d["flops"] += float(flops)
        d["seconds"] += float(seconds)
        d["dispatches"] += int(dispatches)


def reset_device_cost() -> None:
    with _DEVICE_COST_LOCK:
        _DEVICE_COST.clear()


def _peak_flops_for(device_kind: str) -> Optional[Dict[str, float]]:
    env = os.environ.get("TMOG_PEAK_FLOPS")
    if env:
        try:
            return {"bf16": float(env), "f32": float(env)}
        except ValueError:
            logger.warning("TMOG_PEAK_FLOPS=%r is not a number; "
                           "ignoring", env)
    kind = device_kind.lower()
    for sub, peaks in PEAK_FLOPS.items():
        if sub in kind:
            return peaks
    return None


def device_cost_stats() -> Dict[str, Any]:
    """The ``mfu`` / device-utilization block stamped on every runner
    metrics doc and bench doc: per-phase executed FLOPs, measured
    device seconds and dispatch counts, plus derived achieved TFLOP/s
    and MFU percentages against the platform peak (None off-TPU —
    an unknown peak must not fabricate a utilization). ``seconds`` is
    device-dispatch wall (host-side timer around dispatch+pull), so the
    per-phase ``achieved_tflops`` is a dispatch-window utilization;
    phases that only track FLOPs (the CV sweep) report seconds 0 and
    no rate."""
    with _DEVICE_COST_LOCK:
        phases = {k: dict(v) for k, v in _DEVICE_COST.items()}
    total_flops = sum(d["flops"] for d in phases.values())
    total_s = sum(d["seconds"] for d in phases.values())
    try:
        import jax
        device_kind = jax.devices()[0].device_kind
        n_dev = jax.device_count()
    except Exception:  # lint: broad-except — no jax runtime: the block still stamps, with no platform peak
        device_kind, n_dev = "unknown", 1
    peaks = _peak_flops_for(device_kind)
    out: Dict[str, Any] = {
        "device_kind": device_kind,
        "devices": n_dev,
        "device_flops": total_flops,
        "device_seconds": round(total_s, 6),
        "phases": {
            k: {"flops": d["flops"],
                "seconds": round(d["seconds"], 6),
                "dispatches": int(d["dispatches"]),
                "achieved_tflops": (round(d["flops"] / d["seconds"]
                                          / 1e12, 4)
                                    if d["seconds"] > 0 else None)}
            for k, d in sorted(phases.items())},
    }
    # the rate pairs TIMED flops with TIMED seconds only: a phase that
    # tracks FLOPs without dispatch timing (the CV sweep) must not
    # inflate the utilization of the phases that measured both
    timed_flops = sum(d["flops"] for d in phases.values()
                      if d["seconds"] > 0)
    rate = timed_flops / total_s if total_s > 0 else None
    out["achieved_tflops"] = (round(rate / 1e12, 4)
                              if rate is not None else None)
    if peaks and rate is not None:
        peak_total = {k: v * n_dev for k, v in peaks.items()}
        out["mfu_bf16_pct"] = round(100.0 * rate
                                    / peak_total["bf16"], 3)
        out["mfu_f32_pct"] = round(100.0 * rate / peak_total["f32"], 3)
    else:
        out["mfu_bf16_pct"] = None
        out["mfu_f32_pct"] = None
    return out


def telemetry_stats() -> Dict[str, Any]:
    """Always-on telemetry-plane tallies (the ``engine_cache_stats``
    discipline — stamped on every bench doc): whether recording is on,
    how many events/metrics are held, event overflow drops, and the
    cross-process tracing traffic (traces minted/adopted, shards
    written/merged)."""
    with _EVENTS_LOCK:
        n_events = len(_EVENTS)
    with _LOCK:
        n_metrics = len(_REGISTRY)
    with _TRACE_TALLY_LOCK:
        trace = dict(_TRACE_TALLY)
    return {"enabled": _ENABLED, "events": n_events,
            "dropped_events": _DROPPED_EVENTS[0],
            "metrics": n_metrics, "role": trace_role(), **trace}


# ---------------------------------------------------------------------------
# RunListener protocol (OpSparkListener analog)
# ---------------------------------------------------------------------------


class RunListener:
    """Callback protocol over run lifecycle events. Subclass and override
    what you need; every hook is emitted with keyword arguments and must
    tolerate future additions (``**_``)."""

    def on_run_start(self, run_type: str, **_: Any) -> None:
        pass

    def on_run_end(self, run_type: str, seconds: float = 0.0,
                   **_: Any) -> None:
        pass

    def on_layer_start(self, index: int, n_stages: int, **_: Any) -> None:
        pass

    def on_mesh(self, devices: int, data: int, grid: int,
                platform: str = "", **_: Any) -> None:
        """The run resolved its (data, grid) device mesh — the multichip
        substrate every heavy phase shards over (parallel/mesh.py;
        emitted once per train, only for a real multi-device mesh)."""
        pass

    def on_stage_fit(self, uid: str, stage_name: str, fit_s: float,
                     compile_s: float = 0.0, execute_s: float = 0.0,
                     warm_started: bool = False, **_: Any) -> None:
        pass

    def on_stats_pass(self, layer: int, n_stages: int, n_requests: int,
                      passes_saved: int, seconds: float = 0.0,
                      **_: Any) -> None:
        """One fused fit-statistics pass fed a whole DAG layer's
        estimators (fitstats.py, the SequenceAggregators analog)."""
        pass

    def on_score_batch(self, n_rows: int, bucket: int, seconds: float,
                       compiled: bool = False, **_: Any) -> None:
        pass

    def on_pipeline_stats(self, batches: int, workers: int,
                          prefetch_depth: int, starvations: int = 0,
                          buffer_reuses: int = 0, buffer_allocs: int = 0,
                          **_: Any) -> None:
        """One pipelined ingest stream finished (pipeline.py): how many
        batches it moved, the worker count it decoded/prepared on, the
        prefetch depth the autotuner converged to and the
        starvation/buffer-churn evidence behind that depth."""
        pass

    def on_compile(self, event: str, seconds: float, **_: Any) -> None:
        pass

    def on_retry(self, site: str, attempt: int, error: str = "",
                 delay_s: float = 0.0, **_: Any) -> None:
        """A RetryPolicy-governed operation failed transiently and is
        about to back off (resilience.py)."""
        pass

    def on_quarantine(self, site: str, kind: str, count: int,
                      reason: str = "", **_: Any) -> None:
        """Poison item(s) routed to the dead-letter sink
        (resilience.quarantine)."""
        pass

    def on_breaker_trip(self, name: str, failures: int, **_: Any) -> None:
        """A circuit breaker opened: its device tier is now served by
        the host fallback until the reset timeout (resilience.py)."""
        pass

    def on_lint(self, rule: str, severity: str, message: str = "",
                **_: Any) -> None:
        """One pre-flight lint finding (lint.py / docs/static-analysis.md):
        ``rule`` is the stable TMGnnn id, ``severity`` is
        error/warning/info; stage uid / feature name / file location ride
        in the extra kwargs when the rule has them."""
        pass

    def on_plan(self, stages: int, engine_tier: Optional[str] = None,
                pruned_columns: int = 0, cse_merges: int = 0,
                **_: Any) -> None:
        """The whole-DAG planner built an ExecutionPlan (planner.py):
        per-stage tier assignment, dead-column pruning and CSE counts —
        the cost-based middle-end's decision record."""
        pass

    def on_request(self, model: str, rows: int, seconds: float,
                   ok: bool = True, coalesced: int = 1,
                   bucket: int = 0, slo_met: Optional[bool] = None,
                   **_: Any) -> None:
        """The model server completed one scoring request (server.py):
        per-request latency, the dispatch bucket it rode in and how many
        requests shared that dispatch (``coalesced``). ``ok`` is False
        for quarantined/errored requests; ``slo_met`` is None when no
        SLO is configured."""
        pass

    def on_drift(self, model: str, feature: str, rule: str,
                 value: float = 0.0, threshold: float = 0.0,
                 window_rows: int = 0, **_: Any) -> None:
        """The serving-time drift sentinel flagged one feature
        (lifecycle.DriftSentinel): ``rule`` is the TMG6xx advisory id,
        ``value`` the measured JS divergence / fill delta that crossed
        ``threshold`` over the last ``window_rows`` live rows."""
        pass

    def on_rollout(self, model: str, action: str,
                   version: Optional[str] = None, mode: str = "",
                   **_: Any) -> None:
        """A shadow/canary rollout changed state on the model server
        (docs/lifecycle.md): ``action`` is ``deploy`` / ``promote`` /
        ``rollback``, ``mode`` the rollout kind; rollbacks carry a
        ``reason`` kwarg."""
        pass

    def on_retrain(self, model: str, action: str,
                   job: Optional[str] = None,
                   version: Optional[str] = None, **_: Any) -> None:
        """The continuous-training controller changed state
        (continual.RetrainController, docs/lifecycle.md "Continuous
        training"): ``action`` is ``trigger`` / ``start`` /
        ``registered`` / ``deployed`` / ``rejected`` / ``failed`` /
        ``killed`` / ``recovered`` / ``gave_up``; ``job`` names the
        on-disk job record, ``version`` the registered candidate.
        Failures carry an ``error`` kwarg."""
        pass


_LISTENERS: List[RunListener] = []


def add_listener(listener: RunListener) -> RunListener:
    """Register a listener (dispatched only while telemetry is on)."""
    with _LOCK:
        if listener not in _LISTENERS:
            _LISTENERS.append(listener)
    return listener


def remove_listener(listener: RunListener) -> None:
    with _LOCK:
        try:
            _LISTENERS.remove(listener)
        except ValueError:
            pass


def listeners() -> List[RunListener]:
    return list(_LISTENERS)


def emit(event: str, /, **info: Any) -> None:
    """Dispatch ``on_<event>(**info)`` to every listener. A listener that
    raises is logged and skipped — observability must never take down the
    run it observes. (``event`` is positional-only: the compile hook's
    payload reuses the name as a keyword.)"""
    if not _ENABLED or not _LISTENERS:
        return
    for l in list(_LISTENERS):
        fn = getattr(l, "on_" + event, None)
        if fn is None:
            continue
        try:
            fn(**info)
        except Exception:  # lint: broad-except — observability must never take down the run
            logger.exception("telemetry listener %r failed on %s",
                             l, event)


class CollectingRunListener(RunListener):
    """Default listener folding events into an AppMetrics-style summary
    (OpSparkListener.AppMetrics analog). The runner registers one per run
    when telemetry is on and embeds ``summary()`` in its metrics doc."""

    def __init__(self):
        self.events: List[str] = []      # ordered event names (tests/debug)
        self.run_type: Optional[str] = None
        self.app_seconds = 0.0
        self.mesh: Optional[Dict[str, Any]] = None
        self.layers = 0
        self.stages: Dict[str, Dict[str, Any]] = {}
        self.score_batches = 0
        self.rows_scored = 0
        self.compiled_batches = 0
        self.compile_events = 0
        self.compile_seconds = 0.0
        self.stats_passes = 0
        self.fit_passes_saved = 0
        self.pipeline: Optional[Dict[str, Any]] = None
        self.retries = 0
        self.quarantined: Dict[str, int] = {}
        self.breaker_trips = 0
        self.lint_findings: Dict[str, int] = {}
        self.plan: Optional[Dict[str, Any]] = None
        self.requests = 0
        self.request_rows = 0
        self.requests_failed = 0
        self.drift_advisories: Dict[str, int] = {}
        self.rollouts: Dict[str, int] = {}
        self.retrains: Dict[str, int] = {}
        self._lock = threading.Lock()

    def on_run_start(self, run_type: str, **_: Any) -> None:
        with self._lock:
            self.events.append("run_start")
            self.run_type = run_type

    def on_run_end(self, run_type: str, seconds: float = 0.0,
                   **_: Any) -> None:
        with self._lock:
            self.events.append("run_end")
            self.app_seconds = seconds

    def on_layer_start(self, index: int, n_stages: int, **_: Any) -> None:
        with self._lock:
            self.events.append("layer_start")
            self.layers = max(self.layers, index + 1)

    def on_mesh(self, devices: int, data: int, grid: int,
                platform: str = "", **_: Any) -> None:
        with self._lock:
            self.events.append("mesh")
            self.mesh = {"devices": devices, "data": data, "grid": grid,
                         "platform": platform}

    def on_stage_fit(self, uid: str, stage_name: str, fit_s: float,
                     compile_s: float = 0.0, execute_s: float = 0.0,
                     warm_started: bool = False, **_: Any) -> None:
        with self._lock:
            self.events.append("stage_fit")
            self.stages[uid] = {
                "stageName": stage_name, "fitSeconds": round(fit_s, 4),
                "compileSeconds": round(compile_s, 4),
                "executeSeconds": round(execute_s, 4),
                "warmStarted": warm_started}

    def on_stats_pass(self, layer: int, n_stages: int, n_requests: int,
                      passes_saved: int, seconds: float = 0.0,
                      **_: Any) -> None:
        with self._lock:
            self.events.append("stats_pass")
            self.stats_passes += 1
            self.fit_passes_saved += int(passes_saved)

    def on_score_batch(self, n_rows: int, bucket: int, seconds: float,
                       compiled: bool = False, **_: Any) -> None:
        with self._lock:
            self.events.append("score_batch")
            self.score_batches += 1
            self.rows_scored += int(n_rows)
            if compiled:
                self.compiled_batches += 1

    def on_pipeline_stats(self, batches: int, workers: int,
                          prefetch_depth: int, starvations: int = 0,
                          buffer_reuses: int = 0, buffer_allocs: int = 0,
                          **_: Any) -> None:
        with self._lock:
            self.events.append("pipeline_stats")
            prev = self.pipeline or {"streams": 0, "batches": 0,
                                     "starvations": 0, "bufferReuses": 0,
                                     "bufferAllocs": 0}
            # counts accumulate across streams (each stream has its own
            # pool, so the churn evidence is the SUM); workers and the
            # converged prefetch depth are per-stream facts — last wins,
            # same as the module tallies' last_* keys
            self.pipeline = {
                "streams": prev["streams"] + 1,
                "batches": prev["batches"] + int(batches),
                "workers": int(workers),
                "prefetchDepth": int(prefetch_depth),
                "starvations": prev["starvations"] + int(starvations),
                "bufferReuses": prev["bufferReuses"] + int(buffer_reuses),
                "bufferAllocs": prev["bufferAllocs"] + int(buffer_allocs)}

    def on_compile(self, event: str, seconds: float, **_: Any) -> None:
        with self._lock:
            self.events.append("compile")
            self.compile_events += 1
            self.compile_seconds += seconds

    def on_retry(self, site: str, attempt: int, error: str = "",
                 delay_s: float = 0.0, **_: Any) -> None:
        with self._lock:
            self.events.append("retry")
            self.retries += 1

    def on_quarantine(self, site: str, kind: str, count: int,
                      reason: str = "", **_: Any) -> None:
        with self._lock:
            self.events.append("quarantine")
            self.quarantined[kind] = self.quarantined.get(kind, 0) + count

    def on_breaker_trip(self, name: str, failures: int, **_: Any) -> None:
        with self._lock:
            self.events.append("breaker_trip")
            self.breaker_trips += 1

    def on_lint(self, rule: str, severity: str, message: str = "",
                **_: Any) -> None:
        with self._lock:
            self.events.append("lint")
            self.lint_findings[severity] = \
                self.lint_findings.get(severity, 0) + 1

    def on_plan(self, stages: int, engine_tier: Optional[str] = None,
                pruned_columns: int = 0, cse_merges: int = 0,
                **_: Any) -> None:
        with self._lock:
            self.events.append("plan")
            self.plan = {"stages": int(stages),
                         "engineTier": engine_tier,
                         "prunedColumns": int(pruned_columns),
                         "cseMerges": int(cse_merges)}

    def on_request(self, model: str, rows: int, seconds: float,
                   ok: bool = True, coalesced: int = 1,
                   bucket: int = 0, slo_met: Optional[bool] = None,
                   **_: Any) -> None:
        with self._lock:
            self.events.append("request")
            self.requests += 1
            self.request_rows += int(rows)
            if not ok:
                self.requests_failed += 1

    def on_drift(self, model: str, feature: str, rule: str,
                 value: float = 0.0, threshold: float = 0.0,
                 window_rows: int = 0, **_: Any) -> None:
        with self._lock:
            self.events.append("drift")
            self.drift_advisories[rule] = \
                self.drift_advisories.get(rule, 0) + 1

    def on_rollout(self, model: str, action: str,
                   version: Optional[str] = None, mode: str = "",
                   **_: Any) -> None:
        with self._lock:
            self.events.append("rollout")
            self.rollouts[action] = self.rollouts.get(action, 0) + 1

    def on_retrain(self, model: str, action: str,
                   job: Optional[str] = None,
                   version: Optional[str] = None, **_: Any) -> None:
        with self._lock:
            self.events.append("retrain")
            self.retrains[action] = self.retrains.get(action, 0) + 1

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "runType": self.run_type,
                "appSeconds": round(self.app_seconds, 3),
                "mesh": self.mesh,
                "layers": self.layers,
                "fittedStages": len(self.stages),
                "stages": dict(self.stages),
                "scoreBatches": self.score_batches,
                "rowsScored": self.rows_scored,
                "compiledBatches": self.compiled_batches,
                "compileEvents": self.compile_events,
                "compileSeconds": round(self.compile_seconds, 4),
                "statsPasses": self.stats_passes,
                "fitPassesSaved": self.fit_passes_saved,
                "pipeline": dict(self.pipeline) if self.pipeline
                else None,
                "retries": self.retries,
                "quarantined": dict(self.quarantined),
                "breakerTrips": self.breaker_trips,
                "lintFindings": dict(self.lint_findings),
                "plan": dict(self.plan) if self.plan else None,
                "requests": self.requests,
                "requestRows": self.request_rows,
                "requestsFailed": self.requests_failed,
                "driftAdvisories": dict(self.drift_advisories),
                "rollouts": dict(self.rollouts),
                "retrains": dict(self.retrains),
            }


# ---------------------------------------------------------------------------
# XLA compile clock (absorbed from workflow.py — same single listener)
# ---------------------------------------------------------------------------

#: process-wide XLA compile-time clock fed by jax.monitoring duration
#: events; stage timers snapshot it to split fit wall-clock into
#: compile-vs-execute (OpSparkListener's stage breakdown analog).
#: NOTE this sums compile WORK: concurrent compiles (the CV engine's
#: thread-pool phase) can make the delta exceed wall-clock, so consumers
#: clamp to the stage's elapsed time.
_COMPILE_CLOCK = {"s": 0.0}
_COMPILE_LISTENER_ON = [False]
#: how many times a jax.monitoring listener was actually registered —
#: must never exceed 1 per process, telemetry on OR off (the disabled
#: path registers nothing extra; the enabled path reuses the same one)
_COMPILE_LISTENER_REGISTRATIONS = [0]
_COMPILE_CLOCK_LOCK = locks.witness_lock("telemetry._COMPILE_CLOCK_LOCK")


def _ensure_compile_listener() -> None:
    """Install the single shared ``jax.monitoring`` compile listener.
    Idempotent; called lazily from fit/bench paths. The one callback
    always feeds the compile clock and ADDITIONALLY feeds the metrics
    registry + RunListeners only while telemetry is enabled."""
    if _COMPILE_LISTENER_ON[0]:
        return
    from jax import monitoring

    def on_event(event: str, duration: float, **_kw) -> None:
        if not event.startswith("/jax/core/compile/"):
            return
        with _COMPILE_CLOCK_LOCK:
            _COMPILE_CLOCK["s"] += duration
        if _ENABLED:
            counter("xla.compile_events").inc()
            counter("xla.compile_seconds").inc(duration)
            emit("compile", event=event, seconds=duration)

    monitoring.register_event_duration_secs_listener(on_event)
    _COMPILE_LISTENER_ON[0] = True
    _COMPILE_LISTENER_REGISTRATIONS[0] += 1


def compile_clock_s() -> float:
    """Cumulative XLA trace+lower+compile seconds in this process."""
    return _COMPILE_CLOCK["s"]


def peak_rss_mb():
    """Peak resident-set size of this process AND its reaped children, in
    MB (None where the ``resource`` module is unavailable — Windows).

    ``ru_maxrss`` is the high-water mark, so this is the number the
    out-of-core streaming tier is judged by: a streamed fit whose peak
    stays bounded while the materialized fit's grows with the dataset is
    the whole point (docs/performance.md "Out-of-core training").
    ``RUSAGE_CHILDREN`` folds in subprocess workers (bench subprocesses,
    fleet children) — the max of the two is reported, since RSS peaks of
    different processes at different times do not add."""
    try:
        import resource
    except ImportError:
        return None
    kb = max(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
             resource.getrusage(resource.RUSAGE_CHILDREN).ru_maxrss)
    return round(kb / 1024.0, 1)


# ---------------------------------------------------------------------------
# host<->device bandwidth probe (absorbed from workflow.py)
# ---------------------------------------------------------------------------


def probe_device_roundtrip_mbps() -> float:
    """Measure host→device→host bandwidth (MB/s) with a 4MB buffer.
    Measures on every call — ``workflow.device_roundtrip_mbps`` owns the
    once-per-process cache (the single gate-consumer entry point, which
    tests pin to force fusion either way). Monotonic timer — a wall-clock
    step mid-probe cannot fabricate an absurd gate decision."""
    import jax
    import numpy as np

    buf = np.zeros((1 << 20,), np.float32)  # 4 MB
    best = 0.0
    with span("telemetry:bandwidth_probe", bytes=buf.nbytes):
        for _ in range(2):  # first pass absorbs backend/dispatch warm-up
            t0 = time.perf_counter()
            np.asarray(jax.block_until_ready(jax.device_put(buf)))
            dt = max(time.perf_counter() - t0, 1e-9)
            best = max(best, (2 * buf.nbytes / 1e6) / dt)
    gauge("device.roundtrip_mbps").set(best)
    logger.info("host<->device bandwidth probe: %.0f MB/s (%s)",
                best, jax.devices()[0].platform)
    return best
