"""Pre-flight workflow checker — static type-flow + device shape analysis.

TransmogrifAI's headline feature is *compile-time* pipeline type safety:
a mis-wired workflow fails at compile, before Spark ever reads a byte.
The Python port discovers the same mistakes at fit time, deep inside a
``TypeError`` in ``stages/base.py``, after data loading has already been
paid for. This module is the runtime's "compiler front-end": it treats
the feature DAG as an analyzable dataflow graph (the KeystoneML framing,
PAPERS.md) and checks it **before any data is read and without touching
a device**.

Two analysis passes share one :class:`Finding` vocabulary:

* **Graph checker** (``check_workflow`` / ``check_model``, rules
  ``TMG1xx``) — walks the feature DAG edge-by-edge and re-validates
  every stage's declared input contract (``input_spec``) against the
  actual wired features, plus structural invariants the wiring helpers
  enforce only by convention: duplicate stage/feature uids, cycles,
  dead fitted stages, response-leakage reachability (complementing
  ``filters/raw_feature_filter.py``'s *runtime* leakage statistics) and
  estimator-after-model misuse.
* **Device pre-flight** (``preflight_device``, rules ``TMG2xx``) —
  propagates ``jax.ShapeDtypeStruct``s through each layer's
  ``device_compute``/``predict_device`` via ``jax.eval_shape`` over a
  tiny synthetic store (no dataset, no device dispatch — the tf.data
  static-analysis motivation): shape mismatches against the declared
  vector metadata, unintended f64 promotion under the f32 pipeline, and
  retrace/recompile hazards (per-batch-varying prepared signatures,
  bare Python scalars traced by value) that feed the existing
  ``scoring.compile_count`` guard story.

A third rule family, ``TMG3xx``, enforces *repo* invariants via the
AST-based self-lint in ``tools/tmoglint.py`` (monotonic timing uses
``time.perf_counter``, ``resilience.inject`` sites come from the
``FAULT_SITES`` catalog, telemetry spans open via context managers,
``except Exception`` only at allowlisted sites). It reuses this
module's :class:`Finding`/severity vocabulary and rule registry.

The runner executes the graph + device passes as an on-by-default
pre-flight step (``OpParams.customParams.validate``, CLI
``python -m transmogrifai_tpu check params.json`` and
``--fail-on {error,warning}``); findings mirror into telemetry
(``lint.*`` counters and the ``on_lint`` RunListener hook). See
docs/static-analysis.md for the full rule catalog with examples and
suppression syntax.
"""
from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import (Any, Dict, Iterable, List, Optional, Sequence, Set,
                    Tuple)

logger = logging.getLogger(__name__)

__all__ = [
    "Severity", "Finding", "LintError", "RULES",
    "check_workflow", "check_model", "preflight_device",
    "enforce", "emit_findings", "max_severity",
]


# ---------------------------------------------------------------------------
# rule catalog
# ---------------------------------------------------------------------------


class Severity:
    """Finding severities, orderable via :data:`_SEVERITY_RANK`."""

    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    ALL = (ERROR, WARNING, INFO)


_SEVERITY_RANK = {Severity.ERROR: 2, Severity.WARNING: 1, Severity.INFO: 0}


#: rule id -> (default severity, one-line description). The stable
#: contract: ids never change meaning, new rules get new ids
#: (docs/static-analysis.md is the narrative catalog).
RULES: Dict[str, Tuple[str, str]] = {
    # -- TMG0xx: configuration rules (params files, CLI inputs) ------------
    "TMG001": (Severity.ERROR,
               "malformed configuration value (params file / customParams)"),
    # -- TMG1xx: graph rules (static type-flow over the feature DAG) -------
    "TMG101": (Severity.ERROR,
               "input/output FeatureType incompatibility on a DAG edge"),
    "TMG102": (Severity.ERROR,
               "duplicate stage/feature uid (distinct objects collide)"),
    "TMG103": (Severity.ERROR, "cycle in the feature graph"),
    "TMG104": (Severity.WARNING,
               "orphan/dead stage unreachable from the result features"),
    "TMG105": (Severity.ERROR,
               "response leakage: label-derived feature reaches a "
               "predictor-side stage"),
    "TMG106": (Severity.ERROR,
               "estimator-after-model misuse (unfitted estimator in a "
               "scored DAG / estimator consuming a Prediction)"),
    # -- TMG2xx: device pre-flight (eval_shape, no data, no device) --------
    "TMG201": (Severity.ERROR,
               "device compute shape mismatch vs declared vector metadata"),
    "TMG202": (Severity.WARNING,
               "unintended dtype promotion: f64 output under the f32 "
               "pipeline (x32 would silently downcast / emulate)"),
    "TMG203": (Severity.WARNING,
               "retrace/recompile risk: per-batch-varying prepared "
               "signature or bare Python scalar traced by value"),
    "TMG204": (Severity.INFO,
               "pre-flight stopped: stage has no static (eval_shape) form"),
    "TMG205": (Severity.ERROR,
               "mesh-unsafe stage: device_compute row dimension does not "
               "track the input batch, so zero-weight pad_rows cannot pad "
               "it to the mesh's data axis"),
    "TMG206": (Severity.WARNING,
               "device-resident working set exceeds the per-chip VMEM "
               "envelope and feature-axis sharding is not engaged "
               "(customParams.featureShards + meshGridSize shrink the "
               "per-chip kernel working set 1/G)"),
    # -- TMG3xx: repo rules (tools/tmoglint.py AST self-lint) --------------
    "TMG301": (Severity.ERROR,
               "time.time() used for a duration — monotonic timing must "
               "use time.perf_counter() (allow: '# lint: wall-clock')"),
    "TMG302": (Severity.ERROR,
               "broad 'except Exception' outside an allowlisted "
               "breaker/fallback site (allow: '# lint: broad-except')"),
    "TMG303": (Severity.ERROR,
               "resilience.inject() names a site missing from the "
               "resilience.FAULT_SITES catalog"),
    "TMG304": (Severity.ERROR,
               "telemetry span not opened via a context manager "
               "(unpaired begin/end)"),
    "TMG305": (Severity.ERROR,
               "source file does not parse — the self-lint could not "
               "analyze it"),
    "TMG306": (Severity.ERROR,
               "direct make_mesh() call outside parallel/ — runtime code "
               "must go through process_default_mesh()/set_process_mesh "
               "(allow: '# lint: explicit-mesh — reason')"),
    "TMG307": (Severity.ERROR,
               "threading.Thread() without explicit name= and daemon= — "
               "unnamed threads make per-thread telemetry trace tracks "
               "unreadable and implicit daemonness hides shutdown "
               "semantics (allow: '# lint: thread — reason')"),
    "TMG308": (Severity.ERROR,
               "queue.Queue() without an explicit positive maxsize= — "
               "an unbounded queue between pipeline stages hides "
               "backpressure (allow: '# lint: unbounded-queue — "
               "reason')"),
    "TMG309": (Severity.ERROR,
               "subprocess.Popen() without explicit stdout= and "
               "stderr= — an inherited stream ties the child to the "
               "parent's terminal and an undrained PIPE deadlocks it; "
               "a supervisor owns its workers' streams (allow: "
               "'# lint: popen — reason')"),
    "TMG310": (Severity.ERROR,
               "long-lived thread loop body without a catch — an "
               "uncaught exception silently kills the thread and the "
               "subsystem it drives keeps 'running' with nobody home; "
               "loop bodies of Thread targets must catch-and-tally "
               "(allow: '# lint: thread-loop — reason')"),
    "TMG311": (Severity.ERROR,
               "np.argsort() without kind= / np.searchsorted() without "
               "side= in product code — order-dependent monoid folds "
               "silently change under unstable sort ties, and an "
               "implicit side= hides which boundary a temporal window "
               "includes (allow: '# lint: sort — reason')"),
    "TMG312": (Severity.ERROR,
               "pl.pallas_call() outside models/_pallas_hist.py — every "
               "kernel must live behind that module's probe/fallback "
               "gate (pallas_histograms_enabled / with_pallas_fallback) "
               "or a Mosaic rejection at production shapes fails the "
               "run instead of retracing onto the XLA path (allow: "
               "'# lint: pallas — reason')"),
    "TMG313": (Severity.ERROR,
               "telemetry.counter/gauge/histogram() with a non-literal "
               "metric name outside telemetry.py — a dynamic name is "
               "unbounded registry and /metrics exposition cardinality "
               "(every distinct name is a new instrument held forever "
               "and a new scrape family); use a literal name, or mark "
               "a deliberately dynamic-but-bounded name "
               "'# lint: metric-name — reason')"),
    "TMG314": (Severity.ERROR,
               "raw customParams read (subscript or .get()) outside "
               "config.py's registry accessors — a knob consumed off "
               "the declared surface is invisible to `cli check` "
               "validation, the effectiveConfig stamp and the tuner's "
               "search space; route through config.py (numeric_param/"
               "bool_param/string_param) or the runner wrappers, or "
               "mark a deliberate passthrough '# lint: knob — reason'"),
    "TMG399": (Severity.WARNING,
               "stale suppression: a '# lint: <marker>' escape sits on "
               "a line that no longer triggers the rule it silences — "
               "an outdated marker is camouflage for the NEXT real "
               "finding on that line; delete it (or fix the marker if "
               "it silences the wrong rule)"),
    # -- TMG5xx: serving / AOT-bank advisories (aot.py, serving.py,
    #    server.py) — degradation notices, never crash paths ---------------
    "TMG501": (Severity.WARNING,
               "AOT program bank incompatible (version skew, wrong "
               "device kind, plan/state digest mismatch) — scoring "
               "degrades to per-bucket JIT"),
    "TMG502": (Severity.WARNING,
               "AOT bank artifact corrupt/tampered/truncated — affected "
               "program(s) skipped, JIT serves those buckets"),
    "TMG503": (Severity.WARNING,
               "serving export version skew: artifact exported under a "
               "different jax/jaxlib than this process runs"),
    # -- TMG6xx: serving-time drift advisories (lifecycle.DriftSentinel —
    #    the continuous RawFeatureFilter; never crash paths) ---------------
    "TMG601": (Severity.WARNING,
               "serving-time drift: train↔live JS divergence above "
               "threshold over the sliding comparison window"),
    "TMG602": (Severity.WARNING,
               "serving-time drift: live fill rate shifted from the "
               "train-time fill rate beyond the delta/ratio thresholds"),
    "TMG603": (Severity.INFO,
               "drift sentinel inactive: model carries no train-time "
               "feature distributions (RawFeatureFilterResults)"),
    "TMG604": (Severity.WARNING,
               "continuous-training warm start unavailable: persisted "
               "train-time sufficient statistics missing or corrupt — "
               "the retrain degrades to a full refit over the fresh "
               "window"),
    "TMG605": (Severity.ERROR,
               "continuous-training controller FAILED: consecutive "
               "retrain-job failure budget exhausted — retraining is "
               "disarmed until an operator clears the job records "
               "(docs/lifecycle.md runbook)"),
    # -- TMG7xx: temporal / cutoff leakage rules (temporal.check_temporal —
    #    static, reader-aware; extend TMG105's graph taint to event time) --
    "TMG701": (Severity.ERROR,
               "temporal leakage: predictor aggregated with NO cutoff "
               "while a response folds from the same events — every "
               "predictor fold sees post-outcome rows"),
    "TMG702": (Severity.ERROR,
               "temporal leakage: response-side generator declares an "
               "event-time window — responses fold strictly AFTER the "
               "cutoff, a window reaches back across it into the "
               "predictor window"),
    "TMG703": (Severity.WARNING,
               "temporal leakage: join key derived from a response-side "
               "(post-cutoff) field routes outcome information into the "
               "joined predictors"),
    # -- TMG4xx: whole-DAG planner advisories (planner.py) -----------------
    "TMG401": (Severity.WARNING,
               "stage measured slower on device than host but is pinned "
               "to the device tier"),
    "TMG402": (Severity.INFO,
               "prunable dead columns: vectorizer output columns never "
               "reach a sink (dropped before the predictor)"),
    "TMG403": (Severity.INFO,
               "CSE opportunity suppressed: structurally identical stages "
               "differ only in uid-sensitive params/state"),
    "TMG404": (Severity.WARNING,
               "cost database unreadable (corrupt/truncated JSON) — "
               "static fallback estimates are in force"),
    "TMG405": (Severity.WARNING,
               "explicit aggregateColumnar route contradicts the cost "
               "database's measured columnar-vs-rowwise aggregation "
               "tier — the knob wins, the measurement says otherwise"),
    "TMG406": (Severity.WARNING,
               "live serving telemetry contradicts the tuned config: "
               "the online deadline controller converged a tenant's "
               "batch_deadline_s far from the params file's "
               "serveBatchDeadlineMs — re-run the offline tuner "
               "against a fresh recording (docs/tuning.md)"),
    # -- TMG8xx: whole-program concurrency & crash-safety rules
    #    (tools/concurrency_lint.py — cross-module lock-order graph,
    #    thread-escape and held-lock analysis; the runtime analog is
    #    the utils.locks lock-order witness) ------------------------------
    "TMG801": (Severity.ERROR,
               "lock-order cycle: two lock acquisition paths take the "
               "same locks in opposite orders — two threads on those "
               "paths deadlock; both acquisition paths are quoted in "
               "the finding (allow: '# lint: lock-order — reason')"),
    "TMG802": (Severity.ERROR,
               "thread-escape: shared state (module global / shared "
               "object attribute) is mutated lock-free from a function "
               "reachable as a threading.Thread target while its other "
               "mutation sites hold a guarding lock — a torn or lost "
               "update under the right interleaving (allow: "
               "'# lint: thread-escape — reason')"),
    "TMG803": (Severity.ERROR,
               "blocking call while holding a lock: queue get/put "
               "without block=False/timeout, .join()/.wait(), "
               "subprocess, socket/HTTP, or time.sleep inside a lock "
               "body — every other thread needing that lock stalls "
               "behind I/O it cannot see (allow: "
               "'# lint: lock-blocking — reason')"),
    "TMG804": (Severity.ERROR,
               "non-atomic write to a shared artifact: open(path, 'w')/"
               "json.dump into a registry record, CURRENT pointer, cost "
               "db, trace/workload shard or AOT manifest without the "
               "tmp + os.replace pattern — a crash mid-write leaves a "
               "torn file that every reader then trusts (allow: "
               "'# lint: atomic-write — reason')"),
    "TMG805": (Severity.ERROR,
               "fault-site coverage gap: a site registered in "
               "resilience.FAULT_SITES is exercised by no test "
               "(no inject-site string match under tests/) — an "
               "untested fault site is a recovery path that has never "
               "once run"),
}


@dataclass
class Finding:
    """One structured lint finding (stable rule id + severity + subject)."""

    rule: str
    message: str
    severity: str = ""
    #: stage uid (graph/device rules)
    stage: Optional[str] = None
    #: feature name (graph rules)
    feature: Optional[str] = None
    #: ``file:line`` (repo rules)
    location: Optional[str] = None

    def __post_init__(self):
        if not self.severity:
            self.severity = RULES.get(self.rule, (Severity.WARNING, ""))[0]

    def format(self) -> str:
        subject = self.location or ""
        if self.stage:
            subject = f"stage={self.stage}"
            if self.feature:
                subject += f" feature={self.feature}"
        elif self.feature:
            subject = f"feature={self.feature}"
        head = f"{self.rule} {self.severity}"
        return f"{head} [{subject}] {self.message}" if subject \
            else f"{head} {self.message}"

    def to_json(self) -> Dict[str, Any]:
        out = {"rule": self.rule, "severity": self.severity,
               "message": self.message}
        for k in ("stage", "feature", "location"):
            v = getattr(self, k)
            if v:
                out[k] = v
        return out


class LintError(Exception):
    """Pre-flight rejection: carries the findings that crossed the
    ``fail_on`` threshold (every finding rides in ``self.findings``)."""

    def __init__(self, findings: Sequence[Finding], fail_on: str):
        self.findings = list(findings)
        self.fail_on = fail_on
        over = [f for f in self.findings
                if _SEVERITY_RANK[f.severity] >= _SEVERITY_RANK[fail_on]]
        lines = "\n  ".join(f.format() for f in over)
        super().__init__(
            f"pre-flight check failed ({len(over)} finding(s) at or above "
            f"'{fail_on}'):\n  {lines}")


def max_severity(findings: Iterable[Finding]) -> Optional[str]:
    """Highest severity present, or None for an empty/clean list."""
    best: Optional[str] = None
    for f in findings:
        if best is None or _SEVERITY_RANK[f.severity] > _SEVERITY_RANK[best]:
            best = f.severity
    return best


def enforce(findings: Sequence[Finding], fail_on: str = Severity.ERROR
            ) -> None:
    """Raise :class:`LintError` when any finding reaches ``fail_on``
    (``"error"`` — the default — or ``"warning"``)."""
    if fail_on not in (Severity.ERROR, Severity.WARNING):
        raise ValueError(
            f"fail_on must be 'error' or 'warning', got {fail_on!r}")
    threshold = _SEVERITY_RANK[fail_on]
    if any(_SEVERITY_RANK[f.severity] >= threshold for f in findings):
        raise LintError(findings, fail_on)


def emit_findings(findings: Sequence[Finding]) -> None:
    """Mirror findings into telemetry: ``lint.errors`` / ``lint.warnings``
    / ``lint.info`` counters plus one ``on_lint`` RunListener event per
    finding. No-op cost when telemetry is off (null instruments)."""
    from . import telemetry
    names = {Severity.ERROR: "lint.errors", Severity.WARNING:
             "lint.warnings", Severity.INFO: "lint.info"}
    for f in findings:
        telemetry.counter(names[f.severity]).inc()  # lint: metric-name — three fixed severity names
        telemetry.emit("lint", rule=f.rule, severity=f.severity,
                       message=f.message, stage=f.stage,
                       feature=f.feature, location=f.location)


def _apply_suppress(findings: List[Finding],
                    suppress: Iterable[str]) -> List[Finding]:
    if isinstance(suppress, str):
        # a lone "TMG104" (easy JSON mistake for ["TMG104"]) must not be
        # iterated character-by-character
        suppress = (suppress,)
    sup = {str(s).upper() for s in (suppress or ())}
    if not sup:
        return findings
    unknown = sup - set(RULES)
    if unknown:
        raise ValueError(f"unknown lint rule id(s) in suppress: "
                         f"{sorted(unknown)}")
    return [f for f in findings if f.rule not in sup]


# ---------------------------------------------------------------------------
# graph traversal (identity-based — the uid-keyed dicts in graph.py would
# hide exactly the duplicate-uid collisions TMG102 exists to catch)
# ---------------------------------------------------------------------------


def _walk_features(result_features) -> Tuple[List[Any], List[Any],
                                             List[Finding]]:
    """DFS over the feature DAG by object identity.

    Returns ``(features, stages, findings)`` where ``features`` is in
    topological order (ancestors first), ``stages`` are the distinct
    origin-stage objects in the same order, and ``findings`` holds any
    TMG103 cycle findings (traversal stops descending into a cycle but
    still reports everything reachable)."""
    findings: List[Finding] = []
    feats: List[Any] = []
    stage_ids: Set[int] = set()
    stages: List[Any] = []
    done: Set[int] = set()
    on_path: Set[int] = set()
    cycles_seen: Set[int] = set()

    def visit(f) -> None:
        fid = id(f)
        if fid in done:
            return
        if fid in on_path:
            if fid not in cycles_seen:
                cycles_seen.add(fid)
                findings.append(Finding(
                    "TMG103", f"cycle in the feature graph at "
                    f"{f.name!r}: the feature is its own ancestor",
                    feature=f.name))
            return
        on_path.add(fid)
        for p in f.parents:
            visit(p)
        on_path.discard(fid)
        done.add(fid)
        feats.append(f)
        st = f.origin_stage
        if st is not None and id(st) not in stage_ids:
            stage_ids.add(id(st))
            stages.append(st)

    for f in result_features:
        visit(f)
    return feats, stages, findings


def _stage_label(stage) -> str:
    try:
        return f"{stage.stage_name()} [{stage.uid}]"
    except Exception:  # lint: broad-except — labels must never break lint
        return repr(stage)


def _check_graph(result_features, fitted_stages: Optional[Dict[str, Any]]
                 = None, known_stages: Optional[Sequence[Any]] = None
                 ) -> List[Finding]:
    """All TMG1xx rules over a feature DAG.

    ``fitted_stages`` (a WorkflowModel's uid → FittedModel map) arms the
    TMG104 dead-stage and TMG106 unfitted-estimator rules;
    ``known_stages`` is an optional extra stage universe checked for
    reachability (TMG104)."""
    from .stages.base import AllowLabelAsInput, Estimator, Transformer
    from .stages.generator import FeatureGeneratorStage
    from .types.feature_types import Prediction

    feats, stages, findings = _walk_features(result_features)
    feat_ids = {id(f) for f in feats}

    # TMG102 — duplicate uids: distinct objects sharing one uid collapse
    # into a single node in every uid-keyed map (graph.compute_dag,
    # fitted_stages, checkpoints) and silently drop a stage
    by_uid: Dict[str, List[Any]] = {}
    for st in stages:
        by_uid.setdefault(st.uid, []).append(st)
    for uid, group in by_uid.items():
        if len(group) > 1:
            names = ", ".join(s.stage_name() for s in group)
            findings.append(Finding(
                "TMG102", f"duplicate stage uid shared by {len(group)} "
                f"distinct stages: {names}", stage=uid))
    feat_by_uid: Dict[str, List[Any]] = {}
    for f in feats:
        feat_by_uid.setdefault(f.uid, []).append(f)
    for uid, group in feat_by_uid.items():
        if len(group) > 1:
            names = ", ".join(f.name for f in group)
            findings.append(Finding(
                "TMG102", f"duplicate feature uid shared by {len(group)} "
                f"distinct features: {names}", feature=names))

    # per-stage contract checks, ancestors first
    for st in stages:
        if isinstance(st, FeatureGeneratorStage):
            continue
        label = _stage_label(st)
        ins = tuple(getattr(st, "input_features", ()) or ())
        if not ins:
            findings.append(Finding(
                "TMG104", f"orphan stage {label}: inputs never set "
                "(set_input was not called)", stage=st.uid))
            continue

        # TMG101 — re-run the declared input contract statically. set_input
        # enforces it at wiring time, but graphs built by hand, loaded from
        # JSON or rewired (copy_dag, warm start) can bypass it; here it
        # fails BEFORE data loading instead of as a fit-time TypeError.
        try:
            spec = st.input_spec
        except NotImplementedError:
            spec = None
        if spec is not None:
            try:
                spec.check(ins)
            except TypeError as e:
                feat_names = ", ".join(
                    f"{f.name}: {f.ftype.__name__}" for f in ins)
                declared = getattr(spec, "describe", lambda: "?")()
                findings.append(Finding(
                    "TMG101", f"{label} declares inputs {declared} but "
                    f"is wired to ({feat_names}): {e}", stage=st.uid,
                    feature=ins[0].name))

        # TMG101 — a feature claiming a type its producing stage does not
        # output (hand-built Feature nodes)
        try:
            out = st.get_output()
        except ValueError:
            out = None
        if out is not None and id(out) in feat_ids \
                and not issubclass(st.output_type, out.ftype) \
                and not issubclass(out.ftype, st.output_type):
            findings.append(Finding(
                "TMG101", f"feature {out.name!r} claims type "
                f"{out.ftype.__name__} but its origin {label} outputs "
                f"{st.output_type.__name__}", stage=st.uid,
                feature=out.name))

        # TMG106 — an estimator consuming a model's Prediction output:
        # fitting on predictions downstream of the selector is the classic
        # estimator-after-model misuse (the reference allows at most one
        # label-aware model chain)
        if isinstance(st, Estimator) and any(
                issubclass(f.ftype, Prediction) for f in ins):
            pf = next(f for f in ins if issubclass(f.ftype, Prediction))
            findings.append(Finding(
                "TMG106", f"estimator {label} consumes model output "
                f"{pf.name!r} (Prediction) — estimators must fit on "
                "features, not on a downstream model's predictions",
                severity=Severity.WARNING, stage=st.uid, feature=pf.name))

    # TMG105 — response-leakage reachability. set_input gates DIRECT
    # label/predictor mixing; this propagates label taint transitively, so
    # a label-derived feature laundered through an intermediate stage is
    # still caught. AllowLabelAsInput stages (sanity checker, selectors)
    # are the sanctioned consumers: their outputs are considered clean.
    bearing: Dict[int, bool] = {id(f): bool(f.is_response) for f in feats}
    for f in feats:
        st = f.origin_stage
        if st is None or isinstance(st, FeatureGeneratorStage):
            continue
        ins = tuple(getattr(st, "input_features", ()) or ())
        if not ins:
            continue
        flags = [bearing.get(id(p), bool(p.is_response)) for p in ins]
        if isinstance(st, AllowLabelAsInput):
            out_bearing = all(flags)
        elif any(flags) and not all(flags):
            leaked = [p.name for p, b in zip(ins, flags) if b]
            findings.append(Finding(
                "TMG105", f"response leakage: {_stage_label(st)} mixes "
                f"label-derived feature(s) {leaked} with predictors but "
                "is not AllowLabelAsInput — its output would leak the "
                "label into the feature matrix", stage=st.uid,
                feature=leaked[0]))
            out_bearing = True
        else:
            out_bearing = all(flags)
        bearing[id(f)] = bearing.get(id(f), False) or out_bearing

    # fitted-model rules (WorkflowModel)
    if fitted_stages is not None:
        dag_uids = {st.uid for st in stages}
        for st in stages:
            if isinstance(st, Estimator) and not isinstance(st, Transformer) \
                    and st.uid not in fitted_stages:
                findings.append(Finding(
                    "TMG106", f"unfitted estimator {_stage_label(st)} in a "
                    "scored DAG: scoring would raise 'Estimator has no "
                    "fitted model' at transform time", stage=st.uid))
        for uid in fitted_stages:
            if uid not in dag_uids:
                findings.append(Finding(
                    "TMG104", f"dead fitted stage [{uid}]: not reachable "
                    "from the result features (stale checkpoint or pruned "
                    "graph)", stage=uid))

    if known_stages:
        dag_ids = {id(st) for st in stages}
        dag_uids = {st.uid for st in stages}
        for st in known_stages:
            if id(st) not in dag_ids and st.uid not in dag_uids:
                findings.append(Finding(
                    "TMG104", f"dead stage {_stage_label(st)}: not "
                    "reachable from the result features", stage=st.uid))

    return findings


def check_workflow(workflow, known_stages: Optional[Sequence[Any]] = None,
                   suppress: Iterable[str] = (),
                   reader: Any = None) -> List[Finding]:
    """Static graph check (TMG1xx) over an untrained :class:`Workflow`
    (or a bare sequence of result features). Touches no data and no
    device — the compile-time type-safety analog.

    When a ``reader`` is known (passed explicitly — the runner hands its
    training reader in — or set on the workflow via ``set_reader``), the
    temporal cutoff-leakage rules (TMG7xx, ``temporal.check_temporal``)
    run too: the reader OBJECT is inspected structurally, never polled,
    so this still reads no data."""
    feats = getattr(workflow, "result_features", workflow)
    findings = _check_graph(tuple(feats), known_stages=known_stages)
    if reader is None:
        reader = getattr(workflow, "_reader", None)
    if reader is not None:
        from . import temporal
        findings.extend(temporal.check_temporal(reader, tuple(feats)))
    return _apply_suppress(findings, suppress)


def check_model(model, device: bool = True, n_rows: int = 8,
                suppress: Iterable[str] = ()) -> List[Finding]:
    """Graph check (TMG1xx, incl. unfitted-estimator/dead-stage rules)
    plus — when ``device`` — the eval_shape pre-flight (TMG2xx) over a
    fitted :class:`WorkflowModel`."""
    # suppression applies BEFORE the device-pass gate: a suppressed
    # (known/accepted) graph error must not silently disable the TMG2xx
    # shape analysis
    findings = _apply_suppress(
        _check_graph(model.result_features,
                     fitted_stages=model.fitted_stages), suppress)
    if device:
        if any(f.severity == Severity.ERROR for f in findings):
            # a structurally broken DAG cannot be shape-propagated
            # meaningfully — say so instead of skipping silently
            findings.extend(_apply_suppress([Finding(
                "TMG204", "device pre-flight skipped: the graph rules "
                "above found errors (fix or suppress them to get shape "
                "analysis)")], suppress))
        else:
            findings.extend(_apply_suppress(
                preflight_device(model, n_rows=n_rows), suppress))
    return findings


# ---------------------------------------------------------------------------
# device pre-flight (TMG2xx) — ShapeDtypeStructs through eval_shape
# ---------------------------------------------------------------------------


def _placeholder_column(ftype, n: int):
    """A synthetic n-row column of the feature's type: defaults only, no
    dataset read. NonNullable numerics get zeros (None would violate the
    type), raw vectors a width-1 zero matrix, everything else its empty
    value."""
    import numpy as np

    from .columns import VectorColumn, column_from_values, column_of_empty
    from .types.feature_types import (NonNullable, OPNumeric, OPVector,
                                      Prediction)
    if issubclass(ftype, OPVector):
        return VectorColumn(OPVector, np.zeros((n, 1), dtype=np.float32),
                            None)
    if issubclass(ftype, Prediction):
        # Prediction forbids an empty value (the "prediction" key is
        # mandatory) — a zero prediction is the neutral placeholder
        return column_from_values(ftype, [{"prediction": 0.0}] * n)
    if issubclass(ftype, OPNumeric) and issubclass(ftype, NonNullable):
        return column_from_values(ftype, [0.0] * n)
    return column_of_empty(ftype, n)


def _synthetic_store(result_features, n: int):
    from .columns import ColumnStore
    seen: Dict[str, Any] = {}
    for f in result_features:
        for raw in f.raw_features():
            seen.setdefault(raw.name, raw.ftype)
    return ColumnStore({name: _placeholder_column(ft, n)
                        for name, ft in seen.items()}, n)


def _prepared_signature(prepared: Dict[str, Any], n: int):
    """Shape signature of a prepared-block dict with the row dimension
    normalized out, so signatures taken at different batch sizes compare
    equal iff the program cache would reuse one executable."""
    import numpy as np
    sig = []
    for k in sorted(prepared):
        a = np.asarray(prepared[k])
        shape = tuple("N" if d == n else d for d in a.shape)
        sig.append((k, str(a.dtype), shape))
    return tuple(sig)


# TMG206 — per-chip VMEM envelope the device-resident working set of a
# single stage is held against. 16 MiB is the common per-core budget on
# current TPU generations; override with TMOG_VMEM_BYTES for other parts
# (or to exercise the rule in tests with a tiny envelope). The working
# set is extrapolated from the pre-flight probe to TMOG_VMEM_PROBE_ROWS
# rows so the estimate reflects a production batch, not the 8-row probe.
VMEM_ENVELOPE_BYTES = int(os.environ.get("TMOG_VMEM_BYTES",
                                         16 * 1024 * 1024))
VMEM_PROBE_ROWS = int(os.environ.get("TMOG_VMEM_PROBE_ROWS", 8192))


def preflight_device(model, n_rows: int = 8) -> List[Finding]:
    """TMG2xx: propagate shapes/dtypes through every layer's device
    computes via ``jax.eval_shape`` — no dataset, no device dispatch.

    Host-side stages run for real on a tiny synthetic store (cheap, pure
    numpy); each :class:`VectorizerModel`'s ``device_compute`` and each
    predictor's ``predict_device`` are *abstractly* evaluated, so shape
    mismatches, f64 promotion and retrace hazards surface before the
    first real batch compiles."""
    import numpy as np

    findings: List[Finding] = []
    try:
        import jax
        import jax.numpy as jnp
    except Exception as e:  # lint: broad-except — preflight degrades, never kills
        findings.append(Finding(
            "TMG204", f"device pre-flight skipped: jax unavailable ({e})"))
        return findings

    from .columns import PredictionColumn, VectorColumn
    from .models.base import PredictorModel
    from .ops.vectorizer_base import (VEC_DTYPE, VectorizerModel,
                                      canonicalize_prepared)
    from .types.feature_types import OPVector

    n2 = n_rows + 3          # second probe size for the retrace check
    store = _synthetic_store(model.result_features, n_rows)
    store2 = _synthetic_store(model.result_features, n2)

    def halt(stage, exc) -> None:
        findings.append(Finding(
            "TMG204", f"pre-flight stopped at {_stage_label(stage)}: no "
            f"static form ({type(exc).__name__}: {exc})", stage=stage.uid))

    try:
        layers = model._resolved_dag()
    except Exception as e:  # lint: broad-except — an unresolvable DAG is a coverage note here (the graph rules own the error)
        findings.append(Finding(
            "TMG204", f"device pre-flight skipped: the model's DAG does "
            f"not resolve ({e})"))
        return findings
    for layer in layers:
        for m in layer:
            if isinstance(m, VectorizerModel):
                try:
                    raw_prep = m.host_prepare(store)
                    scalars = sorted(k for k, v in raw_prep.items()
                                     if isinstance(v, (int, float))
                                     and not isinstance(v, bool))
                    prep = canonicalize_prepared(raw_prep)
                    prep2 = canonicalize_prepared(m.host_prepare(store2))
                except Exception as e:  # lint: broad-except — report, don't crash pre-flight
                    halt(m, e)
                    return findings
                if scalars:
                    findings.append(Finding(
                        "TMG203", f"{_stage_label(m)} host_prepare returns "
                        f"bare Python scalar(s) {scalars}: a scalar traced "
                        "by value bakes into the compiled program and a "
                        "per-call-varying one forces a retrace per call "
                        "(wrap in np.asarray)", stage=m.uid))
                sig1 = _prepared_signature(prep, n_rows)
                sig2 = _prepared_signature(prep2, n2)
                if sig1 != sig2:
                    moved = sorted(
                        {k for k, _, _ in set(sig1) ^ set(sig2)})
                    findings.append(Finding(
                        "TMG203", f"{_stage_label(m)} prepared signature "
                        f"varies with batch size (blocks {moved}): every "
                        "distinct batch shape recompiles its device "
                        "program (scoring.compile_count grows per call, "
                        "not per bucket)", stage=m.uid))
                structs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                           for k, v in prep.items()}
                truncated = False
                try:
                    # under x32 a requested f64 never reaches the output
                    # dtype — jax silently truncates it to f32 with a
                    # UserWarning. Capturing that warning is the ONLY
                    # static evidence of the promotion in the production
                    # (TPU/x32) configuration; under x64 the dtype check
                    # below sees it directly.
                    import warnings as _warnings
                    with _warnings.catch_warnings(record=True) as caught:
                        _warnings.simplefilter("always")
                        out = jax.eval_shape(
                            lambda p, _m=m: _m.device_compute(jnp, p),
                            structs)
                    truncated = any(
                        "truncated to dtype float32" in str(w.message)
                        for w in caught)
                except Exception as e:  # lint: broad-except — any eval failure IS the finding
                    findings.append(Finding(
                        "TMG201", f"{_stage_label(m)} device_compute fails "
                        f"shape propagation: {type(e).__name__}: {e}",
                        stage=m.uid))
                    return findings
                meta = m.vector_metadata()
                shape = tuple(out.shape)
                if len(shape) != 2 or shape[0] != n_rows \
                        or shape[1] != meta.size:
                    findings.append(Finding(
                        "TMG201", f"{_stage_label(m)} device_compute "
                        f"produces shape {shape}, expected "
                        f"({n_rows}, {meta.size}) per its vector metadata",
                        stage=m.uid))
                    width = shape[1] if len(shape) == 2 else meta.size
                else:
                    width = meta.size
                    # TMG205 — the mesh padding contract: a second probe
                    # size must move the output's row dimension with it.
                    # A stage that bakes the row count into its program
                    # (static slice/reshape) cannot be zero-weight-padded
                    # to the mesh's data axis (parallel/mesh.pad_rows),
                    # so a multichip run would compute on the wrong rows.
                    structs2 = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                                for k, v in prep2.items()}
                    try:
                        out2 = jax.eval_shape(
                            lambda p, _m=m: _m.device_compute(jnp, p),
                            structs2)
                        shape2 = tuple(out2.shape)
                    except Exception as e:  # lint: broad-except — a batch-size-dependent failure IS the finding
                        shape2 = None
                        findings.append(Finding(
                            "TMG205", f"{_stage_label(m)} device_compute "
                            f"fails at a second batch size ({n2} rows: "
                            f"{type(e).__name__}: {e}) — rows cannot be "
                            "padded to the mesh data axis", stage=m.uid))
                    if shape2 is not None and (len(shape2) != 2
                                               or shape2[0] != n2):
                        findings.append(Finding(
                            "TMG205", f"{_stage_label(m)} device_compute "
                            f"row dimension does not track the batch "
                            f"({n_rows}→{shape[0]} rows but "
                            f"{n2}→{shape2[0] if shape2 else '?'}): "
                            "zero-weight pad_rows cannot pad it to the "
                            "mesh's data axis", stage=m.uid))
                if out.dtype == np.float64 or truncated:
                    findings.append(Finding(
                        "TMG202", f"{_stage_label(m)} device_compute "
                        "promotes to float64: under x32 this silently "
                        "downcasts (and on TPU f64 is emulated) — the "
                        "pipeline dtype is f32", stage=m.uid))
                # TMG206 — VMEM envelope: extrapolate the stage's prepared
                # blocks (its device-resident inputs) from the probe batch
                # to VMEM_PROBE_ROWS rows. Row dims scale; constant dims
                # (vocab tables, bin edges) count as-is. Advisory only —
                # the estimate ignores intermediates and XLA's own layout,
                # so it flags order-of-magnitude overruns, not near-misses.
                try:
                    from .models._treefit import active_feature_shards
                    resident = 0
                    for v in prep.values():
                        a = np.asarray(v)
                        nb = int(a.dtype.itemsize)
                        for d in a.shape:
                            nb *= (VMEM_PROBE_ROWS if d == n_rows
                                   else int(d))
                        resident += nb
                    if (resident > VMEM_ENVELOPE_BYTES
                            and active_feature_shards() <= 1):
                        findings.append(Finding(
                            "TMG206", f"{_stage_label(m)} device-resident "
                            f"working set ~{resident / 2**20:.1f} MiB at "
                            f"{VMEM_PROBE_ROWS} rows exceeds the "
                            f"{VMEM_ENVELOPE_BYTES / 2**20:.0f} MiB VMEM "
                            "envelope with feature sharding off: set "
                            "customParams.featureShards (with a grid "
                            "mesh) to shard columns 1/G per chip, or "
                            "customParams.streamFit to bound the host "
                            "working set", stage=m.uid))
                except Exception:  # lint: broad-except — the envelope estimate is advisory, never kills pre-flight
                    pass
                store = store.with_column(
                    m.output_name,
                    VectorColumn(OPVector,
                                 np.zeros((n_rows, width), dtype=VEC_DTYPE),
                                 meta))
                store2 = store2.with_column(
                    m.output_name,
                    VectorColumn(OPVector,
                                 np.zeros((n2, width), dtype=VEC_DTYPE),
                                 meta))
            elif isinstance(m, PredictorModel):
                fcol = store.get(m.input_features[1].name)
                if not isinstance(fcol, VectorColumn):
                    halt(m, TypeError("feature input is not a vector"))
                    return findings
                width = fcol.values.shape[1]
                try:
                    pred, raw, prob = jax.eval_shape(
                        m.predict_device,
                        jax.ShapeDtypeStruct((n_rows, width),
                                             np.dtype(VEC_DTYPE)))
                except Exception as e:  # lint: broad-except — report, don't crash pre-flight
                    halt(m, e)
                    return findings
                if tuple(pred.shape) != (n_rows,):
                    findings.append(Finding(
                        "TMG201", f"{_stage_label(m)} predict_device "
                        f"prediction shape {tuple(pred.shape)}, expected "
                        f"({n_rows},)", stage=m.uid))
                k = raw.shape[1] if len(raw.shape) == 2 else 0
                pcol = PredictionColumn(
                    np.zeros((n_rows,)), np.zeros((n_rows, k)),
                    np.zeros((n_rows, k)))
                pcol2 = PredictionColumn(
                    np.zeros((n2,)), np.zeros((n2, k)), np.zeros((n2, k)))
                store = store.with_column(m.output_name, pcol)
                store2 = store2.with_column(m.output_name, pcol2)
            else:
                # host-only stage: run it for real on the tiny store
                try:
                    store = m.transform(store)
                    store2 = m.transform(store2)
                except Exception as e:  # lint: broad-except — report, don't crash pre-flight
                    halt(m, e)
                    return findings
    return findings
