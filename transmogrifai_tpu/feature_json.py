"""Feature-graph JSON (de)serialization — FeatureJsonHelper analog.

Parity: ``features/.../FeatureJsonHelper.scala`` (140 LoC): round-trip an
UNFITTED feature DAG (features + origin stages + wiring) through JSON —
e.g. to version feature definitions or ship them between services —
independent of any trained model. Reuses model_io's stage/feature record
format so the two serializations can never drift; numpy ctor params are
embedded as lists (a feature graph carries no fitted weights).
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from . import model_io
from .features import Feature

__all__ = ["features_to_json", "features_from_json"]


def features_to_json(result_features: Sequence[Feature]) -> Dict[str, Any]:
    arrays: Dict[str, np.ndarray] = {}
    feats = model_io._topo_features(result_features)
    stage_records = model_io.collect_stage_records(feats, arrays)
    return {
        "features": [model_io._feature_record(f) for f in feats],
        "resultFeatureUids": [f.uid for f in result_features],
        "stages": stage_records,
        "arrays": {k: v.tolist() for k, v in arrays.items()},
    }


def features_from_json(doc: Dict[str, Any]) -> List[Feature]:
    """Rebuild the result features (and their whole ancestor graph)."""
    arrays = {k: np.asarray(v) for k, v in (doc.get("arrays") or {}).items()}
    stage_by_uid = model_io.rebuild_stages(doc["stages"], arrays)
    feat_by_uid = model_io.rebuild_features(doc["features"], stage_by_uid)
    return [feat_by_uid[u] for u in doc["resultFeatureUids"]]
