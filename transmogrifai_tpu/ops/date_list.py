"""DateList vectorization — event-list time pivots.

Parity: ``DateListVectorizer`` (``core/.../impl/feature/DateListVectorizer.scala``):
pivots a list of event timestamps into ``SinceLast`` / ``SinceFirst`` /
``ModeDay`` style summaries. Default pivot is SinceLast (days since the most
recent event, relative to a reference date) + null tracking.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columns import ColumnStore, RaggedColumn
from ..stages.base import register_stage
from ..types.feature_types import DateList
from ..vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizer_base import (TransmogrifierDefaults, VectorizerModel,
                              null_indicator_meta)

__all__ = ["DateListVectorizer", "DateListPivot"]

_MS_PER_DAY = 24 * 3600 * 1000


class DateListPivot:
    SINCE_LAST = "SinceLast"
    SINCE_FIRST = "SinceFirst"


@register_stage
class DateListVectorizer(VectorizerModel):
    """[days since last/first event, (null)] per feature. Pure transformer
    (reference date is a param, no fit state)."""

    operation_name = "vecDateList"
    seq_type = DateList

    def __init__(self, pivot: str = DateListPivot.SINCE_LAST,
                 reference_date_ms: Optional[int] = None,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 input_names: Sequence[str] = (),
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.pivot = pivot
        self.reference_date_ms = reference_date_ms
        self.track_nulls = track_nulls
        self.input_names_saved = list(input_names)

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        names = self._names()
        n = store.n_rows
        k = len(names)
        anchor = np.zeros((n, k), dtype=np.float64)
        mask = np.zeros((n, k), dtype=bool)
        ref = self.reference_date_ms
        reduce = (np.maximum if self.pivot == DateListPivot.SINCE_LAST
                  else np.minimum)
        for j, name in enumerate(names):
            col = store[name]
            assert isinstance(col, RaggedColumn)
            flat = col.flat.astype(np.float64, copy=False)
            counts = np.diff(col.offsets)
            m = counts > 0
            mask[:, j] = m
            if flat.size:
                # segment-reduce over the ragged rows, no per-row Python.
                # Boundaries come from NON-EMPTY rows only: their starts are
                # strictly increasing and each segment then spans exactly
                # that row's events (empty rows contribute no boundary, so
                # they can't truncate a neighbour's segment).
                nonempty = np.flatnonzero(m)
                anchor[nonempty, j] = reduce.reduceat(
                    flat, col.offsets[:-1][nonempty])
        if ref is None:
            present = anchor[mask]
            ref = float(present.max()) if present.size else 0.0
        # subtract epoch-scale anchors on host in f64: ref-anchor is a
        # catastrophic cancellation in f32 (both ~1.7e12); the day delta
        # itself is small and f32-safe
        days = (float(ref) - anchor) / _MS_PER_DAY
        return {"days": days, "mask": mask}

    def device_compute(self, xp, prepared):
        days, mask = prepared["days"], prepared["mask"]
        days = xp.where(mask, days, 0.0)
        if not self.track_nulls:
            return days
        n, k = days.shape
        nulls = (~mask).astype(days.dtype)
        return xp.stack([days, nulls], axis=2).reshape(n, 2 * k)

    def vector_metadata(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for name in self._names():
            cols.append(VectorColumnMetadata(
                parent_feature_name=name, parent_feature_type="DateList",
                descriptor_value=self.pivot))
            if self.track_nulls:
                cols.append(null_indicator_meta(name, "DateList"))
        return VectorMetadata(self.meta_name, cols)
