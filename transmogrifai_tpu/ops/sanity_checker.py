"""SanityChecker — automated feature validation and bad-feature removal.

Parity: ``core/.../impl/preparators/SanityChecker.scala`` (fitFn :535-693,
``ColumnStatistics.reasonsToRemove`` :783-832, defaults :720-739).

TPU re-design: the reference runs ``Statistics.colStats`` + a corr matrix +
a ``reduceByKey`` contingency sweep as separate Spark jobs; here the whole
fit is **two fused device matmuls**:

* moments + correlations: append the label to the feature matrix and compute
  one ``Zᵀ Z`` gram (means/variances/Pearson all fall out of it);
* categorical stats: one ``Yᵀ X`` contingency matmul over the one-hot label
  against every categorical indicator block → χ² / Cramér's V / PMI /
  rule support+confidence per group (``OpStatistics.contingencyStats``,
  ``utils/.../stats/OpStatistics.scala:300``).

The fitted model drops flagged vector slots and re-indexes the metadata.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnStore, VectorColumn
from ..stages.base import (AllowLabelAsInput, Estimator, FittedModel,
                           FixedArity, InputSpec, register_stage)
from ..types.feature_types import OPVector, RealNN
from ..vector_metadata import VectorMetadata
from .vectorizer_base import VectorizerModel

__all__ = ["SanityChecker", "SanityCheckerModel", "SanityCheckerSummary",
           "compute_sanity_stats"]

# defaults (SanityChecker.scala:720-739)
CHECK_SAMPLE = 1.0
SAMPLE_LOWER_LIMIT = 1_000
SAMPLE_UPPER_LIMIT = 1_000_000
MAX_CORRELATION = 0.95
MIN_CORRELATION = 0.0
MIN_VARIANCE = 1e-5
MAX_CRAMERS_V = 0.95
MAX_RULE_CONFIDENCE = 1.0
MIN_REQUIRED_RULE_SUPPORT = 1.0


# statistics kernels live in utils.stats (the OpStatistics analog);
# aliased here for the fit path below
from ..utils.stats import (contingency as _contingency_kernel,
                           cramers_v_stats as _cramers_v,
                           moments as _moments_kernel,
                           pmi_mutual_info as _pmi_mi,
                           spearman_with_label as _spearman_with_label)


class SanityCheckerSummary:
    """Per-column stats + dropped columns with reasons
    (SanityCheckerMetadata.scala)."""

    def __init__(self):
        self.column_stats: List[Dict[str, Any]] = []
        self.categorical_stats: List[Dict[str, Any]] = []
        self.dropped: List[Dict[str, Any]] = []
        self.names: List[str] = []
        self.correlations_with_label: List[float] = []

    def to_json(self) -> Dict[str, Any]:
        return {"columnStats": self.column_stats,
                "categoricalStats": self.categorical_stats,
                "droppedColumns": self.dropped,
                "correlationsWithLabel": dict(
                    zip(self.names, self.correlations_with_label))}


@register_stage
class SanityCheckerModel(FittedModel, AllowLabelAsInput):
    """Drops flagged slots; output vector = kept columns."""

    operation_name = "sanityCheck"
    output_type = OPVector

    def __init__(self, keep_indices: Sequence[int] = (),
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.keep_indices = list(map(int, keep_indices))
        self.summary_: Optional[SanityCheckerSummary] = None

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, OPVector)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[1].name]
        assert isinstance(col, VectorColumn)
        meta = col.metadata.select(self.keep_indices) if col.metadata else None
        if meta is not None:
            meta.name = self.output_name
        if self.keep_indices == list(range(col.values.shape[1])):
            # nothing dropped: reuse the input matrix (the fancy-index
            # below always copies — 1.3 GB at the 300k big_text config)
            return VectorColumn(OPVector, col.values, meta)
        idx = np.asarray(self.keep_indices, dtype=np.int64)
        return VectorColumn(OPVector, col.values[:, idx], meta)

    def get_model_state(self):
        return {"keep_indices": self.keep_indices}

    def summary(self):
        return self.summary_.to_json() if self.summary_ else {}


@register_stage
class SanityChecker(Estimator, AllowLabelAsInput):
    """Estimator(label, features) → cleaned OPVector."""

    operation_name = "sanityCheck"
    output_type = OPVector

    def __init__(self, max_correlation: float = MAX_CORRELATION,
                 min_correlation: float = MIN_CORRELATION,
                 min_variance: float = MIN_VARIANCE,
                 max_cramers_v: float = MAX_CRAMERS_V,
                 remove_bad_features: bool = False,
                 remove_feature_group: bool = True,
                 protect_text_shared_hash: bool = True,
                 max_rule_confidence: float = MAX_RULE_CONFIDENCE,
                 min_required_rule_support: float = MIN_REQUIRED_RULE_SUPPORT,
                 feature_label_corr_only: bool = False,
                 correlation_type: str = "pearson",
                 check_sample: float = CHECK_SAMPLE,
                 sample_seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_correlation = max_correlation
        self.min_correlation = min_correlation
        self.min_variance = min_variance
        self.max_cramers_v = max_cramers_v
        self.remove_bad_features = remove_bad_features
        self.remove_feature_group = remove_feature_group
        # reference default protects hashed text columns from the corr gate
        # (SanityChecker.scala:596-627)
        self.protect_text_shared_hash = protect_text_shared_hash
        self.max_rule_confidence = max_rule_confidence
        self.min_required_rule_support = min_required_rule_support
        self.feature_label_corr_only = feature_label_corr_only
        if correlation_type not in ("pearson", "spearman"):
            raise ValueError(
                f"correlation_type must be pearson|spearman, got "
                f"{correlation_type!r}")
        #: which correlation drives the corr gate (SanityChecker.scala:634-638
        #: CorrelationType); both are always reported in the summary
        self.correlation_type = correlation_type
        self.check_sample = check_sample
        self.sample_seed = sample_seed

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, OPVector)

    def fit_columns(self, store: ColumnStore) -> SanityCheckerModel:
        stats = compute_sanity_stats(
            store, self.input_features[0].name,
            self.input_features[1].name,
            feature_label_corr_only=self.feature_label_corr_only,
            correlation_type=self.correlation_type,
            check_sample=self.check_sample,
            sample_seed=self.sample_seed)
        return self._finalize_from_stats(stats)

    # -- fused fit-statistics opt-in (fitstats.py) -------------------------
    def _stats_params(self) -> Tuple:
        return (("feature_label_corr_only", self.feature_label_corr_only),
                ("correlation_type", self.correlation_type),
                ("check_sample", self.check_sample),
                ("sample_seed", self.sample_seed))

    def stat_requests(self, store):
        from ..fitstats import StatRequest
        return [StatRequest("sanity", self.input_features[1].name,
                            label=self.input_features[0].name,
                            params=self._stats_params())]

    def fit_columns_from_stats(self, store, stats):
        return self._finalize_from_stats(stats.value(
            "sanity", self.input_features[1].name,
            label=self.input_features[0].name,
            params=self._stats_params()))

    def _finalize_from_stats(self, stats: Dict[str, Any]
                             ) -> SanityCheckerModel:
        """Host-side finalize: thresholds, reasons, summary and the
        keep-index model from the computed statistics. Shared verbatim
        by the sequential fit and the fused layer pass — the two paths
        cannot drift."""
        d = stats["d"]
        meta = stats["meta"]
        names = stats["names"]
        is_hash = stats["is_hash"]
        mean, var = stats["mean"], stats["var"]
        corr_label = stats["corr_label"]
        zmin, zmax = stats["zmin"], stats["zmax"]
        spearman_label = stats["spearman_label"]
        ordered, conts = stats["ordered"], stats["conts"]

        gate_corr = (spearman_label if self.correlation_type == "spearman"
                     else corr_label)

        summary = SanityCheckerSummary()
        summary.names = names
        summary.correlations_with_label = [float(c) for c in corr_label]

        reasons: Dict[int, List[str]] = {i: [] for i in range(d)}
        for i in range(d):
            summary.column_stats.append({
                "name": names[i], "mean": float(mean[i]),
                "variance": float(var[i]), "min": float(zmin[i]),
                "max": float(zmax[i]),
                "corrWithLabel": float(corr_label[i]),
                "spearmanCorrWithLabel": (
                    float(spearman_label[i]) if spearman_label is not None
                    else None)})
            if var[i] < self.min_variance:
                reasons[i].append(
                    f"variance {var[i]:.3g} below min {self.min_variance}")
            c = abs(float(gate_corr[i]))
            if not (self.protect_text_shared_hash and is_hash[i]):
                if np.isnan(gate_corr[i]):
                    pass  # zero-variance already flagged
                elif c > self.max_correlation:
                    reasons[i].append(
                        f"|corr with label| {c:.3f} above max "
                        f"{self.max_correlation}")
                elif c < self.min_correlation:
                    reasons[i].append(
                        f"|corr with label| {c:.3f} below min "
                        f"{self.min_correlation}")

        # categorical stats per indicator group (grouping + indicator cols)
        if meta.size == d:
            if ordered:
                for ((parent, grouping), idxs), cont in zip(ordered, conts):
                    cont = np.asarray(cont)
                    v, support, confidence = _cramers_v(cont)
                    pmi, mi = _pmi_mi(cont)
                    summary.categorical_stats.append({
                        "group": f"{parent}_{grouping}",
                        "cramersV": v,
                        "support": support.tolist(),
                        "maxRuleConfidence": confidence.tolist(),
                        "pointwiseMutualInfo": pmi.tolist(),
                        "mutualInfo": mi})
                    for j, i in enumerate(idxs):
                        if v > self.max_cramers_v:
                            reasons[i].append(
                                f"group Cramér's V {v:.3f} above max "
                                f"{self.max_cramers_v}")
                        if (confidence[j] >= self.max_rule_confidence and
                                support[j] >= self.min_required_rule_support):
                            reasons[i].append(
                                f"association rule confidence "
                                f"{confidence[j]:.3f} with support "
                                f"{support[j]:.3f}")

                # feature-group removal (reasonsToRemove :812-822): if any
                # slot of a parent is label-leaky, drop the parent's group
                if self.remove_feature_group:
                    leaky_parents = {
                        meta.columns[i].parent_feature_name
                        for i in range(d)
                        if any("corr with label" in r and "above" in r
                               for r in reasons[i])
                        or any("association rule" in r for r in reasons[i])}
                    for i, cm in enumerate(meta.columns):
                        if (cm.parent_feature_name in leaky_parents
                                and not reasons[i]
                                and not cm.is_null_indicator()):
                            reasons[i].append(
                                f"feature group {cm.parent_feature_name} "
                                "flagged for label leakage")

        keep = [i for i in range(d) if not reasons[i]]
        if not self.remove_bad_features:
            keep = list(range(d))
        for i in range(d):
            if reasons[i]:
                summary.dropped.append({"name": names[i],
                                        "reasons": reasons[i],
                                        "removed": self.remove_bad_features})

        if not keep:  # never output an empty vector
            keep = list(range(d))

        model = SanityCheckerModel(keep_indices=keep)
        model.summary_ = summary
        return model


def compute_sanity_stats(store: ColumnStore, label_name: str,
                         feat_name: str, *,
                         feature_label_corr_only: bool = False,
                         correlation_type: str = "pearson",
                         check_sample: float = CHECK_SAMPLE,
                         sample_seed: int = 42) -> Dict[str, Any]:
    """The SanityChecker's statistics sweep as a standalone computation:
    bounded row sample, fused moments/correlation gram (device kernel or
    host-BLAS twin behind the bandwidth gate), optional Spearman ranks,
    and per-group contingency tables — everything ``fit_columns``
    consumes in its finalize. Exposed at module level so the layer-wide
    fused fit-statistics engine (``fitstats.py``) computes the identical
    values in its single pass: sequential and fused sanity fits share
    this one code path."""
    ycol = store[label_name]
    xcol = store[feat_name]
    assert isinstance(xcol, VectorColumn)
    import jax as _jax
    _f64 = _jax.config.jax_enable_x64
    X = np.asarray(xcol.values,
                   dtype=np.float64 if _f64 else np.float32)
    y = np.asarray(ycol.values, dtype=np.float64)
    n, d = X.shape
    meta = xcol.metadata or VectorMetadata(feat_name, [])

    # sampling (SanityChecker.scala:552-560): bounded row sample
    if n > SAMPLE_UPPER_LIMIT or check_sample < 1.0:
        rng = np.random.default_rng(sample_seed)
        target = int(min(max(n * check_sample, SAMPLE_LOWER_LIMIT),
                         SAMPLE_UPPER_LIMIT))
        if target < n:
            idx = rng.choice(n, size=target, replace=False)
            X, y = X[idx], y[idx]
            n = target

    # Dispatch EVERY device computation first (moments, optional
    # Spearman over ranks, per-group contingencies) and fetch them in
    # ONE device_get at the end: each separate pull pays the device
    # link's round-trip latency (~200ms on a tunnelled TPU). On a
    # SLOW link (the fusion gate's bandwidth probe) and a big matrix
    # the upload costs more than the gram — the host-BLAS twin runs
    # instead (utils.stats.moments_host).
    from ..utils.stats import moments_host as _moments_host
    from ..workflow import (FUSE_MIN_BANDWIDTH_MBPS,
                            device_roundtrip_mbps)
    # slow link + production (x64-off) dtype → host for ANY size:
    # big matrices because the upload dwarfs the gram, small ones
    # because the moments-kernel COMPILE alone costs seconds over a
    # tunnelled compile service. The x64 test path stays on the
    # device kernel (exact f64).
    use_host = (not _f64
                and device_roundtrip_mbps() < FUSE_MIN_BANDWIDTH_MBPS)
    if use_host:
        moments_dev = _moments_host(X, y, feature_label_corr_only)
    else:
        moments_dev = _moments_kernel(jnp.asarray(X), jnp.asarray(y),
                                      feature_label_corr_only)

    # Spearman = Pearson over average ranks (MLlib Statistics.corr
    # "spearman"); ranks built per column on host, correlations in the
    # same fused gram kernel. Only computed when it drives the gate —
    # the reference computes just the configured CorrelationType
    # (SanityChecker.scala:634-638) and the O(d·n log n) host ranking
    # is real money on wide hashed-text vectors.
    spearman_dev = None
    if correlation_type == "spearman":
        spearman_dev, _full = _spearman_with_label(X, y, host=use_host)

    groups: Dict[Tuple[str, str], List[int]] = {}
    if meta.size == d:
        for i, cm in enumerate(meta.columns):
            if cm.indicator_value is not None and cm.grouping is not None:
                groups.setdefault((cm.parent_feature_name, cm.grouping),
                                  []).append(i)
    ordered = sorted(groups.items())
    conts_dev = []
    if ordered:
        classes = np.unique(y)
        Y1 = (y[:, None] == classes[None, :]).astype(np.float64)
        if use_host:
            # same gate as moments: per-group widths mean one device
            # compile EACH over a slow compile service for a matmul
            # the host does in microseconds
            conts_dev = [Y1.T @ np.asarray(X[:, idxs], np.float64)
                         for _g, idxs in ordered]
        else:
            Y1d = jnp.asarray(Y1)
            conts_dev = [_contingency_kernel(Y1d,
                                             jnp.asarray(X[:, idxs]))
                         for _g, idxs in ordered]

    (mean, var, corr_label, _corr, zmin, zmax), spearman_label, conts = \
        jax.device_get((moments_dev, spearman_dev, conts_dev))

    names = meta.column_names() if meta.size == d else \
        [f"{feat_name}_{i}" for i in range(d)]
    is_hash = [meta.size == d and
               (meta.columns[i].descriptor_value or "").startswith("hash_")
               for i in range(d)]

    return {"d": d, "meta": meta, "names": names, "is_hash": is_hash,
            "mean": mean, "var": var, "corr_label": corr_label,
            "zmin": zmin, "zmax": zmax,
            "spearman_label": spearman_label,
            "ordered": ordered, "conts": conts}
