"""Numeric vectorizers: mean/mode impute + null tracking, bucketizers.

Parity targets: ``RealVectorizer`` (mean impute, ``core/.../impl/feature/
RealVectorizer.scala:121``), ``IntegralVectorizer`` (mode impute),
``BinaryVectorizer``, ``NumericBucketizer``.

Layout per input feature: ``[imputed value, (null indicator)]`` — one slot
plus an optional tracked-null slot, concatenated over the N inputs.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from ..columns import ColumnStore, NumericColumn
from ..stages.base import register_stage
from ..types.feature_types import (Binary, FeatureType, Integral, OPNumeric,
                                   Real)
from ..vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizer_base import (TransmogrifierDefaults, VEC_DTYPE,
                              VectorizerEstimator, VectorizerModel,
                              null_indicator_meta, vec_dtype_round)

__all__ = ["RealVectorizer", "IntegralVectorizer", "BinaryVectorizer",
           "NumericBucketizer", "NumericVectorizerModel"]


@register_stage
class NumericVectorizerModel(VectorizerModel):
    """Shared fitted model: per-feature fill value + null tracking."""

    operation_name = "vecNumeric"
    seq_type = OPNumeric

    def __init__(self, fill_values: Sequence[float] = (),
                 track_nulls: bool = True,
                 input_names: Sequence[str] = (),
                 ftype_name: str = "Real",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.fill_values = list(fill_values)
        self.track_nulls = track_nulls
        self.input_names_saved = list(input_names)
        self.ftype_name = ftype_name

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        vals, masks = [], []
        for name in self._names():
            col = store[name]
            vals.append(col.values.astype(np.float64))
            masks.append(col.mask)
        return {"values": np.stack(vals, axis=1),
                "mask": np.stack(masks, axis=1)}

    def device_compute(self, xp, prepared):
        values, mask = prepared["values"], prepared["mask"]
        # VEC_DTYPE to match the canonicalized values on both paths (a f64
        # constant would make numpy promote where jit canonicalizes, and
        # the two paths would drift)
        fill = xp.asarray(np.asarray(self.fill_values, dtype=VEC_DTYPE))
        imputed = xp.where(mask, values, fill[None, :])
        if not self.track_nulls:
            return imputed
        nulls = (~mask).astype(imputed.dtype)
        # interleave [value_i, null_i] to match reference column order
        n, k = imputed.shape
        out = xp.stack([imputed, nulls], axis=2).reshape(n, 2 * k)
        return out

    def vector_metadata(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for name in self._names():
            cols.append(VectorColumnMetadata(
                parent_feature_name=name, parent_feature_type=self.ftype_name))
            if self.track_nulls:
                cols.append(null_indicator_meta(name, self.ftype_name))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        return {"fill_values": self.fill_values,
                "input_names_saved": self._names()}


class _NumericVectorizerBase(VectorizerEstimator):
    def __init__(self, track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 fill_value: float = TransmogrifierDefaults.FILL_VALUE,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.track_nulls = track_nulls
        self.fill_value = fill_value

    def _fill_for(self, col) -> float:
        raise NotImplementedError

    def fit_columns(self, store: ColumnStore) -> NumericVectorizerModel:
        fills = [self._fill_for(store[n]) for n in self.input_names]
        return NumericVectorizerModel(
            fill_values=fills, track_nulls=self.track_nulls,
            input_names=self.input_names,
            ftype_name=self.seq_type.__name__)

    # -- fused fit-statistics opt-in (fitstats.py) -------------------------
    def _stat_request_for(self, name: str):
        """Per-column StatRequest, or None when the fill is a constant
        (no data needed). Subclasses override."""
        return None

    def stat_requests(self, store):
        return [r for r in (self._stat_request_for(n)
                            for n in self.input_names) if r is not None]

    def _fill_from_stats(self, name: str, stats) -> float:
        return float(self.fill_value)

    def fit_columns_from_stats(self, store, stats):
        fills = [self._fill_from_stats(n, stats) for n in self.input_names]
        return NumericVectorizerModel(
            fill_values=fills, track_nulls=self.track_nulls,
            input_names=self.input_names,
            ftype_name=self.seq_type.__name__)


@register_stage
class RealVectorizer(_NumericVectorizerBase):
    """Real → [mean-imputed value, null indicator] (RealVectorizer.scala:121)."""

    operation_name = "vecReal"
    seq_type = Real

    def __init__(self, fill_with_mean: bool = TransmogrifierDefaults.FILL_WITH_MEAN,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 fill_value: float = TransmogrifierDefaults.FILL_VALUE,
                 uid: Optional[str] = None):
        super().__init__(track_nulls=track_nulls, fill_value=fill_value, uid=uid)
        self.fill_with_mean = fill_with_mean

    def _fill_for(self, col) -> float:
        if self.fill_with_mean and col.mask.any():
            return float(col.values[col.mask].astype(np.float64).mean())
        return float(self.fill_value)

    def _stat_request_for(self, name: str):
        if not self.fill_with_mean:
            return None
        from ..fitstats import StatRequest
        return StatRequest("mean", name)

    def _fill_from_stats(self, name: str, stats) -> float:
        if self.fill_with_mean:
            mean = stats.value("mean", name)
            if mean is not None:
                return mean
        return float(self.fill_value)


@register_stage
class IntegralVectorizer(_NumericVectorizerBase):
    """Integral → [mode-imputed value, null indicator]. Mode = most frequent
    value, ties → smallest (SequenceAggregators.ModeSeqNullInt semantics)."""

    operation_name = "vecIntegral"
    seq_type = Integral

    def __init__(self, fill_with_mode: bool = TransmogrifierDefaults.FILL_WITH_MODE,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 fill_value: float = TransmogrifierDefaults.FILL_VALUE,
                 uid: Optional[str] = None):
        super().__init__(track_nulls=track_nulls, fill_value=fill_value, uid=uid)
        self.fill_with_mode = fill_with_mode

    def _fill_for(self, col) -> float:
        if self.fill_with_mode and col.mask.any():
            vals, counts = np.unique(col.values[col.mask], return_counts=True)
            return float(vals[np.argmax(counts)])  # unique is sorted → ties to min
        return float(self.fill_value)

    def _stat_request_for(self, name: str):
        if not self.fill_with_mode:
            return None
        from ..fitstats import StatRequest
        return StatRequest("mode", name)

    def _fill_from_stats(self, name: str, stats) -> float:
        if self.fill_with_mode:
            mode = stats.value("mode", name)
            if mode is not None:
                return mode
        return float(self.fill_value)


@register_stage
class BinaryVectorizer(_NumericVectorizerBase):
    """Binary → [0/1 with fill, null indicator]."""

    operation_name = "vecBinary"
    seq_type = Binary

    def __init__(self, track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 fill_value: float = TransmogrifierDefaults.BINARY_FILL_VALUE,
                 uid: Optional[str] = None):
        super().__init__(track_nulls=track_nulls, fill_value=fill_value, uid=uid)

    def _fill_for(self, col) -> float:
        return float(self.fill_value)


@register_stage
class NumericBucketizerModel(VectorizerModel):
    """One-hot of value buckets + optional null slot per feature."""

    operation_name = "bucketize"
    seq_type = OPNumeric

    def __init__(self, splits: Sequence[Sequence[float]] = (),
                 track_nulls: bool = True,
                 track_invalid: bool = False,
                 input_names: Sequence[str] = (),
                 ftype_name: str = "Real",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        # round fitted edges through the pipeline dtype at CONSTRUCTION so
        # the stored edges ARE the values transform compares against (no
        # second rounding at transform time). Deliberately NO dedup: two f64
        # edges within one f32 ULP collapse to an identical pair, whose
        # bucket simply never fires — keeping the vector width stable is
        # what matters (checkpointed downstream stages are fitted against
        # this width; shrinking it on reload would misalign them all).
        self.splits = [vec_dtype_round(list(s)).tolist() for s in splits]
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid
        self.input_names_saved = list(input_names)
        self.ftype_name = ftype_name

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        vals, masks = [], []
        for name in self._names():
            col = store[name]
            vals.append(col.values.astype(np.float64))
            masks.append(col.mask)
        return {"values": np.stack(vals, axis=1),
                "mask": np.stack(masks, axis=1)}

    def device_compute(self, xp, prepared):
        values, mask = prepared["values"], prepared["mask"]
        outs = []
        for j, splits in enumerate(self.splits):
            # VEC_DTYPE edges: values are canonicalized the same way, so
            # both paths bucket identically (comparisons agree bit-for-bit)
            edges = xp.asarray(np.asarray(splits, dtype=VEC_DTYPE))
            v = values[:, j]
            m = mask[:, j]
            # bucket b: edges[b] <= v < edges[b+1]; last bucket right-closed
            idx = xp.clip(xp.searchsorted(edges, v, side="right") - 1,
                          0, len(splits) - 2)
            in_range = (v >= edges[0]) & (v <= edges[-1])
            valid = m & in_range
            onehot = (idx[:, None] == xp.arange(len(splits) - 1)[None, :])
            onehot = onehot & valid[:, None]
            outs.append(onehot.astype(values.dtype))
            if self.track_invalid:
                outs.append((m & ~in_range).astype(values.dtype)[:, None])
            if self.track_nulls:
                outs.append((~m).astype(values.dtype)[:, None])
        return xp.concatenate(outs, axis=1)

    def vector_metadata(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for name, splits in zip(self._names(), self.splits):
            for b in range(len(splits) - 1):
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name,
                    parent_feature_type=self.ftype_name,
                    indicator_value=f"{splits[b]}-{splits[b + 1]}"))
            if self.track_invalid:
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name,
                    parent_feature_type=self.ftype_name,
                    indicator_value="OutOfBounds"))
            if self.track_nulls:
                cols.append(null_indicator_meta(name, self.ftype_name))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        return {"splits": self.splits, "input_names_saved": self._names()}


@register_stage
class NumericBucketizer(VectorizerEstimator):
    """Fixed or quantile splits → one-hot buckets (NumericBucketizer)."""

    operation_name = "bucketize"
    seq_type = OPNumeric

    def __init__(self, splits: Optional[Sequence[float]] = None,
                 num_buckets: int = 4,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 track_invalid: bool = TransmogrifierDefaults.TRACK_INVALID,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.splits = list(splits) if splits is not None else None
        self.num_buckets = num_buckets
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid

    @staticmethod
    def _splits_of(qs) -> List[float]:
        """Quantile sketch → final split edges (dedup'd, degenerate
        columns padded) — shared by the sequential and fused paths."""
        if qs is None:
            return [0.0, 1.0]
        qs = np.unique(qs)
        if qs.size < 2:
            qs = np.array([qs[0], qs[0] + 1.0])
        return qs.tolist()

    def fit_columns(self, store: ColumnStore) -> NumericBucketizerModel:
        per_feature = []
        for name in self.input_names:
            if self.splits is not None:
                per_feature.append(self.splits)
                continue
            col = store[name]
            present = col.values[col.mask].astype(np.float64)
            qs = (np.quantile(present,
                              np.linspace(0, 1, self.num_buckets + 1))
                  if present.size else None)
            per_feature.append(self._splits_of(qs))
        return NumericBucketizerModel(
            splits=per_feature, track_nulls=self.track_nulls,
            track_invalid=self.track_invalid, input_names=self.input_names,
            ftype_name=self.seq_type.__name__)

    # -- fused fit-statistics opt-in (fitstats.py) -------------------------
    def stat_requests(self, store):
        if self.splits is not None:
            return []           # fixed splits: nothing to scan
        from ..fitstats import StatRequest
        return [StatRequest("quantile", n, params=(self.num_buckets,))
                for n in self.input_names]

    def fit_columns_from_stats(self, store, stats):
        per_feature = []
        for name in self.input_names:
            if self.splits is not None:
                per_feature.append(self.splits)
                continue
            qs = stats.value("quantile", name,
                             params=(self.num_buckets,))
            per_feature.append(self._splits_of(qs))
        return NumericBucketizerModel(
            splits=per_feature, track_nulls=self.track_nulls,
            track_invalid=self.track_invalid, input_names=self.input_names,
            ftype_name=self.seq_type.__name__)
