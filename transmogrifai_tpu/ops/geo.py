"""Geolocation vectorization.

Parity: ``GeolocationVectorizer`` (``core/.../impl/feature/
GeolocationVectorizer.scala:156``): missing coordinates fill with the
geographic mean (computed on the unit sphere, replacing lucene-spatial3d);
output per feature is [lat, lon, accuracy, (null)].
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columns import ColumnStore, GeoColumn
from ..stages.base import register_stage
from ..types.feature_types import Geolocation
from ..vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizer_base import (TransmogrifierDefaults, VEC_DTYPE,
                              VectorizerEstimator, VectorizerModel,
                              null_indicator_meta)

__all__ = ["GeolocationVectorizer", "GeolocationVectorizerModel"]


def geo_mean(values: np.ndarray, mask: np.ndarray) -> List[float]:
    """Geographic midpoint via unit-sphere averaging."""
    if not mask.any():
        return [0.0, 0.0, 0.0]
    lat = np.radians(values[mask, 0])
    lon = np.radians(values[mask, 1])
    x = np.cos(lat) * np.cos(lon)
    y = np.cos(lat) * np.sin(lon)
    z = np.sin(lat)
    mx, my, mz = x.mean(), y.mean(), z.mean()
    hyp = np.hypot(mx, my)
    mean_lat = np.degrees(np.arctan2(mz, hyp))
    mean_lon = np.degrees(np.arctan2(my, mx))
    mean_acc = float(values[mask, 2].mean())
    return [float(mean_lat), float(mean_lon), mean_acc]


@register_stage
class GeolocationVectorizerModel(VectorizerModel):
    operation_name = "vecGeo"
    seq_type = Geolocation

    def __init__(self, fill_values: Sequence[Sequence[float]] = (),
                 track_nulls: bool = True,
                 input_names: Sequence[str] = (),
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.fill_values = [list(map(float, f)) for f in fill_values]
        self.track_nulls = track_nulls
        self.input_names_saved = list(input_names)

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        vals, masks = [], []
        for name in self._names():
            col = store[name]
            assert isinstance(col, GeoColumn)
            vals.append(col.values)
            masks.append(col.mask)
        return {"values": np.stack(vals, axis=1),  # [n, k, 3]
                "mask": np.stack(masks, axis=1)}   # [n, k]

    def device_compute(self, xp, prepared):
        values, mask = prepared["values"], prepared["mask"]
        n, k, _ = values.shape
        fills = xp.asarray(np.asarray(self.fill_values, dtype=VEC_DTYPE))  # [k,3]
        filled = xp.where(mask[:, :, None], values, fills[None, :, :])
        if self.track_nulls:
            nulls = (~mask).astype(values.dtype)[:, :, None]
            out = xp.concatenate([filled, nulls], axis=2)  # [n, k, 4]
            return out.reshape(n, k * 4)
        return filled.reshape(n, k * 3)

    def vector_metadata(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for name in self._names():
            for d in ("lat", "lon", "accuracy"):
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name, parent_feature_type="Geolocation",
                    descriptor_value=d))
            if self.track_nulls:
                cols.append(null_indicator_meta(name, "Geolocation"))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        return {"fill_values": self.fill_values,
                "input_names_saved": self._names()}


@register_stage
class GeolocationVectorizer(VectorizerEstimator):
    operation_name = "vecGeo"
    seq_type = Geolocation

    def __init__(self, fill_with_geo_mean: bool = True,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.fill_with_geo_mean = fill_with_geo_mean
        self.track_nulls = track_nulls

    def fit_columns(self, store: ColumnStore) -> GeolocationVectorizerModel:
        fills = []
        for name in self.input_names:
            col = store[name]
            if self.fill_with_geo_mean:
                fills.append(geo_mean(col.values, col.mask))
            else:
                fills.append([0.0, 0.0, 0.0])
        return GeolocationVectorizerModel(
            fill_values=fills, track_nulls=self.track_nulls,
            input_names=self.input_names)
