"""TextList / MultiPickList transformers behind the Rich* DSL long tail.

Parity targets: ``RichListFeature.scala:59-312`` (tf / tfidf / ngram /
removeStopWords / countVec / vectorize) and ``RichSetFeature.scala:65-142``
(pivot / vectorize / jaccardSimilarity / toNGramSimilarity). The reference
wraps Spark ML's HashingTF / IDF / NGram / StopWordsRemover; these are
native columnar implementations with the same semantics: hashing term
frequencies (murmur3 bucket per token), Spark's IDF formula
``log((m + 1) / (df + 1))``, space-joined n-grams, and an English
stop-word table.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..columns import Column, ColumnStore, NumericColumn, VectorColumn
from ..stages.base import (Estimator, FittedModel, FixedArity, InputSpec,
                           Transformer, register_stage)
from ..types.feature_types import (MultiPickList, OPVector, RealNN, TextList)
from ..vector_metadata import VectorColumnMetadata, VectorMetadata

__all__ = ["OpHashingTF", "OpIDF", "OpIDFModel", "OpNGram",
           "OpStopWordsRemover", "JaccardSimilarity", "ENGLISH_STOP_WORDS"]

#: Spark ML's English stop-word list is Lucene's; this is the standard
#: English table (same spirit, vendored inline — no Lucene dependency)
ENGLISH_STOP_WORDS = frozenset("""a about above after again against all am
an and any are aren't as at be because been before being below between
both but by can't cannot could couldn't did didn't do does doesn't doing
don't down during each few for from further had hadn't has hasn't have
haven't having he he'd he'll he's her here here's hers herself him himself
his how how's i i'd i'll i'm i've if in into is isn't it it's its itself
let's me more most mustn't my myself no nor not of off on once only or
other ought our ours ourselves out over own same shan't she she'd she'll
she's should shouldn't so some such than that that's the their theirs them
themselves then there there's these they they'd they'll they're they've
this those through to too under until up very was wasn't we we'd we'll
we're we've were weren't what what's when when's where where's which while
who who's whom why why's with won't would wouldn't you you'd you'll you're
you've your yours yourself yourselves""".split())


def _rows_of(col, n_rows: int) -> List[List[str]]:
    return [[str(t) for t in (col.get_raw(i) or [])] for i in range(n_rows)]


@register_stage
class OpHashingTF(Transformer):
    """TextList → OPVector of hashed term frequencies (HashingTF wrap in
    ``RichListFeature.tf`` :59; murmur3 bucket per token, optional binary
    counts)."""

    operation_name = "hashingTF"
    output_type = OPVector

    def __init__(self, num_terms: int = 512, binary: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_terms = int(num_terms)
        self.binary = bool(binary)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(TextList)

    def transform_columns(self, store: ColumnStore) -> Column:
        from .hashing import hash_tokens
        col = store[self.input_features[0].name]
        out = np.zeros((store.n_rows, self.num_terms), np.float64)
        rows = _rows_of(col, store.n_rows)
        flat = [t for r in rows for t in r]
        if flat:
            buckets = hash_tokens(flat) % np.uint32(self.num_terms)
            pos = 0
            for i, r in enumerate(rows):
                for _ in r:
                    out[i, buckets[pos]] += 1.0
                    pos += 1
        if self.binary:
            out = (out > 0).astype(np.float64)
        name = self.input_features[0].name
        meta = VectorMetadata(self.output_name, [
            VectorColumnMetadata(parent_feature_name=name,
                                 parent_feature_type="TextList",
                                 grouping=name, indicator_value=None,
                                 descriptor_value=f"tf_{j}", index=j)
            for j in range(self.num_terms)])
        return VectorColumn(OPVector, out, metadata=meta)


@register_stage
class OpIDFModel(FittedModel):
    """Fitted IDF scaling: v → v · log((m + 1) / (df + 1))."""

    operation_name = "idf"
    output_type = OPVector

    def __init__(self, idf: Optional[np.ndarray] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.idf = np.asarray(idf, np.float64) if idf is not None else None

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(OPVector)

    def get_model_state(self):
        return {"idf": self.idf}

    def apply_model_state(self, state) -> None:
        self.idf = np.asarray(state["idf"], np.float64)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        vals = np.asarray(col.values, np.float64) * self.idf[None, :]
        return VectorColumn(OPVector, vals, metadata=col.metadata)


@register_stage
class OpIDF(Estimator):
    """Inverse document frequency estimator (Spark ``IDF`` wrap in
    ``RichListFeature.tfidf`` :76): fit collects per-column document
    frequencies; ``min_doc_freq`` zeroes terms below the floor."""

    operation_name = "idfFit"
    output_type = OPVector

    def __init__(self, min_doc_freq: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.min_doc_freq = int(min_doc_freq)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(OPVector)

    def fit_columns(self, store: ColumnStore) -> OpIDFModel:
        col = store[self.input_features[0].name]
        vals = np.asarray(col.values, np.float64)
        m = vals.shape[0]
        df = (vals > 0).sum(axis=0).astype(np.float64)
        idf = np.log((m + 1.0) / (df + 1.0))
        if self.min_doc_freq > 0:
            idf = np.where(df >= self.min_doc_freq, idf, 0.0)
        return OpIDFModel(idf=idf)


@register_stage
class OpNGram(Transformer):
    """TextList → TextList of space-joined n-grams (Spark ``NGram`` wrap
    in ``RichListFeature.ngram`` :153; fewer than n tokens → empty)."""

    operation_name = "ngramList"
    output_type = TextList

    def __init__(self, n: int = 2, uid: Optional[str] = None):
        super().__init__(uid=uid)
        if n < 1:
            raise ValueError("ngram size must be >= 1")
        self.n = int(n)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(TextList)

    def transform_columns(self, store: ColumnStore) -> Column:
        from ..columns import TextListColumn
        col = store[self.input_features[0].name]
        out = []
        for r in _rows_of(col, store.n_rows):
            toks = [t for t in r if t is not None]
            out.append([" ".join(toks[j:j + self.n])
                        for j in range(len(toks) - self.n + 1)])
        return TextListColumn(TextList, out)


@register_stage
class OpStopWordsRemover(Transformer):
    """TextList → TextList without stop words (Spark ``StopWordsRemover``
    wrap in ``RichListFeature.removeStopWords`` :168)."""

    operation_name = "stopWords"
    output_type = TextList

    def __init__(self, stop_words: Optional[List[str]] = None,
                 case_sensitive: bool = False, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.stop_words = (list(stop_words) if stop_words is not None
                           else sorted(ENGLISH_STOP_WORDS))
        self.case_sensitive = bool(case_sensitive)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(TextList)

    def transform_columns(self, store: ColumnStore) -> Column:
        from ..columns import TextListColumn
        col = store[self.input_features[0].name]
        table = (set(self.stop_words) if self.case_sensitive
                 else {w.lower() for w in self.stop_words})
        out = []
        for r in _rows_of(col, store.n_rows):
            out.append([t for t in r
                        if (t if self.case_sensitive else t.lower())
                        not in table])
        return TextListColumn(TextList, out)


@register_stage
class JaccardSimilarity(Transformer):
    """(MultiPickList, MultiPickList) → RealNN Jaccard overlap
    (``JaccardSimilarity`` via ``RichSetFeature.jaccardSimilarity`` :124;
    two empty sets score 1.0 like the reference)."""

    operation_name = "jaccardSim"
    output_type = RealNN

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(MultiPickList, MultiPickList)

    def transform_columns(self, store: ColumnStore) -> Column:
        a = store[self.input_features[0].name]
        b = store[self.input_features[1].name]
        out = np.empty(store.n_rows, np.float64)
        for i in range(store.n_rows):
            sa = set(a.get_raw(i) or ())
            sb = set(b.get_raw(i) or ())
            union = sa | sb
            out[i] = (len(sa & sb) / len(union)) if union else 1.0
        return NumericColumn(RealNN, out, np.ones(store.n_rows, bool))
