"""Text processing — tokenization and text transformers (host-side).

The reference uses Lucene analyzers + Optimaize language detection
(``core/.../impl/feature/TextTokenizer.scala``); on TPU all tokenization is
host work feeding hashed/indexed device arrays, so the implementation is a
fast table-driven tokenizer with the same interface.
"""
from __future__ import annotations

import re
from typing import List, Optional, Sequence

import numpy as np

from ..columns import Column, ColumnStore, TextColumn, TextListColumn
from ..stages.base import FixedArity, InputSpec, Transformer, register_stage
from ..types.feature_types import Text, TextList

__all__ = ["tokenize_simple", "TextTokenizer"]

_TOKEN_RE = re.compile(r"[\w']+", re.UNICODE)
_MIN_TOKEN_LENGTH = 1


def tokenize_simple(text: str, to_lowercase: bool = True,
                    min_token_length: int = _MIN_TOKEN_LENGTH) -> List[str]:
    """Unicode word tokenizer (Lucene SimpleAnalyzer analog)."""
    if to_lowercase:
        text = text.lower()
    return [t for t in _TOKEN_RE.findall(text) if len(t) >= min_token_length]


@register_stage
class TextTokenizer(Transformer):
    """Text → TextList of tokens (TextTokenizer.scala)."""

    operation_name = "tokenize"
    output_type = TextList

    def __init__(self, to_lowercase: bool = True, min_token_length: int = 1,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.to_lowercase = to_lowercase
        self.min_token_length = min_token_length

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        assert isinstance(col, TextColumn)
        out = [tokenize_simple(v, self.to_lowercase, self.min_token_length)
               if v is not None else []
               for v in col.values]
        return TextListColumn(TextList, out)
