"""Text processing — tokenization, analyzers, language detection (host-side).

The reference uses Lucene analyzers + Optimaize language detection
(``core/.../impl/feature/TextTokenizer.scala:1``, ``utils/.../text``
interfaces ``TextAnalyzer``/``LanguageDetector``). On TPU all tokenization
is host work feeding hashed/indexed device arrays, so the implementation is
a fast table-driven analyzer pipeline with the same interface:

    lowercase → unicode word split → min-length filter → stopword removal
    (per detected/declared language) → optional light stemming

Stemming is a compact Porter-style suffix stripper (plural/participle
steps), enough for bag-of-words feature parity without a linguistics
dependency.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columns import Column, ColumnStore, TextColumn, TextListColumn
from ..stages.base import FixedArity, InputSpec, Transformer, register_stage
from ..types.feature_types import Text, TextList

__all__ = ["tokenize_simple", "tokenize", "TextTokenizer",
           "detect_language", "STOPWORDS"]

_TOKEN_RE = re.compile(r"[\w']+", re.UNICODE)
_MIN_TOKEN_LENGTH = 1

#: small per-language stopword tables (Lucene analyzer stopword analog);
#: also drive the stopword-overlap language detector below
STOPWORDS: Dict[str, frozenset] = {
    "en": frozenset("""a an and are as at be but by for from has have he her
        his i in is it its my not of on or she that the their there they this
        to was we were will with you your""".split()),
    "es": frozenset("""de la que el en y a los del se las por un para con no
        una su al lo como mas pero sus le ya o este si porque esta entre
        cuando muy sin sobre tambien me hasta hay donde quien desde todo nos
        durante todos uno les ni contra otros ese eso ante ellos e esto mi
        antes algunos que unos yo otro otras otra el tanto esa estos mucho
        quienes nada muchos cual poco ella estar estas algunas algo
        nosotros""".split()),
    "fr": frozenset("""de la le et les des en un du une que est pour qui dans
        a par plus pas au sur ne se ce il sont la son avec ils mais comme ou
        si leur y dont elle deux ont ete cette aux tout nous sa meme ces
        son bien ou""".split()),
    "de": frozenset("""der die und in den von zu das mit sich des auf fur ist
        im dem nicht ein eine als auch es an werden aus er hat dass sie nach
        wird bei einer um am sind noch wie einem uber einen so zum war haben
        nur oder aber vor zur bis mehr durch man sein wurde sei""".split()),
    "it": frozenset("""di e il la che in a per un e del con non sono da una
        le si dei nel alla lo piu gli delle questo i ma ha anche al suo o
        come se della questa sulla loro tutti hanno essere fra cui tra""".split()),
    "pt": frozenset("""de a o que e do da em um para com nao uma os no se na
        por mais as dos como mas ao ele das seu sua ou quando muito nos ja
        eu tambem so pelo pela ate isso ela entre depois sem mesmo aos seus
        quem nas me esse eles voce essa num nem suas meu as minha numa pelos
        elas qual nos lhe deles essas esses pelas este dele""".split()),
}


def score_languages(text: str) -> dict:
    """Per-language stopword-overlap fractions (the one scoring formula
    shared by :func:`detect_language` and the ``LanguageDetector`` stage —
    the Optimaize n-gram profile replacement)."""
    toks = _TOKEN_RE.findall(text.lower())
    if not toks:
        return {}
    out = {}
    for lang, words in STOPWORDS.items():
        score = sum(1 for t in toks if t in words) / len(toks)
        if score > 0.0:
            out[lang] = score
    return out


def detect_language(text: str, default: str = "en") -> str:
    """Best language by stopword overlap; ties/no-signal fall back to
    ``default`` (scores below the 0.05 noise floor are ignored)."""
    scores = score_languages(text)
    if not scores:
        return default
    best = max(scores, key=scores.get)
    return best if scores[best] > 0.05 else default


_STEM_SUFFIXES = [
    ("ational", "ate"), ("iveness", "ive"), ("fulness", "ful"),
    ("ousness", "ous"), ("ization", "ize"), ("tional", "tion"),
    ("biliti", "ble"), ("entli", "ent"), ("ation", "ate"), ("alism", "al"),
    ("aliti", "al"), ("ement", ""), ("ness", ""), ("ing", ""), ("edly", ""),
    ("eed", "ee"), ("ies", "y"), ("ied", "y"), ("es", ""), ("ed", ""),
    ("ly", ""), ("s", ""),
]


def stem(token: str) -> str:
    """Compact Porter-style suffix stripping (plurals + participles)."""
    if len(token) <= 3:
        return token
    for suf, repl in _STEM_SUFFIXES:
        if token.endswith(suf) and len(token) - len(suf) + len(repl) >= 3:
            return token[:len(token) - len(suf)] + repl
    return token


def tokenize_simple(text: str, to_lowercase: bool = True,
                    min_token_length: int = _MIN_TOKEN_LENGTH) -> List[str]:
    """Unicode word tokenizer (Lucene SimpleAnalyzer analog)."""
    if to_lowercase:
        text = text.lower()
    return [t for t in _TOKEN_RE.findall(text) if len(t) >= min_token_length]


def tokenize(text: str, to_lowercase: bool = True, min_token_length: int = 1,
             remove_stopwords: bool = False, language: Optional[str] = None,
             auto_detect_language: bool = False,
             stemming: bool = False) -> List[str]:
    """Full analyzer pipeline (TextTokenizer.tokenize analog)."""
    toks = tokenize_simple(text, to_lowercase, min_token_length)
    if remove_stopwords:
        lang = (detect_language(text) if auto_detect_language
                else (language or "en"))
        stop = STOPWORDS.get(lang, STOPWORDS["en"])
        toks = [t for t in toks if t not in stop]
    if stemming:
        toks = [stem(t) for t in toks]
    return toks


@register_stage
class TextTokenizer(Transformer):
    """Text → TextList of tokens (TextTokenizer.scala analyzer pipeline)."""

    operation_name = "tokenize"
    output_type = TextList

    def __init__(self, to_lowercase: bool = True, min_token_length: int = 1,
                 remove_stopwords: bool = False,
                 language: Optional[str] = None,
                 auto_detect_language: bool = False,
                 stemming: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.to_lowercase = to_lowercase
        self.min_token_length = min_token_length
        self.remove_stopwords = remove_stopwords
        self.language = language
        self.auto_detect_language = auto_detect_language
        self.stemming = stemming

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        assert isinstance(col, TextColumn)
        out = [tokenize(v, self.to_lowercase, self.min_token_length,
                        self.remove_stopwords, self.language,
                        self.auto_detect_language, self.stemming)
               if v is not None else []
               for v in col.values]
        return TextListColumn(TextList, out)
