"""Scalar scaling stages.

Parity: ``core/.../impl/feature/OpScalarStandardScaler.scala`` (z-normalize
one scalar with fitted mean/std), ``ScalerTransformer.scala`` /
``DescalerTransformer.scala`` (apply an invertible scaling and later undo it
by reading the scaling metadata off the scaled feature's origin stage —
used to train on a scaled label and descale predictions).
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

from ..columns import Column, ColumnStore, NumericColumn
from ..stages.base import (Estimator, FittedModel, FixedArity, InputSpec,
                           Transformer, register_stage)
from ..types.feature_types import Real, RealNN

__all__ = ["OpScalarStandardScaler", "ScalarStandardScalerModel",
           "ScalerTransformer", "DescalerTransformer", "ScalingType"]


class ScalingType:
    LINEAR = "linear"
    LOGARITHMIC = "logarithmic"


@register_stage
class ScalarStandardScalerModel(FittedModel):
    operation_name = "stdScaled"
    output_type = RealNN

    def __init__(self, mean: float = 0.0, std: float = 1.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.mean = float(mean)
        self.std = float(std)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Real)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        v = col.values.astype(np.float64)
        out = (v - self.mean) / (self.std if self.std > 0 else 1.0)
        out = np.where(col.mask, out, 0.0)
        return NumericColumn(RealNN, out, np.ones_like(out, dtype=bool))

    def get_model_state(self):
        return {"mean": self.mean, "std": self.std}


@register_stage
class OpScalarStandardScaler(Estimator):
    """Estimator(Real) → z-normalized RealNN (OpScalarStandardScaler)."""

    operation_name = "stdScaled"
    output_type = RealNN

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Real)

    def fit_columns(self, store: ColumnStore) -> ScalarStandardScalerModel:
        col = store[self.input_features[0].name]
        present = col.values[col.mask].astype(np.float64)
        mean = float(present.mean()) if present.size else 0.0
        std = float(present.std(ddof=1)) if present.size > 1 else 1.0
        return ScalarStandardScalerModel(mean=mean, std=std or 1.0)

    # -- fused fit-statistics opt-in (fitstats.py) -------------------------
    def stat_requests(self, store):
        from ..fitstats import StatRequest
        name = self.input_features[0].name
        return [StatRequest("mean", name),
                StatRequest("std", name, params=(1,))]

    def fit_columns_from_stats(self, store, stats):
        name = self.input_features[0].name
        mean = stats.value("mean", name)
        std = stats.value("std", name, params=(1,))
        mean = 0.0 if mean is None else mean
        std = 1.0 if std is None else std
        return ScalarStandardScalerModel(mean=mean, std=std or 1.0)


@register_stage
class ScalerTransformer(Transformer):
    """Invertible scaling of one scalar feature (ScalerTransformer.scala).

    ``scaling_type``: 'linear' (slope·x + intercept) or 'logarithmic'
    (ln x). The scaling args live on the stage so DescalerTransformer can
    find and invert them through the feature graph.
    """

    operation_name = "scaled"
    output_type = Real

    def __init__(self, scaling_type: str = ScalingType.LINEAR,
                 slope: float = 1.0, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        if scaling_type not in (ScalingType.LINEAR, ScalingType.LOGARITHMIC):
            raise ValueError(f"Unknown scaling type {scaling_type!r}")
        self.scaling_type = scaling_type
        self.slope = float(slope)
        self.intercept = float(intercept)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Real)

    def scale(self, v: np.ndarray) -> np.ndarray:
        if self.scaling_type == ScalingType.LINEAR:
            return self.slope * v + self.intercept
        return np.log(np.maximum(v, 1e-300))

    def descale(self, v: np.ndarray) -> np.ndarray:
        if self.scaling_type == ScalingType.LINEAR:
            if self.slope == 0:
                raise ValueError("Cannot descale a slope-0 linear scaling")
            return (v - self.intercept) / self.slope
        return np.exp(v)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        v = col.values.astype(np.float64)
        out = np.where(col.mask, self.scale(v), 0.0)
        return NumericColumn(Real, out, col.mask.copy())


@register_stage
class DescalerTransformer(Transformer):
    """Binary(value: Real, scaled source: Real) → Real with the source's
    scaling inverted (DescalerTransformer.scala).

    The second input must descend from a :class:`ScalerTransformer`; its
    scaling metadata is read off the feature graph and inverted on the
    first input (e.g. descale predictions trained on a scaled label).
    """

    operation_name = "descaled"
    output_type = Real

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Real, Real)

    def _find_scaler(self) -> ScalerTransformer:
        f = self.input_features[1]
        while f is not None:
            st = f.origin_stage
            if isinstance(st, ScalerTransformer):
                return st
            f = st.input_features[0] if st is not None and \
                st.input_features else None
        raise ValueError(
            f"Feature {self.input_features[1].name!r} has no "
            "ScalerTransformer ancestor to invert")

    def transform_columns(self, store: ColumnStore) -> Column:
        scaler = self._find_scaler()
        col = store[self.input_features[0].name]
        v = col.values.astype(np.float64)
        out = np.where(col.mask, scaler.descale(v), 0.0)
        return NumericColumn(Real, out, col.mask.copy())
