"""One-hot pivot vectorizers for categorical text and sets.

Parity: ``OpOneHotVectorizer``/``OpSetVectorizer``/``OpTextPivotVectorizer``
(``core/.../impl/feature/OpOneHotVectorizer.scala``): per feature, count
values, keep top-K with count >= min_support, emit
``[cat_1 .. cat_K, OTHER, NullIndicator]``.

Fit is a host-side value count (strings never reach the device); transform
is host vocab lookup → device one-hot scatter.
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from ..columns import ColumnStore, TextColumn, TextSetColumn
from ..stages.base import register_stage
from ..types.feature_types import MultiPickList, OPSet, Text
from ..vector_metadata import (NULL_INDICATOR, OTHER_INDICATOR,
                               VectorColumnMetadata, VectorMetadata)
from .vectorizer_base import (TransmogrifierDefaults, VEC_DTYPE,
                              VectorizerEstimator,
                              VectorizerModel)

__all__ = ["OneHotVectorizer", "SetVectorizer", "OneHotModel"]


def _sorted_topk(counts: Counter, top_k: int, min_support: int) -> List[str]:
    """Top-K by count desc, ties by value asc (deterministic)."""
    items = [(v, c) for v, c in counts.items() if c >= min_support]
    items.sort(key=lambda vc: (-vc[1], vc[0]))
    return [v for v, _ in items[:top_k]]


@register_stage
class OneHotModel(VectorizerModel):
    """Fitted pivot: per-feature vocab → [cats..., OTHER, null]."""

    operation_name = "pivot"
    seq_type = Text

    def __init__(self, vocabs: Sequence[Sequence[str]] = (),
                 track_nulls: bool = True,
                 input_names: Sequence[str] = (),
                 ftype_name: str = "Text",
                 is_set: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vocabs = [list(v) for v in vocabs]
        self.track_nulls = track_nulls
        self.input_names_saved = list(input_names)
        self.ftype_name = ftype_name
        self.is_set = is_set

    @property
    def input_spec(self):
        from ..stages.base import VarArity
        return VarArity(OPSet if self.is_set else Text)

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        """Strings → per-feature one-hot blocks, built on host.

        Output per feature: f64[n, K+1(+1)] already scattered — the one-hot
        scatter is host work because the vocab lookup is; device_compute is
        then a pure concat (fusable into the layer's XLA computation).
        Vocab lookup + scatter are vectorized (ops/_hostvec.py): one dict
        probe per UNIQUE value, one fancy-index per feature.
        """
        from ._hostvec import multihot_block, onehot_block
        names = self._names()
        n = store.n_rows
        nul = 1 if self.track_nulls else 0
        widths = [len(v) + 1 + nul for v in self.vocabs]
        mat = np.zeros((n, sum(widths)), dtype=VEC_DTYPE)
        off = 0
        for name, vocab, w in zip(names, self.vocabs, widths):
            col = store[name]
            sect = mat[:, off:off + w]
            if isinstance(col, TextSetColumn):
                multihot_block(col.values, vocab, self.track_nulls, out=sect)
            else:
                onehot_block(col.values, vocab, self.track_nulls, out=sect)
            off += w
        return {"mat": mat}

    def device_compute(self, xp, prepared):
        return xp.asarray(prepared["mat"])

    def vector_metadata(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for name, vocab in zip(self._names(), self.vocabs):
            for v in vocab:
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name, parent_feature_type=self.ftype_name,
                    grouping=name, indicator_value=v))
            cols.append(VectorColumnMetadata(
                parent_feature_name=name, parent_feature_type=self.ftype_name,
                grouping=name, indicator_value=OTHER_INDICATOR))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name, parent_feature_type=self.ftype_name,
                    grouping=name, indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        return {"vocabs": self.vocabs, "input_names_saved": self._names()}


@register_stage
class OneHotVectorizer(VectorizerEstimator):
    """Categorical text pivot estimator (OpOneHotVectorizer.scala)."""

    operation_name = "pivot"
    seq_type = Text

    def __init__(self, top_k: int = TransmogrifierDefaults.TOP_K,
                 min_support: int = TransmogrifierDefaults.MIN_SUPPORT,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls

    #: vocab-count source for the fused engine + model flavor; the set
    #: pivot overrides both knobs and inherits every fit body
    _count_kind = "value_counts"
    _is_set = False

    def _count(self, col) -> Counter:
        from ._hostvec import value_counts
        return value_counts(col.values)

    def _model_of(self, vocabs) -> OneHotModel:
        # is_set/ftype_name must ride the ctor so save/load preserves them
        return OneHotModel(
            vocabs=vocabs, track_nulls=self.track_nulls,
            input_names=self.input_names,
            ftype_name=self.seq_type.__name__, is_set=self._is_set)

    def fit_columns(self, store: ColumnStore) -> OneHotModel:
        return self._model_of(
            [_sorted_topk(self._count(store[n]), self.top_k,
                          self.min_support)
             for n in self.input_names])

    # -- fused fit-statistics opt-in (fitstats.py) -------------------------
    # Two pivot stages over the same column (different top_k) share ONE
    # value-count pass: the request is the raw Counter, the per-stage
    # top-K cut happens in the finalize.
    def stat_requests(self, store):
        from ..fitstats import StatRequest
        return [StatRequest(self._count_kind, n)
                for n in self.input_names]

    def fit_columns_from_stats(self, store, stats):
        return self._model_of(
            [_sorted_topk(stats.value(self._count_kind, n),
                          self.top_k, self.min_support)
             for n in self.input_names])


@register_stage
class SetVectorizer(OneHotVectorizer):
    """MultiPickList pivot (OpSetVectorizer): multi-hot over top-K values."""

    operation_name = "pivotSet"
    seq_type = OPSet

    _count_kind = "set_value_counts"
    _is_set = True

    def _count(self, col) -> Counter:
        from ._hostvec import flatten_ragged, value_counts
        flat, _rows, _lengths = flatten_ragged(col.values)
        return value_counts(flat)
