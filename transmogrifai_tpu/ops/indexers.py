"""String indexing — text labels ⇄ numeric indices.

Parity:

* ``OpStringIndexerNoFilter`` (``core/.../impl/feature/OpStringIndexerNoFilter.scala:48-74``):
  fit orders labels by descending frequency (Spark StringIndexer default),
  null maps to the literal label ``"null"``, and unseen values at transform
  time take index ``len(labels)`` under the ``unseen_name`` label.
* ``OpIndexToStringNoFilter`` (``OpIndexToString.scala``): index → label,
  out-of-range → ``unseen_name``.
* ``PredictionDeIndexer`` (``core/.../impl/preparators/PredictionDeIndexer.scala:52-88``):
  estimator over (indexed response, prediction) that reads the label mapping
  from the response column's metadata — here the ``labels`` attribute of
  :class:`~transmogrifai_tpu.columns.NumericColumn`, the NominalAttribute
  analog — and deindexes predictions back to label strings.

TPU note: indexing itself is host work (strings live on host); the indexed
output is a dense f64 column + labels metadata, ready for the device.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..columns import (Column, ColumnStore, NumericColumn, PredictionColumn,
                       TextColumn)
from ..stages.base import (AllowLabelAsInput, Estimator, FittedModel,
                           FixedArity, InputSpec, Transformer, register_stage)
from ..types.feature_types import Prediction, RealNN, Text

__all__ = ["OpStringIndexerNoFilter", "OpStringIndexerModel",
           "OpIndexToStringNoFilter", "PredictionDeIndexer",
           "PredictionDeIndexerModel"]

UNSEEN_DEFAULT = "UnseenLabel"
NULL_LABEL = "null"   # reference maps None to the literal "null"


@register_stage
class OpStringIndexerModel(FittedModel):
    """Fitted indexer: label list ordered by training frequency desc."""

    operation_name = "strIdx"
    output_type = RealNN

    def __init__(self, labels: Sequence[str] = (),
                 unseen_name: str = UNSEEN_DEFAULT,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.labels = list(labels)
        self.unseen_name = unseen_name

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text)

    def transform_columns(self, store: ColumnStore) -> Column:
        from ._hostvec import string_codes
        col = store[self.input_features[0].name]
        values = [NULL_LABEL if v is None else v for v in col.values]
        codes, _ = string_codes(values, self.labels)   # unseen → len(labels)
        vals = codes.astype(np.float64)
        return NumericColumn(RealNN, vals, np.ones(len(vals), bool),
                             labels=self.labels + [self.unseen_name])

    def get_model_state(self) -> Dict[str, Any]:
        return {"labels": self.labels}


@register_stage
class OpStringIndexerNoFilter(Estimator):
    """Estimator(Text) → RealNN indices, keeping unseen values (NoFilter)."""

    operation_name = "strIdx"
    output_type = RealNN

    def __init__(self, unseen_name: str = UNSEEN_DEFAULT,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.unseen_name = unseen_name

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text)

    def fit_columns(self, store: ColumnStore) -> OpStringIndexerModel:
        from ._hostvec import value_counts
        col = store[self.input_features[0].name]
        counts = value_counts(
            [NULL_LABEL if v is None else v for v in col.values])
        # frequency desc, label asc tiebreak (Spark frequencyDesc order)
        labels = [lbl for lbl, _ in
                  sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))]
        return OpStringIndexerModel(labels=labels,
                                    unseen_name=self.unseen_name)


@register_stage
class OpIndexToStringNoFilter(Transformer):
    """Transformer(RealNN) → Text via a fixed label list."""

    operation_name = "idx2str"
    output_type = Text

    def __init__(self, labels: Sequence[str] = (),
                 unseen_name: str = UNSEEN_DEFAULT,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.labels = list(labels)
        self.unseen_name = unseen_name

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        idx = np.asarray(col.values).astype(np.int64)
        out = np.empty(len(col), dtype=object)
        k = len(self.labels)
        for i, j in enumerate(idx):
            out[i] = self.labels[j] if 0 <= j < k else self.unseen_name
        return TextColumn(Text, out)


@register_stage
class PredictionDeIndexerModel(FittedModel, AllowLabelAsInput):
    """Fitted deindexer: prediction index → response label string."""

    operation_name = "idx2str"
    output_type = Text

    def __init__(self, labels: Sequence[str] = (),
                 unseen_name: str = UNSEEN_DEFAULT,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.labels = list(labels)
        self.unseen_name = unseen_name

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, Prediction)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[1].name]
        assert isinstance(col, PredictionColumn)
        idx = col.prediction.astype(np.int64)
        out = np.empty(len(col), dtype=object)
        k = len(self.labels)
        for i, j in enumerate(idx):
            out[i] = self.labels[j] if 0 <= j < k else self.unseen_name
        return TextColumn(Text, out)

    def get_model_state(self) -> Dict[str, Any]:
        return {"labels": self.labels}


@register_stage
class PredictionDeIndexer(Estimator, AllowLabelAsInput):
    """Estimator(indexed response, Prediction) → Text.

    Reads the label mapping from the response column's ``labels`` metadata
    (attached by :class:`OpStringIndexerModel`), exactly as the reference
    reads the NominalAttribute from the response schema
    (``PredictionDeIndexer.scala:61-68``)."""

    operation_name = "idx2str"
    output_type = Text

    def __init__(self, unseen_name: str = UNSEEN_DEFAULT,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.unseen_name = unseen_name

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, Prediction)

    def fit_columns(self, store: ColumnStore) -> PredictionDeIndexerModel:
        resp = self.input_features[0]
        col = store[resp.name]
        labels = getattr(col, "labels", None)
        if not labels:
            raise ValueError(
                f"The feature {resp.name!r} does not contain any label/index "
                "mapping in its metadata — index it with "
                "OpStringIndexerNoFilter first")
        return PredictionDeIndexerModel(labels=labels,
                                        unseen_name=self.unseen_name)
