"""SmartTextVectorizer — cardinality-adaptive text vectorization.

Parity: ``core/.../impl/feature/SmartTextVectorizer.scala:60-231``: fit
computes a per-feature ``TextStats`` value-count semigroup capped at
``max_cardinality`` (=100, :170-182). Features with cardinality <=
max_cardinality are pivoted (one-hot top-K + OTHER + null); the rest get the
hashing trick (+ optional length column) + null indicator.

The fitted model delegates to OneHotModel / HashingVectorizerModel blocks so
both paths share the host/device split.
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columns import ColumnStore
from ..stages.base import register_stage
from ..types.feature_types import Text
from ..vector_metadata import VectorColumnMetadata, VectorMetadata
from .hashing import HashingVectorizerModel, hash_tokens
from .onehot import OneHotModel, _sorted_topk
from .vectorizer_base import (TransmogrifierDefaults, VEC_DTYPE,
                              VectorizerEstimator,
                              VectorizerModel, null_indicator_meta)

__all__ = ["SmartTextVectorizer", "SmartTextVectorizerModel"]


class TextStats:
    """Value-count semigroup with cardinality cap (SmartTextVectorizer.scala:170)."""

    def __init__(self, max_cardinality: int):
        self.max_cardinality = max_cardinality
        self.counts: Counter = Counter()
        self.capped = False

    def add(self, value: Optional[str]) -> None:
        if value is None or self.capped:
            return
        self.counts[value] += 1
        if len(self.counts) > self.max_cardinality:
            self.capped = True

    @property
    def cardinality(self) -> int:
        return len(self.counts)


@register_stage
class SmartTextVectorizerModel(VectorizerModel):
    """Per-feature routing: categorical → pivot block, text → hash block."""

    operation_name = "smartTextVec"
    seq_type = Text

    def __init__(self, is_categorical: Sequence[bool] = (),
                 vocabs: Sequence[Sequence[str]] = (),
                 num_features: int = TransmogrifierDefaults.HASH_SIZE,
                 track_nulls: bool = True,
                 track_text_len: bool = False,
                 seed: int = 42,
                 input_names: Sequence[str] = (),
                 ftype_name: str = "Text",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.is_categorical = list(is_categorical)
        self.vocabs = [list(v) for v in vocabs]
        self.num_features = num_features
        self.track_nulls = track_nulls
        self.track_text_len = track_text_len
        self.seed = seed
        self.input_names_saved = list(input_names)
        self.ftype_name = ftype_name

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def _widths(self) -> List[int]:
        widths = []
        vocab_iter = iter(self.vocabs)
        nul = 1 if self.track_nulls else 0
        for cat in self.is_categorical:
            if cat:
                widths.append(len(next(vocab_iter)) + 1 + nul)
            else:
                widths.append(self.num_features
                              + (1 if self.track_text_len else 0) + nul)
        return widths

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        """One full-width matrix written in place — per-feature sections are
        views, so no concat copy ever happens (a full copy of a 512-wide
        hash block costs seconds on one host core)."""
        from ._hostvec import hashed_text_block, onehot_block
        names = self._names()
        n = store.n_rows
        widths = self._widths()
        mat = np.zeros((n, sum(widths)), dtype=VEC_DTYPE)
        vocab_iter = iter(self.vocabs)
        off = 0
        for j, name in enumerate(names):
            col = store[name]
            sect = mat[:, off:off + widths[j]]
            if self.is_categorical[j]:
                vocab = next(vocab_iter)
                onehot_block(col.values, vocab, self.track_nulls, out=sect)
            else:
                # fused C++ tokenize+hash+scatter (Python-tokenizer
                # fallback inside) — see _hostvec.hashed_text_block
                nullf = hashed_text_block(
                    col.values, self.num_features, self.seed, False,
                    out=mat, col_offset=off)
                null_mask = nullf > 0
                if self.track_text_len:
                    lens = np.fromiter(
                        (0.0 if v is None else len(v) for v in col.values),
                        np.float64, count=n)
                    sect[:, self.num_features] = lens
                if self.track_nulls:
                    sect[null_mask, -1] = 1.0
            off += widths[j]
        return {"mat": mat}

    def device_compute(self, xp, prepared):
        return xp.asarray(prepared["mat"])

    def vector_metadata(self) -> VectorMetadata:
        from ..vector_metadata import OTHER_INDICATOR
        names = self._names()
        cols: List[VectorColumnMetadata] = []
        vocab_iter = iter(self.vocabs)
        for j, name in enumerate(names):
            if self.is_categorical[j]:
                vocab = next(vocab_iter)
                for v in vocab:
                    cols.append(VectorColumnMetadata(
                        parent_feature_name=name,
                        parent_feature_type=self.ftype_name,
                        grouping=name, indicator_value=v))
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name,
                    parent_feature_type=self.ftype_name,
                    grouping=name, indicator_value=OTHER_INDICATOR))
                if self.track_nulls:
                    cols.append(null_indicator_meta(name, self.ftype_name, name))
            else:
                for i in range(self.num_features):
                    cols.append(VectorColumnMetadata(
                        parent_feature_name=name,
                        parent_feature_type=self.ftype_name,
                        descriptor_value=f"hash_{i}"))
                if self.track_text_len:
                    cols.append(VectorColumnMetadata(
                        parent_feature_name=name,
                        parent_feature_type=self.ftype_name,
                        descriptor_value="TextLen"))
                if self.track_nulls:
                    cols.append(null_indicator_meta(name, self.ftype_name))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        return {"is_categorical": self.is_categorical, "vocabs": self.vocabs,
                "input_names_saved": self._names()}


@register_stage
class SmartTextVectorizer(VectorizerEstimator):
    """Estimator: probe cardinality, route each feature (SmartTextVectorizer)."""

    operation_name = "smartTextVec"
    seq_type = Text

    def __init__(self, max_cardinality: int = 100,
                 top_k: int = TransmogrifierDefaults.TOP_K,
                 min_support: int = TransmogrifierDefaults.MIN_SUPPORT,
                 num_features: int = TransmogrifierDefaults.HASH_SIZE,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 track_text_len: bool = False,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_features = num_features
        self.track_nulls = track_nulls
        self.track_text_len = track_text_len

    def fit_columns(self, store: ColumnStore) -> SmartTextVectorizerModel:
        is_cat: List[bool] = []
        vocabs: List[List[str]] = []
        for name in self.input_names:
            stats = TextStats(self.max_cardinality)
            col = store[name]
            for v in col.values:
                stats.add(v)
            if not stats.capped:
                is_cat.append(True)
                vocabs.append(_sorted_topk(stats.counts, self.top_k,
                                           self.min_support))
            else:
                is_cat.append(False)
        return SmartTextVectorizerModel(
            is_categorical=is_cat, vocabs=vocabs,
            num_features=self.num_features, track_nulls=self.track_nulls,
            track_text_len=self.track_text_len,
            input_names=self.input_names, ftype_name=self.seq_type.__name__)
