"""Date/time vectorization — unit-circle embedding.

Parity: ``DateToUnitCircleTransformer`` (``core/.../impl/feature/
DateToUnitCircleTransformer.scala:78``): a timestamp's periodic component
(HourOfDay / DayOfWeek / DayOfMonth / DayOfYear) maps to (sin θ, cos θ) on
the unit circle — the TPU-friendly continuous encoding of cyclic time.

Timestamps are epoch milliseconds (reference convention, joda-free).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columns import ColumnStore
from ..stages.base import register_stage
from ..types.feature_types import Date
from ..vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizer_base import (TransmogrifierDefaults, VectorizerEstimator,
                              VectorizerModel, null_indicator_meta)

__all__ = ["DateToUnitCircleVectorizer", "TimePeriod", "period_radians"]

_MS_PER_HOUR = 3600 * 1000
_MS_PER_DAY = 24 * _MS_PER_HOUR


class TimePeriod:
    HOUR_OF_DAY = "HourOfDay"
    DAY_OF_WEEK = "DayOfWeek"
    DAY_OF_MONTH = "DayOfMonth"
    DAY_OF_YEAR = "DayOfYear"
    WEEK_OF_YEAR = "WeekOfYear"
    MONTH_OF_YEAR = "MonthOfYear"

    ALL = [HOUR_OF_DAY, DAY_OF_WEEK, DAY_OF_MONTH, DAY_OF_YEAR]


def period_radians(xp, millis, period: str):
    """θ in [0, 2π) for the given period of an epoch-ms timestamp.

    Pure array math (no calendar library) so it jits: day-of-week uses the
    epoch anchor (1970-01-01 = Thursday); month/day-of-year use the mean
    month/year length — adequate for a cyclic embedding.
    """
    two_pi = 2.0 * np.pi
    if period == TimePeriod.HOUR_OF_DAY:
        frac = (millis % _MS_PER_DAY) / _MS_PER_DAY
    elif period == TimePeriod.DAY_OF_WEEK:
        days = millis // _MS_PER_DAY
        frac = ((days + 4) % 7) / 7.0  # epoch was Thursday (index 4 of Mon=0)
    elif period == TimePeriod.DAY_OF_MONTH:
        days = (millis / _MS_PER_DAY) % 30.4375
        frac = days / 30.4375
    elif period == TimePeriod.DAY_OF_YEAR:
        days = (millis / _MS_PER_DAY) % 365.2425
        frac = days / 365.2425
    elif period == TimePeriod.WEEK_OF_YEAR:
        weeks = xp.floor(((millis / _MS_PER_DAY) % 365.2425) / 7.0)
        frac = weeks / 52.1775
    elif period == TimePeriod.MONTH_OF_YEAR:
        months = xp.floor(((millis / _MS_PER_DAY) % 365.2425) / 30.4375)
        frac = months / 12.0
    else:
        raise ValueError(f"Unknown time period {period!r}")
    return frac * two_pi


@register_stage
class DateToUnitCircleVectorizer(VectorizerModel):
    """Date(s) → [sin θ, cos θ] per period per feature (+ null tracking).

    A pure transformer (no fit state), but exposed with the vectorizer
    protocol so it fuses into layer compilation like the others.
    """

    operation_name = "dateToUnitCircle"
    seq_type = Date

    def __init__(self, periods: Sequence[str] = (TimePeriod.HOUR_OF_DAY,),
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 input_names: Sequence[str] = (),
                 ftype_name: str = "Date",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.periods = list(periods)
        self.track_nulls = track_nulls
        self.input_names_saved = list(input_names)
        self.ftype_name = ftype_name

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        """Reduce epoch millis → (sin θ, cos θ) ON HOST in f64.

        Two reasons the reduction happens host-side: raw epoch milliseconds
        (~1.7e12) defeat f32 (24-bit mantissa ⇒ ~±1e5 ms error, enough to
        flip a day boundary), and sin/cos are transcendentals — XLA's TPU
        polynomial approximations differ from libm at the ULP level, which
        would break the fused-vs-host bit-identity guarantee. After this,
        ``device_compute`` is pure where/concat (exact in f32)."""
        sincos, masks = [], []
        for name in self._names():
            col = store[name]
            millis = col.values.astype(np.float64)
            sc = np.empty((len(millis), len(self.periods), 2), np.float64)
            for p_i, p in enumerate(self.periods):
                theta = period_radians(np, millis, p)
                sc[:, p_i, 0] = np.sin(theta)
                sc[:, p_i, 1] = np.cos(theta)
            sincos.append(sc)  # [n, P, 2] (P may be 0: null-only output)
            masks.append(col.mask)
        return {"sincos": np.stack(sincos, axis=1),  # [n, k, P, 2]
                "mask": np.stack(masks, axis=1)}     # [n, k]

    def device_compute(self, xp, prepared):
        sincos, mask = prepared["sincos"], prepared["mask"]
        n, k, P, _ = sincos.shape
        outs = []
        for j in range(k):
            m = mask[:, j]
            if P:
                vals = xp.where(m[:, None], sincos[:, j].reshape(n, 2 * P), 0.0)
                outs.append(vals)
            if self.track_nulls:
                outs.append((~m).astype(sincos.dtype)[:, None])
        return xp.concatenate(outs, axis=1)

    def vector_metadata(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for name in self._names():
            for period in self.periods:
                for d in ("x", "y"):
                    cols.append(VectorColumnMetadata(
                        parent_feature_name=name,
                        parent_feature_type=self.ftype_name,
                        descriptor_value=f"{period}_{d}"))
            if self.track_nulls:
                cols.append(null_indicator_meta(name, self.ftype_name))
        return VectorMetadata(self.meta_name, cols)

    # transformer with no fit: estimator interface for Transmogrifier
    def fit_columns(self, store):  # pragma: no cover - unused
        return self
