"""Score calibrators.

Parity: ``core/.../impl/feature/PercentileCalibrator.scala:48-120`` (quantile
buckets scaled to 0–99) and
``core/.../impl/regression/IsotonicRegressionCalibrator.scala`` (Spark
``IsotonicRegression`` on a single feature).

TPU re-design: percentile fitting is one ``np.quantile`` over the column;
isotonic fitting is pool-adjacent-violators on the sorted scores (O(n) after
the sort) with the fitted (boundary, value) staircase evaluated by
``searchsorted`` + linear interpolation at transform time — both transforms
are pure vectorized array ops.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..columns import Column, ColumnStore, NumericColumn
from ..stages.base import (AllowLabelAsInput, Estimator, FittedModel,
                           FixedArity, InputSpec, register_stage)
from ..types.feature_types import Real, RealNN

__all__ = ["PercentileCalibrator", "PercentileCalibratorModel",
           "IsotonicRegressionCalibrator", "IsotonicRegressionModel",
           "pava"]


@register_stage
class PercentileCalibratorModel(FittedModel):
    """Maps a score into its training-distribution percentile (0–99)."""

    operation_name = "percentileCalibrator"
    output_type = RealNN

    def __init__(self, splits: Sequence[float] = (),
                 output_max: int = 99, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.splits = [float(s) for s in splits]
        self.output_max = int(output_max)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Real)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        v = col.values.astype(np.float64)
        edges = np.asarray(self.splits)
        # bucket index scaled onto [0, output_max]
        idx = np.clip(np.searchsorted(edges, v, side="right") - 1,
                      0, max(len(edges) - 2, 0))
        n_buckets = max(len(edges) - 1, 1)
        scaled = np.floor(idx * (self.output_max + 1) / n_buckets)
        out = np.minimum(scaled, self.output_max)
        return NumericColumn(RealNN, out, np.ones_like(out, dtype=bool))

    def get_model_state(self):
        return {"splits": self.splits, "output_max": self.output_max}


@register_stage
class PercentileCalibrator(Estimator):
    """Estimator(Real) → RealNN percentile score (PercentileCalibrator.scala)."""

    operation_name = "percentileCalibrator"
    output_type = RealNN

    def __init__(self, num_buckets: int = 100, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_buckets = num_buckets

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Real)

    def fit_columns(self, store: ColumnStore) -> PercentileCalibratorModel:
        col = store[self.input_features[0].name]
        present = col.values[col.mask].astype(np.float64)
        if present.size == 0:
            edges = np.array([0.0, 1.0])
        else:
            qs = np.quantile(present,
                             np.linspace(0.0, 1.0, self.num_buckets + 1))
            edges = np.unique(qs)
            if edges.size < 2:
                edges = np.array([edges[0], edges[0] + 1.0])
        edges = edges.copy()
        edges[0], edges[-1] = -np.inf, np.inf
        return PercentileCalibratorModel(splits=edges.tolist(),
                                         output_max=99)


def pava(scores: np.ndarray, labels: np.ndarray, weights: np.ndarray):
    """Pool-adjacent-violators → (boundaries, values), both ascending.

    Returns the isotonic staircase fitted to (score, label, weight) triples
    (Spark IsotonicRegression semantics: ties averaged, boundaries at the
    pooled block edges).
    """
    order = np.argsort(scores, kind="stable")
    s, y, w = scores[order], labels[order], weights[order]
    # blocks as (sum_wy, sum_w, left_idx, right_idx) stacks
    vals: List[float] = []
    wsum: List[float] = []
    lefts: List[int] = []
    rights: List[int] = []
    for i in range(len(s)):
        vals.append(float(y[i] * w[i]))
        wsum.append(float(w[i]))
        lefts.append(i)
        rights.append(i)
        while len(vals) > 1 and \
                vals[-2] / max(wsum[-2], 1e-300) >= \
                vals[-1] / max(wsum[-1], 1e-300):
            v, ww = vals.pop(), wsum.pop()
            r = rights.pop()
            lefts.pop()
            vals[-1] += v
            wsum[-1] += ww
            rights[-1] = r
    boundaries: List[float] = []
    values: List[float] = []
    for v, ww, l, r in zip(vals, wsum, lefts, rights):
        mean = v / max(ww, 1e-300)
        boundaries.append(float(s[l]))
        values.append(mean)
        if r != l:
            boundaries.append(float(s[r]))
            values.append(mean)
    return np.asarray(boundaries), np.asarray(values)


@register_stage
class IsotonicRegressionModel(FittedModel, AllowLabelAsInput):
    """Monotone staircase: interpolated lookup of the PAVA fit."""

    operation_name = "isotonicCalibrator"
    output_type = RealNN

    def __init__(self, boundaries: Sequence[float] = (),
                 values: Sequence[float] = (),
                 isotonic: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.boundaries = np.asarray(list(boundaries), dtype=np.float64)
        self.values = np.asarray(list(values), dtype=np.float64)
        self.isotonic = isotonic

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, Real)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[1].name]
        v = col.values.astype(np.float64)
        x = -v if not self.isotonic else v
        if self.boundaries.size == 0:
            out = np.zeros_like(v)
        else:
            out = np.interp(x, self.boundaries, self.values)
        return NumericColumn(RealNN, out, np.ones_like(out, dtype=bool))

    def get_model_state(self):
        return {"boundaries": self.boundaries, "values": self.values,
                "isotonic": self.isotonic}


@register_stage
class IsotonicRegressionCalibrator(Estimator, AllowLabelAsInput):
    """Estimator(label RealNN, score Real) → calibrated RealNN
    (IsotonicRegressionCalibrator.scala)."""

    operation_name = "isotonicCalibrator"
    output_type = RealNN

    def __init__(self, isotonic: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.isotonic = isotonic

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, Real)

    def fit_columns(self, store: ColumnStore) -> IsotonicRegressionModel:
        ycol = store[self.input_features[0].name]
        scol = store[self.input_features[1].name]
        y = ycol.values.astype(np.float64)
        s = scol.values.astype(np.float64)
        w = scol.mask.astype(np.float64)
        x = -s if not self.isotonic else s
        keep = w > 0
        boundaries, values = pava(x[keep], y[keep], w[keep])
        return IsotonicRegressionModel(boundaries.tolist(), values.tolist(),
                                       self.isotonic)
