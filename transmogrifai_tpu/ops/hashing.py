"""Feature hashing — MurmurHash3-based hashing trick.

Parity: ``OPCollectionHashingVectorizer`` + ``HashingFun``
(``core/.../impl/feature/OPCollectionHashingVectorizer.scala``): MurmurHash3
x86 32-bit of each token, bucketed modulo ``num_features``, with a shared or
per-feature hash space (``HashSpaceStrategy``).

Hashing runs on host (strings live there); the scattered count matrix is the
device input. A C++ batch hasher (native/fasthash.cc) accelerates the hot
loop when built; the pure-Python murmur3 below is the always-available
fallback and the reference implementation for tests.
"""
from __future__ import annotations

import ctypes
import os
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..columns import ColumnStore, TextColumn, TextListColumn, TextSetColumn
from ..stages.base import register_stage
from ..types.feature_types import MultiPickList, Text, TextList
from ..vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizer_base import (TransmogrifierDefaults, VEC_DTYPE,
                              VectorizerEstimator,
                              VectorizerModel, null_indicator_meta)

__all__ = ["murmur3_32", "hash_tokens", "HashingVectorizerModel",
           "HashSpaceStrategy"]


class HashSpaceStrategy:
    SHARED = "Shared"
    SEPARATE = "Separate"
    AUTO = "Auto"


# ---------------------------------------------------------------------------
# MurmurHash3 x86 32-bit
# ---------------------------------------------------------------------------

_native_lib = None


def _stale(so_path: str, src_path: str) -> bool:
    """The cached .so predates the source — rebuild. mtime is the
    freshness gate: a fresh build always lands with mtime >= the source's
    (os.replace preserves the just-written time)."""
    try:
        return os.path.getmtime(src_path) > os.path.getmtime(so_path)
    except OSError:
        return False


def _load_native():
    global _native_lib
    if _native_lib is not None:
        return _native_lib
    native_dir = os.path.abspath(os.path.join(
        os.path.dirname(__file__), os.pardir, os.pardir, "native"))
    path = os.path.join(native_dir, "libtmogtpu.so")
    src = os.path.join(native_dir, "fasthash.cc")
    if os.path.exists(src) and (not os.path.exists(path)
                                or _stale(path, src)):
        # lazy build from source (no wheel/packaging step in this repo;
        # the binary is NOT committed — it is always built here or via
        # native/Makefile); failures fall back to the pure-Python hasher
        # silently. Compile to a per-pid temp file + atomic rename so
        # concurrent processes never see (or permanently keep) a
        # half-written .so. CXX/CXXFLAGS honor the same env overrides as
        # the Makefile, with identical defaults — one flag source, two
        # build entry points. -pthread is load-bearing: the kernel spawns
        # std::thread, and glibc<2.34/musl abort at first thread creation
        # without it.
        import shlex
        import subprocess
        tmp = f"{path}.{os.getpid()}.tmp"
        cxx = os.environ.get("CXX", "g++")
        flags = shlex.split(os.environ.get(
            "CXXFLAGS", "-O3 -std=c++17 -fPIC -Wall -pthread"))
        try:
            subprocess.run(
                [cxx, *flags, "-shared", "-o", tmp, src],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, path)
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    if os.path.exists(path):
        try:
            lib = ctypes.CDLL(path)
            lib.murmur3_batch.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint32)]
            try:
                lib.tokenized_hash_counts.argtypes = [
                    ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_int64, ctypes.c_uint32, ctypes.c_uint32,
                    ctypes.c_int32, ctypes.c_int32,
                    ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                    ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
                    ctypes.c_int32]
            except AttributeError:
                # stale .so from before the fused kernel: rebuild lazily
                # next process; this one uses the Python tokenizer path
                lib.tokenized_hash_counts = None
            _native_lib = lib
            return lib
        except OSError:
            try:   # corrupt artifact: remove so a future process rebuilds
                os.unlink(path)
            except OSError:
                pass
    _native_lib = False
    return False


def murmur3_32(data: bytes, seed: int = 42) -> int:
    """MurmurHash3 x86 32-bit (public algorithm, Austin Appleby)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    length = len(data)
    n_blocks = length // 4
    for i in range(n_blocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    tail = data[4 * n_blocks:]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def hash_tokens(tokens: Sequence[str], seed: int = 42) -> np.ndarray:
    """uint32 murmur3 hash per token; uses the C++ batch hasher if built."""
    if not tokens:
        return np.zeros((0,), dtype=np.uint32)
    lib = _load_native()
    if lib:
        encoded = [t.encode("utf-8") for t in tokens]
        blob = b"".join(encoded)
        offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        out = np.zeros(len(encoded), dtype=np.uint32)
        lib.murmur3_batch(
            blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(encoded), seed,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return out
    return np.array([murmur3_32(t.encode("utf-8"), seed) for t in tokens],
                    dtype=np.uint32)


def _column_tokens(col) -> List[List[str]]:
    """Per-row token lists for a whole column."""
    if isinstance(col, TextColumn):
        return [[v] if v is not None else [] for v in col.values]
    if isinstance(col, (TextListColumn, TextSetColumn)):
        return [list(v) for v in col.values]
    raise TypeError(f"Cannot hash column {type(col).__name__}")


@register_stage
class HashingVectorizerModel(VectorizerModel):
    """Hashing-trick transform: token counts scattered into hash buckets.

    ``shared_hash_space=True`` → all features share one ``num_features``-wide
    space; else each feature gets its own block.
    """

    operation_name = "hash"
    seq_type = (Text, TextList, MultiPickList)  # hashable collection types

    def __init__(self, num_features: int = TransmogrifierDefaults.HASH_SIZE,
                 shared_hash_space: bool = False,
                 track_nulls: bool = True,
                 binary_freq: bool = False,
                 seed: int = 42,
                 input_names: Sequence[str] = (),
                 ftype_name: str = "Text",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_features = num_features
        self.shared_hash_space = shared_hash_space
        self.track_nulls = track_nulls
        self.binary_freq = binary_freq
        self.seed = seed
        self.input_names_saved = list(input_names)
        self.ftype_name = ftype_name

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        from ._hostvec import hashed_count_block, hashed_count_flat
        names = self._names()
        n = store.n_rows
        k = len(names)
        width = self.num_features if self.shared_hash_space \
            else self.num_features * k
        # counts and null indicators live in ONE matrix (nulls in the tail
        # columns) so no concat copy is needed downstream
        mat = np.zeros((n, width + (k if self.track_nulls else 0)),
                       dtype=VEC_DTYPE)
        for j, name in enumerate(names):
            col = store[name]
            base = 0 if self.shared_hash_space else j * self.num_features
            if isinstance(col, TextColumn):
                # flat fast-path: a Text column's tokens ARE its non-null
                # values — no per-row singleton lists
                null_mask = np.fromiter((v is None for v in col.values),
                                        bool, count=n)
                rows = np.nonzero(~null_mask)[0]
                flat = [col.values[r] for r in rows]
                _, null_j = hashed_count_flat(
                    flat, rows, null_mask, n, self.num_features, self.seed,
                    self.binary_freq, out=mat, col_offset=base)
            else:
                _, null_j = hashed_count_block(
                    _column_tokens(col), self.num_features, self.seed,
                    self.binary_freq, out=mat, col_offset=base)
            if self.track_nulls:
                mat[:, width + j] = null_j
        return {"mat": mat}

    def device_compute(self, xp, prepared):
        return xp.asarray(prepared["mat"])

    def vector_metadata(self) -> VectorMetadata:
        names = self._names()
        cols: List[VectorColumnMetadata] = []
        if self.shared_hash_space:
            for i in range(self.num_features):
                cols.append(VectorColumnMetadata(
                    parent_feature_name=names[0] if len(names) == 1 else "shared",
                    parent_feature_type=self.ftype_name,
                    grouping=None, descriptor_value=f"hash_{i}"))
        else:
            for name in names:
                for i in range(self.num_features):
                    cols.append(VectorColumnMetadata(
                        parent_feature_name=name,
                        parent_feature_type=self.ftype_name,
                        descriptor_value=f"hash_{i}"))
        if self.track_nulls:
            for name in names:
                cols.append(null_indicator_meta(name, self.ftype_name))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        return {"input_names_saved": self._names()}
