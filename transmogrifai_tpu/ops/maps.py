"""Map vectorizers — expand map keys into columns, delegate per element kind.

Parity: ``OPMapVectorizer`` family (``core/.../impl/feature/OPMapVectorizer.scala``,
``TextMapPivotVectorizer``, ``MultiPickListMapVectorizer``,
``SmartTextMapVectorizer``, ``GeolocationMapVectorizer``,
``DateMapToUnitCircleVectorizer``).

Design: a fitted map vectorizer records the key set discovered at fit time,
explodes each map feature into per-key child columns named
``{feature}::{key}``, and delegates to the matching scalar vectorizer model
— so every element kind reuses the exact impute/pivot/hash/unit-circle logic
and metadata layout of its scalar counterpart, with ``grouping`` set to the
map key (OpVectorColumnMetadata semantics).
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..columns import (Column, ColumnStore, GeoColumn, MapColumn,
                       NumericColumn, TextColumn, TextSetColumn,
                       column_of_empty)
from ..features import Feature
from ..stages.base import (FixedArity, Transformer, VarArity,
                           register_stage)
from ..types import feature_types as ft
from ..vector_metadata import VectorColumnMetadata, VectorMetadata
from .dates import DateToUnitCircleVectorizer
from .geo import GeolocationVectorizerModel, geo_mean
from .numeric import NumericVectorizerModel
from .onehot import OneHotModel, _sorted_topk
from .vectorizer_base import (TransmogrifierDefaults, VectorizerEstimator,
                              VectorizerModel)

__all__ = ["MapVectorizer", "MapVectorizerModel", "vectorize_maps",
           "FilterMapKeys", "ExtractMapKey"]


def _exploded_name(feature: str, key: str) -> str:
    return f"{feature}::{key}"


def _child_or_empty(col: MapColumn, key: str, elem_ftype) -> Column:
    child = col.children.get(key)
    if child is not None:
        return child
    return column_of_empty(elem_ftype, len(col))


def _explode(store: ColumnStore, names: Sequence[str],
             keys_per_feature: Sequence[Sequence[str]]) -> ColumnStore:
    cols = {}
    for name, keys in zip(names, keys_per_feature):
        col = store[name]
        assert isinstance(col, MapColumn), f"{name} is not a map column"
        for k in keys:
            cols[_exploded_name(name, k)] = _child_or_empty(
                col, k, col.ftype.element_type)
    return ColumnStore(cols, store.n_rows)


@register_stage
class MapVectorizerModel(VectorizerModel):
    """Fitted map vectorizer: keys + a delegate scalar vectorizer model."""

    operation_name = "vecMap"
    seq_type = ft.OPMap

    def __init__(self, keys_per_feature: Sequence[Sequence[str]] = (),
                 delegate_class: str = "NumericVectorizerModel",
                 delegate_params: Optional[dict] = None,
                 input_names: Sequence[str] = (),
                 ftype_name: str = "RealMap",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.keys_per_feature = [list(k) for k in keys_per_feature]
        self.delegate_class = delegate_class
        self.delegate_params = dict(delegate_params or {})
        self.input_names_saved = list(input_names)
        self.ftype_name = ftype_name
        self._delegate = None

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    @property
    def delegate(self):
        if self._delegate is None:
            from ..stages.base import STAGE_REGISTRY
            cls = STAGE_REGISTRY[self.delegate_class]
            exploded = [_exploded_name(n, k)
                        for n, keys in zip(self._names(), self.keys_per_feature)
                        for k in keys]
            self._delegate = cls(input_names=exploded, **self.delegate_params)
        return self._delegate

    def host_prepare(self, store: ColumnStore):
        exploded = _explode(store, self._names(), self.keys_per_feature)
        return self.delegate.host_prepare(exploded)

    def device_compute(self, xp, prepared):
        return self.delegate.device_compute(xp, prepared)

    def vector_metadata(self) -> VectorMetadata:
        meta = self.delegate.vector_metadata()
        cols = []
        for cm in meta.columns:
            feat, _, key = cm.parent_feature_name.partition("::")
            cols.append(VectorColumnMetadata(
                parent_feature_name=feat,
                parent_feature_type=self.ftype_name,
                grouping=key or cm.grouping,
                indicator_value=cm.indicator_value,
                descriptor_value=cm.descriptor_value))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        return {"keys_per_feature": self.keys_per_feature,
                "delegate_params": self.delegate_params,
                "input_names_saved": self._names()}


@register_stage
class MapVectorizer(VectorizerEstimator):
    """Estimator: discover keys, fit the per-kind delegate
    (OPMapVectorizer.scala)."""

    operation_name = "vecMap"
    seq_type = ft.OPMap

    def __init__(self, top_k: int = TransmogrifierDefaults.TOP_K,
                 min_support: int = TransmogrifierDefaults.MIN_SUPPORT,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 default_value: Optional[float] = None,
                 fill_with_mean: bool = True,
                 fill_with_mode: bool = True,
                 uid: Optional[str] = None):
        """``default_value`` / ``fill_with_mean`` / ``fill_with_mode``
        mirror RichMapFeature.vectorize's per-call fill surface
        (``core/.../dsl/RichMapFeature.scala:497-540,665-696``): a fixed
        fill for missing keys, or the per-key train mean (Real maps) /
        mode (Integral maps) when the respective flag is on (the
        reference's ``fillWithMean``/``fillWithMode`` semantics)."""
        super().__init__(uid=uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls
        self.default_value = default_value
        self.fill_with_mean = fill_with_mean
        self.fill_with_mode = fill_with_mode

    def _discover_keys(self, store: ColumnStore) -> List[List[str]]:
        out = []
        for name in self.input_names:
            col = store[name]
            assert isinstance(col, MapColumn)
            out.append(sorted(col.children.keys()))
        return out

    def fit_columns(self, store: ColumnStore) -> MapVectorizerModel:
        elem = self.input_features[0].ftype.map_element_kind
        ftype = self.input_features[0].ftype
        keys = self._discover_keys(store)
        exploded = _explode(store, self.input_names, keys)
        exploded_names = list(exploded.names())

        if elem in (ft.ColumnKind.REAL, ft.ColumnKind.INTEGRAL,
                    ft.ColumnKind.BINARY):
            if issubclass(ftype.element_type, ft.Date):
                delegate_cls, params = "DateToUnitCircleVectorizer", {
                    "periods": TransmogrifierDefaults.CIRCULAR_DATE_REPRESENTATIONS,
                    "track_nulls": self.track_nulls}
            else:
                base_fill = (0.0 if self.default_value is None
                             else float(self.default_value))
                fills = []
                for n in exploded_names:
                    col = exploded[n]
                    if (elem == ft.ColumnKind.REAL and self.fill_with_mean
                            and col.mask.any()):
                        fills.append(float(
                            col.values[col.mask].astype(np.float64).mean()))
                    elif (elem == ft.ColumnKind.INTEGRAL
                            and self.fill_with_mode and col.mask.any()):
                        vals, counts = np.unique(col.values[col.mask],
                                                 return_counts=True)
                        fills.append(float(vals[np.argmax(counts)]))
                    else:
                        fills.append(base_fill)
                delegate_cls, params = "NumericVectorizerModel", {
                    "fill_values": fills, "track_nulls": self.track_nulls,
                    "ftype_name": ftype.__name__}
        elif elem in (ft.ColumnKind.TEXT, ft.ColumnKind.TEXT_SET):
            vocabs = []
            for n in exploded_names:
                col = exploded[n]
                c: Counter = Counter()
                if isinstance(col, TextSetColumn):
                    for values in col.values:
                        for v in values:
                            c[v] += 1
                else:
                    for v in col.values:
                        if v is not None:
                            c[v] += 1
                vocabs.append(_sorted_topk(c, self.top_k, self.min_support))
            delegate_cls, params = "OneHotModel", {
                "vocabs": vocabs, "track_nulls": self.track_nulls,
                "ftype_name": ftype.__name__,
                "is_set": elem == ft.ColumnKind.TEXT_SET}
        elif elem == ft.ColumnKind.GEO:
            fills = []
            for n in exploded_names:
                col = exploded[n]
                assert isinstance(col, GeoColumn)
                fills.append(geo_mean(col.values, col.mask))
            delegate_cls, params = "GeolocationVectorizerModel", {
                "fill_values": fills, "track_nulls": self.track_nulls}
        else:
            raise TypeError(
                f"No map vectorizer for element kind {elem} ({ftype.__name__})")

        return MapVectorizerModel(
            keys_per_feature=keys, delegate_class=delegate_cls,
            delegate_params=params, input_names=self.input_names,
            ftype_name=ftype.__name__)


@register_stage
class SmartTextMapVectorizer(MapVectorizer):
    """TextMap smart vectorization (``RichMapFeature.smartVectorize``,
    ``core/.../dsl/RichMapFeature.scala:280-350``): each map KEY gets the
    SmartText cardinality probe — low-cardinality keys pivot into top-K
    one-hot columns, high-cardinality keys hash — instead of the plain
    MapVectorizer's pivot-everything. The fitted delegate is a
    ``SmartTextVectorizerModel`` over the exploded per-key columns."""

    operation_name = "smartVecTextMap"
    seq_type = ft.OPMap

    def __init__(self, max_cardinality: int = 100,
                 top_k: int = TransmogrifierDefaults.TOP_K,
                 min_support: int = TransmogrifierDefaults.MIN_SUPPORT,
                 num_features: int = TransmogrifierDefaults.HASH_SIZE,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 track_text_len: bool = False,
                 uid: Optional[str] = None):
        super().__init__(top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls, uid=uid)
        self.max_cardinality = max_cardinality
        self.num_features = num_features
        self.track_text_len = track_text_len

    def fit_columns(self, store: ColumnStore) -> MapVectorizerModel:
        from .smart_text import TextStats

        ftype = self.input_features[0].ftype
        if ftype.map_element_kind is not ft.ColumnKind.TEXT:
            raise TypeError(
                f"smartVectorize needs a text-valued map, got "
                f"{ftype.__name__}")
        keys = self._discover_keys(store)
        exploded = _explode(store, self.input_names, keys)
        is_cat: List[bool] = []
        vocabs: List[List[str]] = []
        for name in exploded.names():
            stats = TextStats(self.max_cardinality)
            for v in exploded[name].values:
                stats.add(v)
            if not stats.capped:
                is_cat.append(True)
                vocabs.append(_sorted_topk(stats.counts, self.top_k,
                                           self.min_support))
            else:
                is_cat.append(False)
        return MapVectorizerModel(
            keys_per_feature=keys, delegate_class="SmartTextVectorizerModel",
            delegate_params={
                "is_categorical": is_cat, "vocabs": vocabs,
                "num_features": self.num_features,
                "track_nulls": self.track_nulls,
                "track_text_len": self.track_text_len,
                "ftype_name": ftype.__name__},
            input_names=self.input_names, ftype_name=ftype.__name__)


def vectorize_maps(features: Sequence[Feature],
                   defaults: Type[TransmogrifierDefaults]
                   ) -> List[Feature]:
    """Group map features by concrete type; one MapVectorizer per type."""
    by_type: Dict[Type, List[Feature]] = {}
    for f in features:
        by_type.setdefault(f.ftype, []).append(f)
    out = []
    for ftype, feats in sorted(by_type.items(), key=lambda kv: kv[0].__name__):
        stage = MapVectorizer(top_k=defaults.TOP_K,
                              min_support=defaults.MIN_SUPPORT,
                              track_nulls=defaults.TRACK_NULLS,
                              default_value=defaults.FILL_VALUE,
                              fill_with_mean=defaults.FILL_WITH_MEAN,
                              fill_with_mode=defaults.FILL_WITH_MODE)
        out.append(feats[0].transform_with(stage, *feats[1:]))
    return out


# ---------------------------------------------------------------------------
# Map-feature DSL transformers (RichMapFeature analogs)
# ---------------------------------------------------------------------------

@register_stage
class FilterMapKeys(Transformer):
    """Map → same map with keys filtered by allow/block lists
    (RichMapFeature ``filter`` with whiteList/blackList keys,
    ``core/.../dsl/RichMapFeature.scala``)."""

    operation_name = "filterMapKeys"

    def __init__(self, allow: Optional[Sequence[str]] = None,
                 block: Sequence[str] = (), uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.allow = list(allow) if allow is not None else None
        self.block = list(block)
        self.output_type = ft.FeatureType    # refined in get_output

    @property
    def input_spec(self):
        return FixedArity(ft.OPMap)

    def get_output(self) -> Feature:
        if self._output_feature is None:
            f = self.input_features[0]
            self._output_feature = Feature(
                name=self.make_output_name(), ftype=f.ftype,
                is_response=f.is_response, origin_stage=self,
                parents=self.input_features)
        return self._output_feature

    def _keep(self, key: str) -> bool:
        if self.allow is not None and key not in self.allow:
            return False
        return key not in self.block

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        assert isinstance(col, MapColumn)
        children = {k: c for k, c in col.children.items() if self._keep(k)}
        return MapColumn(col.ftype, children, len(col))


@register_stage
class ExtractMapKey(Transformer):
    """Map → the element-typed column of one key (missing key → all-null;
    the per-key access every map vectorizer/pivot builds on — exposed as a
    standalone DSL stage so users can route single map entries into any
    scalar pipeline)."""

    operation_name = "extractMapKey"

    def __init__(self, key: str = "", uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.key = key
        self.output_type = ft.FeatureType    # refined in get_output

    @property
    def input_spec(self):
        return FixedArity(ft.OPMap)

    def get_output(self) -> Feature:
        if self._output_feature is None:
            f = self.input_features[0]
            self._output_feature = Feature(
                name=self.make_output_name(),
                ftype=f.ftype.element_type,
                is_response=f.is_response, origin_stage=self,
                parents=self.input_features)
        return self._output_feature

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        assert isinstance(col, MapColumn)
        return _child_or_empty(col, self.key, col.ftype.element_type)
