"""Collection-lifted unary transforms — the OPCollectionTransformer family.

Parity: ``core/.../impl/feature/OPCollectionTransformer.scala:1-209``:
a unary transformer between scalar feature types lifts onto the matching
collection types — map VALUES, set elements, list elements — with the
same early type validation (an ``OPMapTransformer`` built from a
``Real → Text`` transformer only accepts RealMap inputs and yields a
TextMap) and the same empty-in → empty-out contract.

TPU-first design: the reference boxes every element through the scalar
transformer's ``transformFn`` per row. Here the lift stays columnar —
a map's per-key child columns ARE scalar columns, so each key transforms
as one whole column; list/set elements transform once over the FLAT
element array (CSR offsets re-nest the result) — one vectorized pass per
collection, never a per-element Python call into the stage.
"""
from __future__ import annotations

from typing import List, Optional, Type

import numpy as np

from ..columns import (Column, ColumnStore, MapColumn, RaggedColumn,
                       TextListColumn, TextSetColumn, column_from_values)
from ..stages.base import (FixedArity, InputSpec, Transformer,
                           register_stage)
from ..types import feature_types as ft
from ..types.feature_types import ColumnKind, FeatureType

__all__ = ["OPMapTransformer", "OPListTransformer", "OPSetTransformer",
           "lift_to_collection", "map_type_for"]


def map_type_for(elem_ftype: Type[FeatureType]) -> Type[FeatureType]:
    """Scalar feature type → its OPMap type (Real → RealMap, …), the
    ``O → OMap`` association the reference fixes with type parameters."""
    from ..types.feature_types import FEATURE_TYPE_REGISTRY
    named = FEATURE_TYPE_REGISTRY.get(f"{elem_ftype.__name__}Map")
    if named is not None:
        return named
    for cand in FEATURE_TYPE_REGISTRY.values():
        if (getattr(cand, "column_kind", None) is ColumnKind.MAP
                and getattr(cand, "element_type", None) is elem_ftype):
            return cand
    raise TypeError(f"No OPMap type holds {elem_ftype.__name__} values")


_LIST_OUT = {
    # scalar output kind → list type that can hold it
    ColumnKind.TEXT: ft.TextList,
    ColumnKind.INTEGRAL: ft.DateList,
}


#: element kind carried by each collection column kind
_ELEM_KIND = {ColumnKind.TEXT_LIST: ColumnKind.TEXT,
              ColumnKind.TEXT_SET: ColumnKind.TEXT,
              ColumnKind.INTEGRAL_LIST: ColumnKind.INTEGRAL}


def _check_elem(collection_ftype: Type[FeatureType],
                scalar_in: Type[FeatureType], what: str) -> None:
    """requireValidateTypes analog: fail at wiring, not mid-transform."""
    kind = collection_ftype.column_kind
    if kind is ColumnKind.MAP:
        ok = (getattr(collection_ftype, "element_type", None) is scalar_in
              or collection_ftype.map_element_kind
              is scalar_in.column_kind)
    else:
        ok = _ELEM_KIND.get(kind) is scalar_in.column_kind
    if not ok:
        raise TypeError(
            f"{collection_ftype.__name__} is not convertible with the "
            f"given {what} transformer over {scalar_in.__name__}")


def _private_copy(stage: Transformer) -> Transformer:
    """Fresh instance from ctor params (+ fitted state) — same mechanism
    stage persistence uses, so anything serializable copies faithfully."""
    from ..stages.base import FittedModel
    params = dict(stage.get_params())
    params.pop("uid", None)
    copy = type(stage)(**params)
    if isinstance(stage, FittedModel):
        state = stage.get_model_state()
        if hasattr(copy, "apply_model_state"):
            copy.apply_model_state(state)
        else:
            for k, v in state.items():
                setattr(copy, k, v)
    return copy


class _LiftedTransformer(Transformer):
    """Shared wrapper: holds the scalar transformer, wires it to a
    synthetic element feature once, and exposes columnar element
    application."""

    collection_base: Type[FeatureType] = FeatureType

    def __init__(self, transformer: Transformer,
                 operation_name: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        # PRIVATE copy (ctor-params + fitted state, the persistence
        # mechanism): the lift wires the scalar transformer to its own
        # synthetic element feature, which would silently clobber wiring
        # on a caller-owned instance shared with the DAG or another lift
        self.transformer = _private_copy(transformer)
        self.operation_name = (operation_name
                               or f"{self.lift_name}_"
                                  f"{transformer.operation_name}")
        scalar_in = self._scalar_in()
        builder = getattr(__import__(
            "transmogrifai_tpu.features", fromlist=["FeatureBuilder"]
        ).FeatureBuilder, scalar_in.__name__)
        self._elem_feature = (builder(f"__elem_{self.uid}__")
                              .from_column().as_predictor())
        self.transformer.set_input(self._elem_feature)

    # -- scalar plumbing ---------------------------------------------------
    def _scalar_in(self) -> Type[FeatureType]:
        spec = self.transformer.input_spec
        types = getattr(spec, "types", None)
        if not types or len(types) != 1:
            raise TypeError(
                "Only UNARY transformers lift onto collections "
                f"({type(self.transformer).__name__} is not)")
        return types[0]

    def _apply_elems(self, col: Column) -> Column:
        """Run the scalar transform over one column of elements."""
        name = self._elem_feature.name
        return self.transformer.transform_columns(
            ColumnStore({name: col}, len(col)))

    def set_input(self, *features):
        _check_elem(features[0].ftype, self._scalar_in(), self.lift_name)
        return super().set_input(*features)

    def get_params(self):
        p = super().get_params()
        p.pop("operation_name", None)
        return p


@register_stage
class OPMapTransformer(_LiftedTransformer):
    """Lift a scalar unary transformer over an OPMap's VALUES
    (``OPMapTransformer.doTransform``: keys pass through untouched)."""

    lift_name = "mapValues"
    operation_name = "mapValues"

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPMap)

    @property
    def output_type(self) -> Type[FeatureType]:
        return map_type_for(self.transformer.output_type)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        assert isinstance(col, MapColumn)
        children = {k: self._apply_elems(child)
                    for k, child in col.children.items()}
        return MapColumn(self.output_type, children, col.n_rows)


class _FlatLift(_LiftedTransformer):
    """List/set lift: flatten elements, transform ONCE, re-nest."""

    def _flat_rows(self, col: Column) -> List[list]:
        if isinstance(col, RaggedColumn):
            return [col.get_raw(i) for i in range(len(col))]
        return [list(col.get_raw(i) or ()) for i in range(len(col))]

    def _lifted(self, store: ColumnStore):
        col = store[self.input_features[0].name]
        rows = self._flat_rows(col)
        lengths = [len(r) for r in rows]
        flat = [x for r in rows for x in r]
        flat_in = column_from_values(self._scalar_in(), flat)
        out_col = self._apply_elems(flat_in)
        out_vals = [out_col.get_raw(i) for i in range(len(flat))]
        nested, pos = [], 0
        for ln in lengths:
            nested.append(out_vals[pos:pos + ln])
            pos += ln
        return nested

    @property
    def output_type(self) -> Type[FeatureType]:
        out_kind = self.transformer.output_type.column_kind
        lifted = _LIST_OUT.get(out_kind)
        if lifted is None:
            raise TypeError(
                f"No OPList type holds {out_kind} elements "
                f"(from {self.transformer.output_type.__name__})")
        return lifted


@register_stage
class OPListTransformer(_FlatLift):
    """Lift over OPList elements.

    Text-output lifts preserve order with one entry per input element —
    nulls from the scalar transform stay in place (the reference's 'no
    checks on the output' note). Integral-output lifts DROP null
    elements: the CSR ragged encoding has no element mask, so an
    unparseable element shortens that row's list rather than poisoning
    the numeric flat array — alignment with the source list is not
    preserved in that case."""

    lift_name = "listElems"
    operation_name = "listElems"

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPList)

    def transform_columns(self, store: ColumnStore) -> Column:
        nested = self._lifted(store)
        out_t = self.output_type
        if out_t.column_kind is ColumnKind.TEXT_LIST:
            return TextListColumn(out_t, nested)
        flat = np.asarray([x for r in nested for x in r
                           if x is not None], dtype=np.int64)
        lengths = np.asarray(
            [sum(1 for x in r if x is not None) for r in nested],
            dtype=np.int64)
        # per-row dropped-null accounting (ADVICE r3): integral lifts
        # shorten rows, so consumers needing element alignment with the
        # source list can detect (and quantify) the divergence here
        dropped = np.asarray([len(r) for r in nested],
                             dtype=np.int64) - lengths
        self.last_dropped_counts = dropped
        total = int(dropped.sum())
        if total and not getattr(self, "_warned_dropped", False):
            # once per stage instance — a streaming scoring loop would
            # otherwise emit one identical warning per micro-batch
            self._warned_dropped = True
            import logging
            logging.getLogger(__name__).warning(
                "OPListTransformer %s dropped %d null element(s) across "
                "%d row(s); integral output rows are shorter than their "
                "source lists (see last_dropped_counts; further drops "
                "by this stage are not logged)",
                self.uid, total, int((dropped > 0).sum()))
        offsets = np.concatenate([[0], np.cumsum(lengths)])
        return RaggedColumn(out_t, flat, offsets)


@register_stage
class OPSetTransformer(_FlatLift):
    """Lift over OPSet elements; output rows are de-duplicated sets
    (``OPSetTransformer.doTransform`` maps over set values)."""

    lift_name = "setElems"
    operation_name = "setElems"

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(ft.OPSet)

    @property
    def output_type(self) -> Type[FeatureType]:
        if self.transformer.output_type.column_kind is not ColumnKind.TEXT:
            raise TypeError("OPSet lifts only onto string-element sets "
                            "(MultiPickList)")
        return ft.MultiPickList

    def transform_columns(self, store: ColumnStore) -> Column:
        nested = self._lifted(store)
        return TextSetColumn(
            self.output_type,
            [{x for x in r if x is not None} for r in nested])


def lift_to_collection(transformer: Transformer,
                       collection_ftype: Type[FeatureType]) -> Transformer:
    """Pick the right lift for a collection type (the factory the
    reference spells as three class constructors)."""
    kind = collection_ftype.column_kind
    if kind is ColumnKind.MAP:
        lifted = OPMapTransformer(transformer)
    elif kind is ColumnKind.TEXT_SET:
        lifted = OPSetTransformer(transformer)
    elif kind in (ColumnKind.TEXT_LIST, ColumnKind.INTEGRAL_LIST):
        lifted = OPListTransformer(transformer)
    else:
        raise TypeError(
            f"{collection_ftype.__name__} is not a liftable collection")
    _check_elem(collection_ftype, lifted._scalar_in(), lifted.lift_name)
    return lifted
