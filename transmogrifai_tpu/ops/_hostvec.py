"""Vectorized host-side feature-prep primitives.

The round-1 vectorizers looped over rows in Python (``block[r, i] = 1``,
per-row ``hash_tokens``) — hours of host time at the 10M-row BASELINE
config before a single model fit. These helpers restate the same
transforms as numpy bulk ops:

* string → vocab code mapping runs the Python dict only over the UNIQUE
  values (``np.unique(..., return_inverse=True)`` is C-speed); rows are
  recovered with one fancy-index;
* ragged token/set columns are flattened once with row offsets and
  scattered with a single ``np.add.at``;
* murmur3 hashing runs over unique tokens through the batch (C++ when
  built) hasher.

This is host work feeding the device (SURVEY §7: "strings stay on host and
enter the device as hashed/int-indexed dense arrays"), so numpy (not JAX)
is the right substrate — object dtypes never reach XLA.
"""
from __future__ import annotations

from collections import Counter
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .vectorizer_base import VEC_DTYPE

__all__ = ["string_codes", "onehot_block", "multihot_block",
           "hashed_count_block", "hashed_count_flat", "flatten_ragged",
           "value_counts", "hashed_text_block"]

#: sentinel that cannot collide with real values (contains a NUL byte)
_NULL = "\0\0null"


def _unique_object(arr: np.ndarray, **kw):
    """np.unique over an OBJECT array of strings. Never converts to a
    fixed-width unicode dtype: '<U' arrays are sized n × longest value, so
    one long outlier in a big column would explode memory."""
    return np.unique(arr, **kw)


def string_codes(values: Sequence[Optional[str]], vocab: Sequence[str]
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Map per-row optional strings to vocab codes.

    Returns (codes [n] int64 with code k meaning OTHER, null_mask [n]).
    The vocab dict is consulted once per UNIQUE value.
    """
    k = len(vocab)
    null_mask = np.fromiter((v is None for v in values), bool,
                            count=len(values))
    arr = np.array([_NULL if v is None else v for v in values], dtype=object)
    uniq, inv = _unique_object(arr, return_inverse=True)
    index = {v: i for i, v in enumerate(vocab)}
    uniq_codes = np.fromiter(
        (index.get(u, k) for u in uniq), dtype=np.int64, count=len(uniq))
    return uniq_codes[inv], null_mask


def value_counts(values: Sequence[str]) -> Counter:
    """Counter of non-null string values via one C-speed unique pass."""
    vals = [v for v in values if v is not None]
    if not vals:
        return Counter()
    uniq, counts = _unique_object(np.asarray(vals, dtype=object),
                                  return_counts=True)
    return Counter(dict(zip(uniq.tolist(), counts.tolist())))


def onehot_block(values: Sequence[Optional[str]], vocab: Sequence[str],
                 track_nulls: bool,
                 out: Optional[np.ndarray] = None) -> np.ndarray:
    """[n, K+1(+1)] pivot block: [cat_1..cat_K, OTHER(, Null)].

    ``out`` (a zeroed array or view of the right width) avoids allocating —
    callers preassemble one full-width matrix so no concat copy is needed.
    """
    n = len(values)
    k = len(vocab)
    width = k + 1 + (1 if track_nulls else 0)
    block = out if out is not None else np.zeros((n, width), dtype=VEC_DTYPE)
    codes, null_mask = string_codes(values, vocab)
    rows = np.nonzero(~null_mask)[0]
    block[rows, codes[rows]] = 1.0
    if track_nulls:
        block[null_mask, k + 1] = 1.0
    return block


def flatten_ragged(row_values: Sequence[Sequence[str]]
                   ) -> Tuple[List[str], np.ndarray, np.ndarray]:
    """Ragged per-row string collections → (flat values, row index per
    flat value, per-row lengths)."""
    lengths = np.fromiter((len(v) for v in row_values), dtype=np.int64,
                          count=len(row_values))
    flat: List[str] = []
    for v in row_values:
        flat.extend(v)
    rows = np.repeat(np.arange(len(row_values)), lengths)
    return flat, rows, lengths


def multihot_block(row_values: Sequence[Sequence[str]], vocab: Sequence[str],
                   track_nulls: bool,
                   out: Optional[np.ndarray] = None) -> np.ndarray:
    """[n, K+1(+1)] multi-hot block for set/list columns; empty collection
    counts as null."""
    n = len(row_values)
    k = len(vocab)
    width = k + 1 + (1 if track_nulls else 0)
    block = out if out is not None else np.zeros((n, width), dtype=VEC_DTYPE)
    flat, rows, lengths = flatten_ragged(row_values)
    if flat:
        codes, _ = string_codes(flat, vocab)
        block[rows, codes] = 1.0          # multi-hot: assignment dedupes
    if track_nulls:
        block[lengths == 0, k + 1] = 1.0
    return block


def hashed_count_block(row_tokens: Sequence[Sequence[str]], num_features: int,
                       seed: int, binary_freq: bool,
                       out: Optional[np.ndarray] = None,
                       col_offset: int = 0
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Hashing-trick counts: [n, num_features] bucket counts + [n] null
    (empty-token-list) mask. Tokens are hashed once per UNIQUE token.

    ``out``/``col_offset`` let shared-hash-space callers accumulate several
    features into one block.

    The scatter is sparse: unique (row, bucket) pairs + multiplicities via
    one sort over the ~nnz flat tokens, then a single fancy-indexed
    accumulate. Work is O(nnz log nnz), never O(n * num_features) — both
    ``np.add.at`` (per-element dispatch) and dense ``np.bincount``
    transients were 20-100x slower at the 200k-row scale on one host core.
    """
    n = len(row_tokens)
    flat, rows, lengths = flatten_ragged(row_tokens)
    return hashed_count_flat(flat, rows, lengths == 0, n, num_features,
                             seed, binary_freq, out=out,
                             col_offset=col_offset)


def hashed_text_block(values: Sequence[Optional[str]], num_features: int,
                      seed: int, binary_freq: bool,
                      out: np.ndarray, col_offset: int = 0) -> np.ndarray:
    """Free-text column → hashed token counts, written in place into
    ``out[:, col_offset:col_offset+num_features]``. Returns the [n] null
    mask (f32).

    Fast path: the fused C++ tokenize+hash+scatter kernel
    (``native/fasthash.cc tokenized_hash_counts``) streams every string
    once — tokens are ASCII runs of ``[\\w']`` lowercased in place,
    bit-exact with ``tokenize_simple`` + murmur3 for ASCII text; rows
    containing non-ASCII bytes are flagged by the kernel and re-done
    here through the exact unicode-aware Python tokenizer. At 300k rows
    this replaces ~10 s of re.findall/list/np.unique host work per
    transform with a ~0.3 s pass. Fallback (no native lib): tokenize
    per UNIQUE value, then one bulk hashed scatter."""
    import ctypes

    from .hashing import _load_native
    from .text import _MIN_TOKEN_LENGTH, tokenize_simple

    n = len(values)
    null_mask = np.fromiter((v is None for v in values), bool, count=n)
    lib = _load_native()
    kern = getattr(lib, "tokenized_hash_counts", None) if lib else None
    if kern is not None and out.flags.c_contiguous \
            and out.dtype == np.float32:
        encoded = [b"" if v is None else v.encode("utf-8") for v in values]
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        blob = b"".join(encoded)
        flags = np.zeros(n, dtype=np.uint8)
        import os
        n_threads = min(os.cpu_count() or 1, 16)
        # min_token_len threads the tokenizer module's constant through —
        # the native kernel and the Python fallback (tokenize_simple's
        # default, same constant) must tokenize in lockstep
        kern(blob,
             offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
             n, np.uint32(seed), np.uint32(num_features),
             np.int32(_MIN_TOKEN_LENGTH),
             1 if binary_freq else 0,
             out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
             out.shape[1], col_offset,
             flags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
             n_threads)
        redo = np.nonzero(flags)[0]
        if redo.size:
            # exact Python tokenizer for the non-ASCII rows only
            from .hashing import hash_tokens
            region = out[:, col_offset:col_offset + num_features]
            for i in redo:
                toks = tokenize_simple(values[i])
                if not toks:
                    continue
                buckets = (hash_tokens(toks, seed)
                           % np.uint32(num_features)).astype(np.int64)
                if binary_freq:
                    region[i, buckets] = 1.0
                else:
                    np.add.at(region[i], buckets, 1.0)
        return np.asarray(null_mask, VEC_DTYPE)

    # fallback: tokenize per UNIQUE text (short fields repeat plenty),
    # then one bulk hashed scatter
    vals = np.array([v if v is not None else "" for v in values],
                    dtype=object)
    uniq, inv = _unique_object(vals, return_inverse=True)
    toks = [tokenize_simple(u) for u in uniq.tolist()]
    row_tokens = [[] if null_mask[r] else toks[i]
                  for r, i in enumerate(inv)]
    hashed_count_block(row_tokens, num_features, seed, binary_freq,
                       out=out, col_offset=col_offset)
    return np.asarray(null_mask, VEC_DTYPE)


def hashed_count_flat(flat: Sequence[str], rows: np.ndarray,
                      null_mask: np.ndarray, n: int, num_features: int,
                      seed: int, binary_freq: bool,
                      out: Optional[np.ndarray] = None,
                      col_offset: int = 0
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Core of :func:`hashed_count_block` for callers that already have the
    flat token list + row index (e.g. a Text column, whose tokens are just
    its non-null values — no need to build n singleton lists)."""
    from .hashing import hash_tokens

    counts = out if out is not None else np.zeros((n, num_features),
                                                  dtype=VEC_DTYPE)
    if len(flat):
        uniq, inv = _unique_object(np.asarray(flat, dtype=object),
                                   return_inverse=True)
        buckets = (hash_tokens(list(uniq), seed)
                   % np.uint32(num_features)).astype(np.int64)[inv]
        pair = rows * np.int64(num_features) + buckets
        upair, mult = np.unique(pair, return_counts=True)
        r = upair // num_features
        b = upair % num_features
        region = counts[:, col_offset:col_offset + num_features]
        if binary_freq:
            # assignment semantics: idempotent across repeated tokens AND
            # across features sharing a hash space
            region[r, b] = 1.0
        else:
            region[r, b] += mult
    return counts, np.asarray(null_mask, VEC_DTYPE)
