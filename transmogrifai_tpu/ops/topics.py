"""Topic and embedding models — OpLDA and OpWord2Vec, TPU-native.

Parity targets:

* ``OpLDA`` (``core/.../impl/feature/OpLDA.scala``): wraps Spark MLlib LDA
  over token-count vectors → per-document topic distribution. Here LDA is
  fitted directly with variational multiplicative EM updates — two dense
  matmuls per iteration under ``lax.fori_loop``, so the whole fit is one
  jitted XLA computation (MXU-shaped, unlike the reference's driver-side
  Gibbs/EM over RDDs).
* ``OpWord2Vec`` (``OpWord2Vec.scala``): wraps Spark Word2Vec; transform is
  the average of token embeddings. Here a compact skip-gram
  negative-sampling model trains in JAX (one jitted epoch over batched
  center/context pairs), and transform averages learned vectors.

Both keep fitted state as dense arrays → save/load via the standard npz
path.
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..columns import Column, ColumnStore, TextListColumn, VectorColumn
from ..stages.base import (Estimator, FittedModel, FixedArity, InputSpec,
                           register_stage)
from ..types.feature_types import OPVector, TextList
from ..vector_metadata import VectorColumnMetadata, VectorMetadata

__all__ = ["OpLDA", "LDAModel", "OpWord2Vec", "Word2VecModel"]


# ---------------------------------------------------------------------------
# LDA
# ---------------------------------------------------------------------------

@jax.jit
def _lda_em(X, beta0, n_iter: int = 60, alpha: float = 1.1):
    """Variational multiplicative EM: X [n, V] counts, beta [K, V] topics.
    Returns (beta, theta [n, K])."""
    n, V = X.shape
    K = beta0.shape[0]
    theta0 = jnp.full((n, K), 1.0 / K)

    def step(_i, carry):
        beta, theta = carry
        # E: responsibilities via current params; M: multiplicative updates
        # (KL-NMF equivalence of variational LDA)
        mix = theta @ beta                        # [n, V]
        ratio = X / jnp.maximum(mix, 1e-12)       # [n, V]
        theta_new = theta * (ratio @ beta.T) + (alpha - 1.0)
        theta_new = jnp.maximum(theta_new, 1e-12)
        theta_new = theta_new / theta_new.sum(axis=1, keepdims=True)
        beta_new = beta * (theta.T @ ratio)
        beta_new = jnp.maximum(beta_new, 1e-12)
        beta_new = beta_new / beta_new.sum(axis=1, keepdims=True)
        return beta_new, theta_new

    return lax.fori_loop(0, n_iter, step, (beta0, theta0))


@jax.jit
def _lda_infer(Xd, beta, n_iter):
    """Infer doc-topic theta for a fixed beta (module-level jit so repeated
    scoring reuses the compiled program)."""
    n = Xd.shape[0]
    K = beta.shape[0]
    theta = jnp.full((n, K), 1.0 / K)

    def step(_i, th):
        mix = th @ beta
        ratio = Xd / jnp.maximum(mix, 1e-12)
        th2 = th * (ratio @ beta.T)
        th2 = jnp.maximum(th2, 1e-12)
        return th2 / th2.sum(axis=1, keepdims=True)
    return lax.fori_loop(0, n_iter, step, theta)


@register_stage
class LDAModel(FittedModel):
    """Fitted topics: vocab + beta [K, V]; transform infers theta per doc."""

    operation_name = "lda"
    output_type = OPVector

    def __init__(self, vocab: Sequence[str] = (),
                 beta: Optional[np.ndarray] = None,
                 n_infer_iter: int = 30, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vocab = list(vocab)
        self.beta = np.asarray(beta) if beta is not None else None
        self.n_infer_iter = n_infer_iter

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(TextList)

    def _counts(self, col) -> np.ndarray:
        index = {t: i for i, t in enumerate(self.vocab)}
        X = np.zeros((len(col), len(self.vocab)))
        for r, toks in enumerate(col.values):
            for t in toks:
                j = index.get(t)
                if j is not None:
                    X[r, j] += 1.0
        return X

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        K = self.beta.shape[0]
        if not self.vocab:
            theta = np.full((len(col), K), 1.0 / K)
        else:
            X = self._counts(col)
            theta = np.asarray(
                _lda_infer(jnp.asarray(X), jnp.asarray(self.beta),
                           self.n_infer_iter), dtype=np.float64)
        meta = VectorMetadata(self.output_name, [
            VectorColumnMetadata(
                parent_feature_name=self.input_features[0].name,
                parent_feature_type="TextList",
                descriptor_value=f"topic_{k}") for k in range(K)])
        return VectorColumn(OPVector, theta, meta)

    def get_model_state(self) -> Dict[str, Any]:
        return {"vocab": self.vocab, "beta": self.beta}


@register_stage
class OpLDA(Estimator):
    """Estimator(TextList) → per-doc topic distribution OPVector."""

    operation_name = "lda"
    output_type = OPVector

    def __init__(self, n_topics: int = 10, vocab_size: int = 1024,
                 n_iter: int = 60, seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.n_topics = n_topics
        self.vocab_size = vocab_size
        self.n_iter = n_iter
        self.seed = seed

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(TextList)

    def fit_columns(self, store: ColumnStore) -> LDAModel:
        col = store[self.input_features[0].name]
        df: Counter = Counter()
        for toks in col.values:
            df.update(toks)
        vocab = [t for t, _c in sorted(df.items(),
                                       key=lambda kv: (-kv[1], kv[0]))
                 [:self.vocab_size]]
        if not vocab:    # all-empty corpus: uniform-topic degenerate model
            return LDAModel(vocab=[],
                            beta=np.zeros((self.n_topics, 0)))
        model = LDAModel(vocab=vocab,
                         beta=np.zeros((self.n_topics, len(vocab))))
        model.input_features = self.input_features   # for _counts
        X = model._counts(col)
        rng = np.random.default_rng(self.seed)
        beta0 = rng.random((self.n_topics, len(vocab))) + 0.5
        beta0 /= beta0.sum(axis=1, keepdims=True)
        beta, _theta = _lda_em(jnp.asarray(X), jnp.asarray(beta0),
                               self.n_iter)
        model.beta = np.asarray(beta, dtype=np.float64)
        return model


# ---------------------------------------------------------------------------
# Word2Vec (skip-gram negative sampling)
# ---------------------------------------------------------------------------

@register_stage
class Word2VecModel(FittedModel):
    """Fitted embeddings: vocab + vectors [V, D]; transform = mean of a
    doc's token vectors (Spark Word2VecModel.transform semantics)."""

    operation_name = "w2v"
    output_type = OPVector

    def __init__(self, vocab: Sequence[str] = (),
                 vectors: Optional[np.ndarray] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vocab = list(vocab)
        self.vectors = np.asarray(vectors) if vectors is not None else None

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(TextList)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        index = {t: i for i, t in enumerate(self.vocab)}
        D = self.vectors.shape[1]
        out = np.zeros((len(col), D))
        for r, toks in enumerate(col.values):
            idx = [index[t] for t in toks if t in index]
            if idx:
                out[r] = self.vectors[idx].mean(axis=0)
        meta = VectorMetadata(self.output_name, [
            VectorColumnMetadata(
                parent_feature_name=self.input_features[0].name,
                parent_feature_type="TextList",
                descriptor_value=f"w2v_{d}") for d in range(D)])
        return VectorColumn(OPVector, out, meta)

    def get_model_state(self) -> Dict[str, Any]:
        return {"vocab": self.vocab, "vectors": self.vectors}


@register_stage
class OpWord2Vec(Estimator):
    """Estimator(TextList) → averaged skip-gram embeddings OPVector."""

    operation_name = "w2v"
    output_type = OPVector

    def __init__(self, dim: int = 100, window: int = 5, epochs: int = 100,
                 neg_samples: int = 5, lr: float = 0.5,
                 vocab_size: int = 65536, min_count: int = 5,
                 subsample_t: float = 1e-3,
                 seed: int = 42, uid: Optional[str] = None):
        # dim/window/min_count match Spark ml Word2Vec's defaults
        # (vectorSize=100, windowSize=5, minCount=5 — the estimator
        # OpWord2Vec wraps in the reference). NB: one "epoch" is one
        # FULL-BATCH gradient step over every skip-gram pair (the whole
        # update is a fused jitted scan), so the defaults are GD-scale
        # (many steps, large lr), not SGD-scale.
        super().__init__(uid=uid)
        self.dim = dim
        self.window = window
        self.epochs = epochs
        self.neg_samples = neg_samples
        self.lr = lr
        self.vocab_size = vocab_size
        self.min_count = min_count
        #: frequent-word subsampling threshold (word2vec's t; 0 disables):
        #: tokens with frequency f are kept with prob sqrt(t/f) (+ t/f)
        self.subsample_t = subsample_t
        self.seed = seed

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(TextList)

    def fit_columns(self, store: ColumnStore) -> Word2VecModel:
        col = store[self.input_features[0].name]
        counts: Counter = Counter()
        for toks in col.values:
            counts.update(toks)
        vocab = [t for t, c in sorted(counts.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c >= self.min_count][:self.vocab_size]
        index = {t: i for i, t in enumerate(vocab)}
        V = len(vocab)
        rng = np.random.default_rng(self.seed)
        if V == 0:
            return Word2VecModel(vocab=[], vectors=np.zeros((0, self.dim)))

        # frequent-word subsampling (word2vec's t-schedule): discard
        # tokens of very frequent words with prob 1 - (sqrt(t/f) + t/f)
        total_tokens = float(sum(counts[t] for t in vocab)) or 1.0
        keep_p = np.ones((V,))
        if self.subsample_t > 0:
            freq = np.array([counts[t] / total_tokens for t in vocab])
            with np.errstate(divide="ignore"):
                keep_p = np.minimum(
                    np.sqrt(self.subsample_t / freq)
                    + self.subsample_t / freq, 1.0)

        # host: materialize (center, context) pairs once
        centers: List[int] = []
        contexts: List[int] = []
        for toks in col.values:
            ids = [index[t] for t in toks if t in index]
            if self.subsample_t > 0 and ids:
                kept = rng.random(len(ids)) < keep_p[ids]
                ids = [i for i, k in zip(ids, kept) if k]
            for i, c in enumerate(ids):
                lo = max(0, i - self.window)
                for j in range(lo, min(len(ids), i + self.window + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            return Word2VecModel(vocab=vocab,
                                 vectors=rng.normal(0, 0.1, (V, self.dim)))
        cen = jnp.asarray(np.array(centers, dtype=np.int32))
        ctx = jnp.asarray(np.array(contexts, dtype=np.int32))
        n_pairs = len(centers)

        W0 = jnp.asarray(rng.normal(0, 0.1, (V, self.dim)))
        C0 = jnp.asarray(rng.normal(0, 0.1, (V, self.dim)))
        lr = self.lr
        S = self.neg_samples
        key0 = jax.random.PRNGKey(self.seed)
        # word2vec's unigram^0.75 negative-sampling distribution
        uni = np.array([counts[t] for t in vocab], dtype=np.float64) ** 0.75
        neg_logits = jnp.asarray(np.log(uni / uni.sum()), jnp.float32)

        @jax.jit
        def train(W, C):
            def epoch(carry, e):
                W, C = carry
                # negatives sampled in-loop: memory stays one epoch's worth
                neg_e = jax.random.categorical(
                    jax.random.fold_in(key0, e), neg_logits,
                    shape=(n_pairs, S))

                def loss_fn(params):
                    W_, C_ = params
                    w = W_[cen]                        # [P, D]
                    pos = jnp.sum(w * C_[ctx], axis=1)
                    nv = C_[neg_e]                     # [P, S, D]
                    negs = jnp.einsum("pd,psd->ps", w, nv)
                    return -(jnp.mean(jax.nn.log_sigmoid(pos))
                             + jnp.mean(jax.nn.log_sigmoid(-negs)))
                g = jax.grad(loss_fn)((W, C))
                return (W - lr * g[0], C - lr * g[1]), None
            (W, C), _ = lax.scan(epoch, (W, C),
                                 jnp.arange(self.epochs))
            # (input + context)/2: co-occurrence is trained on W·C cross
            # terms, so the averaged embedding makes co-occurring tokens
            # neighbors (standard SGNS practice)
            return 0.5 * (W + C)
        W = train(W0, C0)
        return Word2VecModel(vocab=vocab,
                             vectors=np.asarray(W, dtype=np.float64))
