"""Transmogrifier — automatic feature engineering dispatch.

Parity: ``core/.../impl/feature/Transmogrifier.scala:92-348``: groups raw
features by type and applies each type's default vectorizer, then combines
all blocks into one OPVector. ``transmogrify(features)`` is the one-call
automated feature engineering entry (RichFeaturesCollection.transmogrify).

Type dispatch (mirroring the reference's match):

=================================  =======================================
Real/RealNN/Percent/Currency       RealVectorizer (mean impute + null)
Integral                           IntegralVectorizer (mode impute + null)
Binary                             BinaryVectorizer
Date/DateTime                      DateToUnitCircleVectorizer
PickList/ComboBox/Country/State/
City/PostalCode/Street/ID          OneHotVectorizer (topK + OTHER + null)
Text/TextArea/Email/URL/Phone/
Base64                             SmartTextVectorizer (pivot|hash by card.)
MultiPickList                      SetVectorizer
Geolocation                        GeolocationVectorizer
TextList                           HashingVectorizerModel
OPVector                           passthrough
maps                               OPMapVectorizer family (ops.maps)
=================================  =======================================
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from ..features import Feature
from ..types import feature_types as ft
from .dates import DateToUnitCircleVectorizer, TimePeriod
from .geo import GeolocationVectorizer
from .hashing import HashingVectorizerModel
from .numeric import BinaryVectorizer, IntegralVectorizer, RealVectorizer
from .onehot import OneHotVectorizer, SetVectorizer
from .smart_text import SmartTextVectorizer
from .vectorizer_base import TransmogrifierDefaults
from .vectors import VectorsCombiner

__all__ = ["transmogrify", "Transmogrifier"]

# pivot (one-hot) text subtypes: closed-ish vocabularies
_PIVOT_TEXT = (ft.PickList, ft.ComboBox, ft.Country, ft.State, ft.City,
               ft.PostalCode, ft.Street, ft.ID)
# free text → smart vectorization
_SMART_TEXT = (ft.Text,)


def _group_features(features: Sequence[Feature]) -> Dict[str, List[Feature]]:
    groups: Dict[str, List[Feature]] = {}

    def add(key: str, f: Feature) -> None:
        groups.setdefault(key, []).append(f)

    for f in features:
        t = f.ftype
        if issubclass(t, ft.Binary):
            add("binary", f)
        elif issubclass(t, ft.Date):  # Date/DateTime before Integral
            add("date", f)
        elif issubclass(t, ft.Integral):
            add("integral", f)
        elif issubclass(t, ft.Real):  # Real, RealNN, Percent, Currency
            add("real", f)
        elif issubclass(t, ft.MultiPickList):
            add("set", f)
        elif issubclass(t, _PIVOT_TEXT):
            add("pivot_text", f)
        elif issubclass(t, ft.Text):
            add("smart_text", f)
        elif issubclass(t, ft.Geolocation):
            add("geo", f)
        elif issubclass(t, ft.TextList):
            add("text_list", f)
        elif issubclass(t, ft.OPVector):
            add("vector", f)
        elif issubclass(t, (ft.DateList,)):
            add("date_list", f)
        elif issubclass(t, ft.OPMap):
            add("map", f)
        else:
            raise TypeError(
                f"Transmogrifier has no default vectorizer for {t.__name__}")
    return groups


class Transmogrifier:
    """Type-dispatch table (Transmogrifier.scala:92)."""

    @staticmethod
    def vectorize(features: Sequence[Feature],
                  defaults: Type[TransmogrifierDefaults] = TransmogrifierDefaults
                  ) -> Feature:
        if not features:
            raise ValueError("transmogrify needs at least one feature")
        groups = _group_features(features)
        blocks: List[Feature] = []

        def wire(stage, feats) -> None:
            blocks.append(feats[0].transform_with(stage, *feats[1:]))

        if "real" in groups:
            wire(RealVectorizer(track_nulls=defaults.TRACK_NULLS,
                                fill_with_mean=defaults.FILL_WITH_MEAN,
                                fill_value=defaults.FILL_VALUE),
                 groups["real"])
        if "integral" in groups:
            wire(IntegralVectorizer(track_nulls=defaults.TRACK_NULLS,
                                    fill_with_mode=defaults.FILL_WITH_MODE,
                                    fill_value=defaults.FILL_VALUE),
                 groups["integral"])
        if "binary" in groups:
            wire(BinaryVectorizer(
                track_nulls=defaults.TRACK_NULLS,
                fill_value=defaults.BINARY_FILL_VALUE),
                 groups["binary"])
        if "date" in groups:
            wire(DateToUnitCircleVectorizer(
                periods=defaults.CIRCULAR_DATE_REPRESENTATIONS,
                track_nulls=defaults.TRACK_NULLS,
                input_names=[f.name for f in groups["date"]]), groups["date"])
        if "pivot_text" in groups:
            wire(OneHotVectorizer(top_k=defaults.TOP_K,
                                  min_support=defaults.MIN_SUPPORT,
                                  track_nulls=defaults.TRACK_NULLS),
                 groups["pivot_text"])
        if "smart_text" in groups:
            wire(SmartTextVectorizer(top_k=defaults.TOP_K,
                                     min_support=defaults.MIN_SUPPORT,
                                     num_features=defaults.HASH_SIZE,
                                     track_nulls=defaults.TRACK_NULLS),
                 groups["smart_text"])
        if "set" in groups:
            wire(SetVectorizer(top_k=defaults.TOP_K,
                               min_support=defaults.MIN_SUPPORT,
                               track_nulls=defaults.TRACK_NULLS), groups["set"])
        if "geo" in groups:
            wire(GeolocationVectorizer(track_nulls=defaults.TRACK_NULLS),
                 groups["geo"])
        if "text_list" in groups:
            wire(HashingVectorizerModel(
                num_features=defaults.HASH_SIZE,
                track_nulls=defaults.TRACK_NULLS,
                input_names=[f.name for f in groups["text_list"]]),
                groups["text_list"])
        if "map" in groups:
            from .maps import vectorize_maps
            blocks.extend(vectorize_maps(groups["map"], defaults))
        if "date_list" in groups:
            from .date_list import DateListVectorizer
            wire(DateListVectorizer(track_nulls=defaults.TRACK_NULLS),
                 groups["date_list"])
        blocks.extend(groups.get("vector", []))

        if len(blocks) == 1:
            return blocks[0]
        combiner = VectorsCombiner()
        return blocks[0].transform_with(combiner, *blocks[1:])


def transmogrify(features: Sequence[Feature]) -> Feature:
    """One-call automated feature engineering: features → single OPVector."""
    return Transmogrifier.vectorize(features)
