"""Vectorizer protocol — host/device split for fusable transforms.

Every vectorizer model separates its transform into:

* ``host_prepare(store) -> {name: np.ndarray}`` — string lookups, vocab
  indexing, hashing: anything that must touch host objects. Produces only
  dense numeric arrays (+ masks).
* ``device_compute(xp, prepared) -> xp.ndarray [n, d]`` — pure array math,
  written against the ``xp`` namespace so the same code runs as numpy on
  host or inside a jitted XLA computation (``xp = jax.numpy``).

The contract is **f32-native**: every prepared block is canonicalized
(``canonicalize_prepared``) to the dtypes jit sees with x64 off — f64→f32,
i64→i32 — BEFORE ``device_compute`` on BOTH the numpy and the fused-jit
path, so the two paths compute on bit-identical inputs and can never
drift. The flip side is a contract obligation on ``host_prepare``: any
quantity whose magnitude defeats f32 (epoch milliseconds, row counts ≥2³¹)
must be reduced on host in f64 first (see dates.py: period angles, not raw
timestamps, cross the boundary).

This is the TPU answer to ``FitStagesUtil.applyOpTransformations``'s row
fusion (``core/.../utils/stages/FitStagesUtil.scala:96-119``): the workflow
can jit ONE function per DAG layer that runs every vectorizer's
``device_compute`` and concatenates the results — a single fused XLA
computation per layer instead of a per-row RDD map.

All vectorizers are sequence stages (N same-typed inputs → one OPVector),
mirroring the reference's ``SequenceEstimator`` vectorizers.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..columns import Column, ColumnStore, VectorColumn
from ..stages.base import (Estimator, FittedModel, InputSpec, Transformer,
                           VarArity)
from ..types.feature_types import FeatureType, OPVector
from ..vector_metadata import VectorColumnMetadata, VectorMetadata

__all__ = ["VectorizerModel", "VectorizerEstimator", "TransmogrifierDefaults",
           "canonicalize_prepared", "VEC_DTYPE", "vec_dtype_round"]

#: dtype of the vector pipeline: f32 end-to-end (TPU-native; MXU/VPU run
#: f32/bf16 — f64 would be emulated and silently downcast under jit anyway)
VEC_DTYPE = np.float32


def canonicalize_prepared(prepared: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Cast prepared blocks to the dtypes jit produces under x64-off.

    f64→f32, i64→i32, u64→u32; bools and narrower types pass through.
    Applying this on the host path too makes numpy and fused-jit transforms
    bit-identical for elementwise work (the x64 gate this replaces existed
    only because the two paths used to see different dtypes)."""
    out = {}
    for k, v in prepared.items():
        a = np.asarray(v)
        if a.dtype == np.float64:
            a = a.astype(VEC_DTYPE)
        elif a.dtype == np.int64:
            # host_prepare's contract forbids magnitudes beyond int32 —
            # enforce it (ADVICE r3): a vectorizer passing raw epoch-millis
            # would otherwise wrap silently and corrupt values downstream
            if a.size and (a.max(initial=0) > np.iinfo(np.int32).max
                           or a.min(initial=0) < np.iinfo(np.int32).min):
                raise ValueError(
                    f"prepared block {k!r} holds int64 values outside the "
                    "int32 range; host_prepare must pre-scale them "
                    "(e.g. epoch-millis → coarser units) before canonical "
                    "casting")
            a = a.astype(np.int32)
        elif a.dtype == np.uint64:
            if a.size and a.max(initial=0) > np.iinfo(np.uint32).max:
                raise ValueError(
                    f"prepared block {k!r} holds uint64 values outside the "
                    "uint32 range; host_prepare must pre-scale them before "
                    "canonical casting")
            a = a.astype(np.uint32)
        out[k] = a
    return out


def vec_dtype_round(values) -> "np.ndarray":
    """Round fitted f64 constants (bucket edges, fill values) through the
    pipeline dtype ONCE at fit time, so fit-time decisions and transform-time
    comparisons see exactly the same numbers."""
    return np.asarray(values, dtype=VEC_DTYPE).astype(np.float64)


class TransmogrifierDefaults:
    """Default knobs (core/.../impl/feature/Transmogrifier.scala:52-88)."""

    TOP_K = 20
    MIN_SUPPORT = 10
    FILL_VALUE = 0.0
    BINARY_FILL_VALUE = 0.0
    HASH_SIZE = 512  # DefaultNumOfFeatures
    MAX_NUM_FEATURES = 16384
    FILL_WITH_MEAN = True
    FILL_WITH_MODE = True
    TRACK_NULLS = True
    TRACK_INVALID = False
    MIN_DOC_FREQUENCY = 0
    OTHER_STRING = "OTHER"
    NULL_STRING = "NullIndicatorValue"
    CIRCULAR_DATE_REPRESENTATIONS = ["HourOfDay", "DayOfWeek", "DayOfMonth",
                                     "DayOfYear"]


class VectorizerModel(FittedModel):
    """Fitted vectorizer: N typed inputs → OPVector via host/device split."""

    output_type = OPVector
    seq_type: Type[FeatureType] = FeatureType

    @property
    def input_spec(self) -> InputSpec:
        return VarArity(self.seq_type)

    # -- protocol ----------------------------------------------------------
    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def device_compute(self, xp, prepared: Dict[str, Any]):
        raise NotImplementedError

    def vector_metadata(self) -> VectorMetadata:
        raise NotImplementedError

    # -- Transformer impl --------------------------------------------------
    def transform_columns(self, store: ColumnStore) -> Column:
        prepared = canonicalize_prepared(self.host_prepare(store))
        mat = self.device_compute(np, prepared)
        # store the pipeline dtype (f32): device_compute already ran on
        # f32-canonicalized inputs, so an f64 copy holds no extra
        # information — it only doubled every downstream copy/transfer
        # (a [300k, 550] layer is 660 MB in f32, 1.3 GB in f64)
        mat = np.asarray(mat, dtype=VEC_DTYPE)
        meta = self.vector_metadata()
        assert mat.ndim == 2 and mat.shape[1] == meta.size, \
            (type(self).__name__, mat.shape, meta.size)
        return VectorColumn(OPVector, mat, meta)

    @property
    def width(self) -> int:
        return self.vector_metadata().size

    @property
    def meta_name(self) -> str:
        """Vector metadata name; falls back to the operation when the model
        is used as an unwired delegate (map vectorizers)."""
        try:
            return self.output_name
        except ValueError:
            return self.operation_name

    def get_model_state(self) -> Dict[str, Any]:
        return {}


class VectorizerEstimator(Estimator):
    """Base sequence estimator for vectorizers."""

    output_type = OPVector
    seq_type: Type[FeatureType] = FeatureType

    @property
    def input_spec(self) -> InputSpec:
        return VarArity(self.seq_type)

    @property
    def input_names(self) -> List[str]:
        return [f.name for f in self.input_features]


def null_indicator_meta(feature_name: str, ftype_name: str,
                        grouping: Optional[str] = None) -> VectorColumnMetadata:
    from ..vector_metadata import NULL_INDICATOR
    return VectorColumnMetadata(
        parent_feature_name=feature_name, parent_feature_type=ftype_name,
        grouping=grouping, indicator_value=NULL_INDICATOR)
