"""DecisionTreeNumericBucketizer — label-aware numeric bucketization.

Parity: ``core/.../impl/feature/DecisionTreeNumericBucketizer.scala`` (:300
defaults — Gini, MaxDepth 5, MaxBins 32, MinInstancesPerNode 1,
MinInfoGain 0.01) and ``DecisionTreeNumericMapBucketizer.scala:170``.

The reference trains a single-feature Spark decision tree and uses its
split thresholds as bucket edges, gated on MinInfoGain. Here the 1-D tree
is fitted exactly with vectorized prefix-sum Gini gains over quantile
candidate thresholds — no tree library needed, one sort + cumsum per node.
The fitted model reuses :class:`NumericBucketizerModel` one-hot semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columns import ColumnStore, MapColumn, NumericColumn
from ..stages.base import (AllowLabelAsInput, Estimator, FixedArity,
                           InputSpec, register_stage)
from ..types.feature_types import OPNumeric, OPVector, RealMap, RealNN
from .numeric import NumericBucketizerModel
from .vectorizer_base import TransmogrifierDefaults


def map_child_numeric(mcol: MapColumn, key: str):
    """(values, mask) of one map key's numeric child (absent key → all-null)."""
    child = mcol.children.get(key)
    if child is None:
        n = len(mcol)
        return np.zeros(n), np.zeros(n, dtype=bool)
    return child.values.astype(np.float64), child.mask.copy()

__all__ = ["DecisionTreeNumericBucketizer", "DecisionTreeNumericMapBucketizer",
           "find_dt_splits"]

# defaults (DecisionTreeNumericBucketizer.scala:293-300)
MAX_DEPTH = 5
MAX_BINS = 32
MIN_INSTANCES_PER_NODE = 1
MIN_INFO_GAIN = 0.01


def _gini(counts: np.ndarray) -> np.ndarray:
    """Gini impurity of class-count vectors (… , K) → (…)."""
    tot = counts.sum(axis=-1, keepdims=True)
    with np.errstate(divide="ignore", invalid="ignore"):
        p = np.where(tot > 0, counts / tot, 0.0)
    return 1.0 - (p * p).sum(axis=-1)


def find_dt_splits(x: np.ndarray, y: np.ndarray,
                   max_depth: int = MAX_DEPTH, max_bins: int = MAX_BINS,
                   min_instances: int = MIN_INSTANCES_PER_NODE,
                   min_info_gain: float = MIN_INFO_GAIN) -> List[float]:
    """Split thresholds of an exact 1-D Gini decision tree on (x, y)."""
    classes, y_idx = np.unique(y, return_inverse=True)
    K = len(classes)
    if K < 2 or x.size == 0:
        return []
    order = np.argsort(x, kind="stable")
    xs, ys = x[order], y_idx[order]
    onehot = np.eye(K)[ys]                      # [n, K]

    # candidate thresholds: quantile-binned midpoints (MaxBins cap)
    uniq = np.unique(xs)
    if uniq.size < 2:
        return []
    mids = (uniq[:-1] + uniq[1:]) / 2.0
    if mids.size > max_bins - 1:
        mids = np.quantile(mids, np.linspace(0, 1, max_bins - 1))
        mids = np.unique(mids)

    thresholds: List[float] = []

    def grow(lo: int, hi: int, depth: int) -> None:
        if depth >= max_depth or hi - lo < 2 * min_instances:
            return
        seg_x = xs[lo:hi]
        cum = np.cumsum(onehot[lo:hi], axis=0)     # [m, K]
        total = cum[-1]
        n_tot = hi - lo
        parent = _gini(total[None, :])[0]
        # left counts at each candidate: rows with x <= t
        left_n = np.searchsorted(seg_x, mids, side="right")
        valid = (left_n >= min_instances) & (n_tot - left_n >= min_instances)
        if not valid.any():
            return
        left_counts = np.where(
            (left_n > 0)[:, None], cum[np.maximum(left_n - 1, 0)], 0.0)
        right_counts = total[None, :] - left_counts
        gain = parent - (left_n / n_tot) * _gini(left_counts) \
            - ((n_tot - left_n) / n_tot) * _gini(right_counts)
        gain = np.where(valid, gain, -np.inf)
        best = int(np.argmax(gain))
        if gain[best] < min_info_gain:
            return
        t = float(mids[best])
        thresholds.append(t)
        mid = lo + int(left_n[best])
        grow(lo, mid, depth + 1)
        grow(mid, hi, depth + 1)

    grow(0, len(xs), 0)
    return sorted(thresholds)


@register_stage
class DecisionTreeNumericBucketizer(Estimator, AllowLabelAsInput):
    """Estimator(label RealNN, numeric) → one-hot buckets at DT splits."""

    operation_name = "dtBucketize"
    output_type = OPVector

    def __init__(self, max_depth: int = MAX_DEPTH, max_bins: int = MAX_BINS,
                 min_instances_per_node: int = MIN_INSTANCES_PER_NODE,
                 min_info_gain: float = MIN_INFO_GAIN,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 track_invalid: bool = TransmogrifierDefaults.TRACK_INVALID,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, OPNumeric)

    def _splits_for(self, x: np.ndarray, mask: np.ndarray,
                    y: np.ndarray) -> List[float]:
        present = mask & np.isfinite(x)
        thr = find_dt_splits(
            x[present], y[present], max_depth=self.max_depth,
            max_bins=self.max_bins,
            min_instances=self.min_instances_per_node,
            min_info_gain=self.min_info_gain)
        return [-np.inf] + thr + [np.inf]

    def fit_columns(self, store: ColumnStore) -> NumericBucketizerModel:
        ycol = store[self.input_features[0].name]
        xcol = store[self.input_features[1].name]
        assert isinstance(xcol, NumericColumn)
        y = ycol.values.astype(np.float64)
        splits = self._splits_for(xcol.values.astype(np.float64),
                                  xcol.mask, y)
        model = NumericBucketizerModel(
            splits=[splits], track_nulls=self.track_nulls,
            track_invalid=self.track_invalid,
            input_names=[self.input_features[1].name],
            ftype_name=xcol.ftype.__name__)
        # the model transforms only the numeric input (label not needed)
        model._bucket_input = self.input_features[1].name
        return model

    def fit(self, store: ColumnStore):
        model = super().fit(store)
        # rebind the fitted model to the numeric input only: bucket transform
        # must not require the label at scoring time
        model.input_features = (self.input_features[1],)
        return model


@register_stage
class DecisionTreeNumericMapBucketizer(DecisionTreeNumericBucketizer):
    """Same per map key (DecisionTreeNumericMapBucketizer.scala:170)."""

    operation_name = "dtMapBucketize"

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(RealNN, RealMap)

    def fit_columns(self, store: ColumnStore) -> NumericBucketizerModel:
        ycol = store[self.input_features[0].name]
        mcol = store[self.input_features[1].name]
        assert isinstance(mcol, MapColumn)
        y = ycol.values.astype(np.float64)
        names, splits = [], []
        for key in sorted(mcol.children):
            vals, mask = map_child_numeric(mcol, key)
            names.append(key)
            splits.append(self._splits_for(vals, mask, y))
        model = _MapBucketizerModel(
            splits=splits, keys=names, track_nulls=self.track_nulls,
            track_invalid=self.track_invalid,
            input_names=[self.input_features[1].name],
            ftype_name=mcol.ftype.__name__)
        return model


@register_stage
class _MapBucketizerModel(NumericBucketizerModel):
    """Bucketizer over map keys: one split set per key."""

    def __init__(self, splits: Sequence[Sequence[float]] = (),
                 keys: Sequence[str] = (), track_nulls: bool = True,
                 track_invalid: bool = False,
                 input_names: Sequence[str] = (),
                 ftype_name: str = "RealMap", uid: Optional[str] = None):
        super().__init__(splits=splits, track_nulls=track_nulls,
                         track_invalid=track_invalid,
                         input_names=input_names, ftype_name=ftype_name,
                         uid=uid)
        self.keys = list(keys)

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        mcol = store[self._names()[0]]
        assert isinstance(mcol, MapColumn)
        vals, masks = [], []
        for key in self.keys:
            v, m = map_child_numeric(mcol, key)
            vals.append(v)
            masks.append(m)
        return {"values": np.stack(vals, axis=1),
                "mask": np.stack(masks, axis=1)}

    def vector_metadata(self):
        from ..vector_metadata import (VectorColumnMetadata, VectorMetadata,
                                       NULL_INDICATOR)
        name = self._names()[0]
        cols = []
        for key, splits in zip(self.keys, self.splits):
            for b in range(len(splits) - 1):
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name,
                    parent_feature_type=self.ftype_name, grouping=key,
                    indicator_value=f"{splits[b]}-{splits[b + 1]}"))
            if self.track_invalid:
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name,
                    parent_feature_type=self.ftype_name, grouping=key,
                    indicator_value="OutOfBounds"))
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name,
                    parent_feature_type=self.ftype_name, grouping=key,
                    indicator_value=NULL_INDICATOR))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        state = super().get_model_state()
        state["keys"] = self.keys
        return state
