"""Text feature suite — count vectorization, similarity, semantic parsers.

Parity targets (all host-side; outputs are dense arrays / typed columns):

* ``OpCountVectorizer`` (``core/.../impl/feature/OpCountVectorizer.scala``):
  vocabulary-building token count vectorizer (minDF / vocabSize).
* ``NGramSimilarity`` (``NGramSimilarity.scala``): character n-gram cosine
  similarity between two text features.
* ``EmailParser`` / ``RichTextFeature.toEmailPrefix/Domain``
  (``core/.../dsl/RichTextFeature.scala``).
* ``PhoneNumberParser`` (``PhoneNumberParser.scala`` — libphonenumber
  replaced by a table of country calling codes + national length rules).
* URL validation/extraction (``RichTextFeature.toUrlProtocol/Domain``).
* ``MimeTypeDetector`` (``MimeTypeDetector.scala`` — Tika replaced by a
  magic-bytes table over Base64 content).
"""
from __future__ import annotations

import base64
import binascii
import re
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..columns import (Column, ColumnStore, NumericColumn, TextColumn,
                       TextListColumn, VectorColumn)
from ..stages.base import (Estimator, FittedModel, FixedArity, InputSpec,
                           Transformer, register_stage)
from ..types.feature_types import (Base64, Binary, Email, MultiPickList,
                                   OPVector, Phone, Real, Text, TextList,
                                   URL)
from ..vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizer_base import VEC_DTYPE, VectorizerEstimator, VectorizerModel

__all__ = [
    "OpCountVectorizer", "CountVectorizerModel", "NGramSimilarity",
    "NameEntityRecognizer",
    "EmailParser", "PhoneNumberParser", "UrlParser", "MimeTypeDetector",
    "parse_email", "parse_phone", "parse_url", "detect_mime",
]


# ---------------------------------------------------------------------------
# Count vectorizer
# ---------------------------------------------------------------------------

@register_stage
class CountVectorizerModel(VectorizerModel):
    """Token counts over a fitted vocabulary, one block per input."""

    operation_name = "countVec"
    seq_type = TextList

    def __init__(self, vocabs: Sequence[Sequence[str]] = (),
                 binary: bool = False, input_names: Sequence[str] = (),
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vocabs = [list(v) for v in vocabs]
        self.binary = binary
        self.input_names_saved = list(input_names)

    def _names(self) -> List[str]:
        if self.input_features:
            return [f.name for f in self.input_features]
        return self.input_names_saved

    def host_prepare(self, store: ColumnStore) -> Dict[str, np.ndarray]:
        from ._hostvec import flatten_ragged
        names = self._names()
        n = store.n_rows
        widths = [len(v) for v in self.vocabs]
        mat = np.zeros((n, sum(widths)), dtype=VEC_DTYPE)
        off = 0
        for name, vocab in zip(names, self.vocabs):
            col = store[name]
            index = {t: i for i, t in enumerate(vocab)}
            flat, rows, _len = flatten_ragged(col.values)
            if flat:
                codes = np.fromiter((index.get(t, -1) for t in flat),
                                    np.int64, count=len(flat))
                okm = codes >= 0
                pair = rows[okm] * np.int64(len(vocab)) + codes[okm]
                upair, mult = np.unique(pair, return_counts=True)
                r, c = upair // len(vocab), upair % len(vocab)
                if self.binary:
                    mat[r, off + c] = 1.0
                else:
                    mat[r, off + c] += mult
            off += len(vocab)
        return {"mat": mat}

    def device_compute(self, xp, prepared):
        return xp.asarray(prepared["mat"])

    def vector_metadata(self) -> VectorMetadata:
        cols: List[VectorColumnMetadata] = []
        for name, vocab in zip(self._names(), self.vocabs):
            for t in vocab:
                cols.append(VectorColumnMetadata(
                    parent_feature_name=name, parent_feature_type="TextList",
                    grouping=name, indicator_value=t))
        return VectorMetadata(self.meta_name, cols)

    def get_model_state(self):
        return {"vocabs": self.vocabs, "input_names_saved": self._names()}


@register_stage
class OpCountVectorizer(VectorizerEstimator):
    """Estimator(TextList…) → token count OPVector (OpCountVectorizer)."""

    operation_name = "countVec"
    seq_type = TextList

    def __init__(self, vocab_size: int = 512, min_df: int = 1,
                 binary: bool = False, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.vocab_size = vocab_size
        self.min_df = min_df
        self.binary = binary

    def fit_columns(self, store: ColumnStore) -> CountVectorizerModel:
        vocabs = []
        for name in self.input_names:
            col = store[name]
            df: Counter = Counter()
            for toks in col.values:
                for t in set(toks):
                    df[t] += 1
            kept = [(c, t) for t, c in df.items() if c >= self.min_df]
            kept.sort(key=lambda ct: (-ct[0], ct[1]))
            vocabs.append([t for _c, t in kept[:self.vocab_size]])
        return CountVectorizerModel(vocabs=vocabs, binary=self.binary,
                                    input_names=self.input_names)


# ---------------------------------------------------------------------------
# N-gram similarity
# ---------------------------------------------------------------------------

def _char_ngrams(s: str, n: int) -> Counter:
    s = f" {s.lower()} "
    return Counter(s[i:i + n] for i in range(max(len(s) - n + 1, 0)))


@register_stage
class NGramSimilarity(Transformer):
    """(Text, Text) → Real cosine similarity of char n-gram profiles
    (NGramSimilarity.scala; Spark's NGram + cosine distance)."""

    operation_name = "ngramSim"
    output_type = Real

    def __init__(self, n: int = 3, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.n = n

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text, Text)

    def transform_columns(self, store: ColumnStore) -> Column:
        a = store[self.input_features[0].name]
        b = store[self.input_features[1].name]
        n_rows = store.n_rows
        vals = np.zeros(n_rows)
        mask = np.zeros(n_rows, bool)
        for i in range(n_rows):
            va, vb = a.values[i], b.values[i]
            if va is None or vb is None:
                continue
            mask[i] = True
            ca, cb = _char_ngrams(va, self.n), _char_ngrams(vb, self.n)
            dot = sum(c * cb.get(g, 0) for g, c in ca.items())
            na = sum(c * c for c in ca.values()) ** 0.5
            nb = sum(c * c for c in cb.values()) ** 0.5
            vals[i] = dot / (na * nb) if na > 0 and nb > 0 else 0.0
        return NumericColumn(Real, vals, mask)


# ---------------------------------------------------------------------------
# Semantic parsers (email / phone / url / mime)
# ---------------------------------------------------------------------------

_EMAIL_RE = re.compile(
    r"^(?P<prefix>[A-Za-z0-9._%+-]+)@(?P<domain>[A-Za-z0-9.-]+\.[A-Za-z]{2,})$")


def parse_email(value: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """(prefix, domain) or (None, None) when invalid."""
    if not value:
        return None, None
    m = _EMAIL_RE.match(value.strip())
    return (m.group("prefix"), m.group("domain")) if m else (None, None)


#: country calling code → (iso region, min/max national significant digits)
#: (libphonenumber metadata subset; lengths per ITU-T E.164 national plans)
_PHONE_PLANS: Dict[str, Tuple[str, int, int]] = {
    "1": ("US", 10, 10), "44": ("GB", 9, 10), "49": ("DE", 6, 11),
    "33": ("FR", 9, 9), "34": ("ES", 9, 9), "39": ("IT", 8, 11),
    "81": ("JP", 9, 10), "86": ("CN", 10, 11), "91": ("IN", 10, 10),
    "61": ("AU", 9, 9), "55": ("BR", 10, 11), "7": ("RU", 10, 10),
    "52": ("MX", 10, 10), "82": ("KR", 8, 10), "31": ("NL", 9, 9),
}
_REGION_TO_CODE = {r: c for c, (r, _a, _b) in _PHONE_PLANS.items()}


def parse_phone(value: Optional[str], default_region: str = "US"
                ) -> Tuple[bool, Optional[str]]:
    """(is_valid, national digits) — PhoneNumberParser.scala semantics:
    '+'-prefixed numbers resolve their country plan, bare numbers use the
    default region's plan."""
    if not value:
        return False, None
    digits = re.sub(r"[\s().\-]", "", value.strip())
    if digits.startswith("+"):
        rest = digits[1:]
        if not rest.isdigit():
            return False, None
        for cc_len in (3, 2, 1):
            cc = rest[:cc_len]
            if cc in _PHONE_PLANS:
                _region, lo, hi = _PHONE_PLANS[cc]
                nat = rest[cc_len:]
                return (lo <= len(nat) <= hi), (nat or None)
        return False, None
    if not digits.isdigit():
        return False, None
    cc = _REGION_TO_CODE.get(default_region, "1")
    _region, lo, hi = _PHONE_PLANS[cc]
    return (lo <= len(digits) <= hi), digits


_URL_RE = re.compile(
    r"^(?P<protocol>https?|ftp)://(?P<domain>[A-Za-z0-9.-]+\.[A-Za-z]{2,})"
    r"(?P<rest>[/:?#].*)?$")


def parse_url(value: Optional[str]
              ) -> Tuple[Optional[str], Optional[str]]:
    """(protocol, domain) or (None, None) when invalid
    (RichTextFeature.toUrlProtocol/Domain)."""
    if not value:
        return None, None
    m = _URL_RE.match(value.strip())
    return (m.group("protocol"), m.group("domain")) if m else (None, None)


#: magic byte prefixes → mime (Tika replacement table)
_MAGIC: List[Tuple[bytes, str]] = [
    (b"%PDF", "application/pdf"),
    (b"\x89PNG", "image/png"),
    (b"GIF8", "image/gif"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"BM", "image/bmp"),
    (b"ID3", "audio/mpeg"),
    (b"OggS", "audio/ogg"),
    (b"fLaC", "audio/flac"),
    (b"RIFF", "audio/wav"),
    (b"MZ", "application/x-msdownload"),
    (b"%!PS", "application/postscript"),
    (b"<?xml", "application/xml"),
    (b"<html", "text/html"),
    (b"{\\rtf", "application/rtf"),
]


def detect_mime(b64: Optional[str]) -> Optional[str]:
    """Base64 content → mime type via magic bytes; text fallback when the
    payload decodes as UTF-8 (MimeTypeDetector.scala semantics)."""
    if not b64:
        return None
    try:
        head = base64.b64decode(b64[:64], validate=True)
    except (binascii.Error, ValueError):
        return None
    for magic, mime in _MAGIC:
        if head.startswith(magic):
            return mime
    try:
        head.decode("utf-8")
        return "text/plain"
    except UnicodeDecodeError:
        return "application/octet-stream"


class _UnaryTextTransformer(Transformer):
    """Shared shell: Text-ish input → parsed typed column."""

    input_type = Text

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(self.input_type)

    def _parse_one(self, value):
        raise NotImplementedError

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        out = np.empty(store.n_rows, dtype=object)
        for i, v in enumerate(col.values):
            out[i] = self._parse_one(v)
        return TextColumn(self.output_type, out)


@register_stage
class EmailParser(_UnaryTextTransformer):
    """Email → Text prefix or domain (RichTextFeature.toEmailPrefix/Domain)."""

    operation_name = "emailParse"
    output_type = Text
    input_type = Email

    def __init__(self, part: str = "domain", uid: Optional[str] = None):
        super().__init__(uid=uid)
        if part not in ("prefix", "domain"):
            raise ValueError(f"part must be prefix|domain, got {part!r}")
        self.part = part

    def _parse_one(self, value):
        prefix, domain = parse_email(value)
        return prefix if self.part == "prefix" else domain


@register_stage
class UrlParser(_UnaryTextTransformer):
    """URL → Text protocol or domain; invalid → None."""

    operation_name = "urlParse"
    output_type = Text
    input_type = URL

    def __init__(self, part: str = "domain", uid: Optional[str] = None):
        super().__init__(uid=uid)
        if part not in ("protocol", "domain"):
            raise ValueError(f"part must be protocol|domain, got {part!r}")
        self.part = part

    def _parse_one(self, value):
        protocol, domain = parse_url(value)
        return protocol if self.part == "protocol" else domain


@register_stage
class MimeTypeDetector(_UnaryTextTransformer):
    """Base64 → Text mime type (MimeTypeDetector.scala)."""

    operation_name = "mimeDetect"
    output_type = Text
    input_type = Base64

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    def _parse_one(self, value):
        return detect_mime(value)


#: sentence-leading words that look capitalized but are not names
_NER_STOP = frozenset("""the a an this that these those he she it they we i
    you my his her its their our your mr mrs ms dr monday tuesday wednesday
    thursday friday saturday sunday january february march april may june
    july august september october november december""".split())

_SENT_SPLIT = re.compile(r"[.!?]\s+")
_CAP_TOKEN = re.compile(r"^[A-Z][a-zA-Z'’-]*$")


@register_stage
class LanguageDetector(Transformer):
    """Text → RealMap of per-language confidence scores
    (``RichTextFeature.detectLanguages`` :403-417; the reference scores
    with Optimaize's n-gram profiles, here the stopword-overlap fraction
    each language's table achieves — same output contract: a RealMap
    keyed by language code)."""

    operation_name = "detectLanguages"

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text)

    @property
    def output_type(self):
        from ..types.feature_types import RealMap
        return RealMap

    def transform_columns(self, store: ColumnStore) -> Column:
        from ..columns import column_from_values
        from .text import score_languages

        col = store[self.input_features[0].name]
        rows = [None if (v := col.get_raw(i)) is None
                else score_languages(str(v))
                for i in range(store.n_rows)]
        return column_from_values(self.output_type, rows)


@register_stage
class NameEntityRecognizer(Transformer):
    """Text → MultiPickList of detected entity spans.

    The reference tags tokens with OpenNLP's pretrained NER models
    (``NameEntityRecognizer.scala:1``, binaries under ``models/``). This
    build vendors its own learned weights the same way: an averaged-
    perceptron BIO tagger (PER/ORG/LOC, lexicon + shape + context
    features; trained offline by ``tools/train_taggers.py``, weights
    under ``resources/taggers/``) — see ``utils/taggers.py`` for the
    model and its training-data provenance. ``entity_types`` filters the
    emitted spans (None → all). If the weight resources are missing the
    stage degrades to the round-2 capitalized-run heuristic. Override
    ``tag_sentence`` to swap in another tagger.
    """

    operation_name = "ner"
    output_type = MultiPickList

    def __init__(self, min_span_tokens: int = 1,
                 entity_types: Optional[List[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.min_span_tokens = min_span_tokens
        self.entity_types = entity_types

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text)

    def tag_sentence(self, tokens: List[str]) -> List[str]:
        """→ entity spans found in one sentence's tokens (model-based;
        heuristic fallback documented above)."""
        from ..utils.taggers import load_tagger
        ner = load_tagger("ner")
        if ner is not None:
            pos_tagger = load_tagger("pos")
            pos = pos_tagger.tag(tokens) if pos_tagger else None
            spans = ner.spans(tokens, ner.tag(tokens, pos))
            return [text for text, etype in spans
                    if (self.entity_types is None
                        or etype in self.entity_types)
                    and len(text.split()) >= self.min_span_tokens]
        if self.entity_types is not None \
                and not getattr(self, "_warned_types", False):
            self._warned_types = True
            import logging
            logging.getLogger(__name__).warning(
                "NameEntityRecognizer %s: entity_types filter requires "
                "the vendored NER weights (missing) — the heuristic "
                "fallback returns UNTYPED spans unfiltered", self.uid)
        return self._heuristic_spans(tokens)

    def _heuristic_spans(self, tokens: List[str]) -> List[str]:
        """Capitalized-run fallback (skips the ambiguous sentence-initial
        token) — only used when the vendored weights are absent."""
        spans: List[str] = []
        run: List[str] = []
        for i, tok in enumerate(tokens):
            word = tok.strip(",;:()\"'.!?")
            is_cap = bool(_CAP_TOKEN.match(word)) and \
                word.lower() not in _NER_STOP
            if is_cap and i > 0:
                run.append(word)
            else:
                if len(run) >= self.min_span_tokens:
                    spans.append(" ".join(run))
                run = []
        if len(run) >= self.min_span_tokens:
            spans.append(" ".join(run))
        return spans

    def transform_columns(self, store: ColumnStore) -> Column:
        from ..columns import TextSetColumn
        col = store[self.input_features[0].name]
        out = []
        for v in col.values:
            if not v:
                out.append(set())
                continue
            ents: set = set()
            for sent in split_sentences(v):
                ents.update(self.tag_sentence(_ner_tokenize(sent)))
            out.append(ents)
        return TextSetColumn(MultiPickList, out)


#: light word tokenizer for tagging: splits trailing/leading punctuation
#: into their own tokens while keeping internal dots/apostrophes/hyphens
#: ("U.S.", "3.5", "O'Brien", "state-of-the-art") together
_NER_TOK = re.compile(r"[A-Za-z0-9]+(?:['’.\-][A-Za-z0-9]+)*|[^\sA-Za-z0-9]")


def _ner_tokenize(sent: str) -> List[str]:
    return _NER_TOK.findall(sent)


def split_sentences(text: str) -> List[str]:
    """Model-based sentence splitting (``OpenNLPSentenceSplitter.scala:1``
    analog); regex fallback when the vendored weights are absent."""
    from ..utils.taggers import load_tagger
    splitter = load_tagger("sent")
    if splitter is not None:
        return splitter.split(text)
    return [s for s in _SENT_SPLIT.split(text) if s]


@register_stage
class OpSentenceSplitter(Transformer):
    """Text → TextList of sentences (the reference's ``SentenceSplitter``
    interface backed by OpenNLP's ``en-sent`` model; here an averaged-
    perceptron boundary classifier over punctuation contexts —
    abbreviations, initials and decimals stay inside their sentence).
    Weights vendored by ``tools/train_taggers.py``."""

    operation_name = "sentSplit"

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text)

    @property
    def output_type(self):
        from ..types.feature_types import TextList
        return TextList

    def transform_columns(self, store: ColumnStore) -> Column:
        from ..columns import TextListColumn
        col = store[self.input_features[0].name]
        rows = [split_sentences(str(v)) if v else []
                for v in col.values]
        return TextListColumn(self.output_type, rows)


@register_stage
class OpPOSTagger(Transformer):
    """Text → TextList of "token/TAG" pairs (OpenNLP POSTagger analog —
    the reference vendors ``en-pos-maxent.bin``; here the vendored
    averaged-perceptron tagger, see ``utils/taggers.py``)."""

    operation_name = "posTag"

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Text)

    @property
    def output_type(self):
        from ..types.feature_types import TextList
        return TextList

    def transform_columns(self, store: ColumnStore) -> Column:
        from ..columns import TextListColumn
        from ..utils.taggers import load_tagger
        tagger = load_tagger("pos")
        rows = []
        col = store[self.input_features[0].name]
        for v in col.values:
            if not v:
                rows.append([])
                continue
            # same tokenization the model was trained on (punctuation as
            # its own token) — whitespace splitting would feed it unseen
            # "word." forms
            toks = _ner_tokenize(str(v))
            tags = tagger.tag(toks) if tagger else ["UNK"] * len(toks)
            rows.append([f"{t}/{g}" for t, g in zip(toks, tags)])
        return TextListColumn(self.output_type, rows)


@register_stage
class PhoneNumberParser(Transformer):
    """Phone → Binary validity or Text national number
    (PhoneNumberParser.scala isValidPhoneNumber / parse)."""

    operation_name = "phoneParse"
    output_type = Binary

    def __init__(self, default_region: str = "US", output: str = "valid",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        if output not in ("valid", "national"):
            raise ValueError(f"output must be valid|national, got {output!r}")
        self.default_region = default_region
        self.output = output
        if output == "national":
            self.output_type = Text

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Phone)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        n = store.n_rows
        if self.output == "valid":
            vals = np.zeros(n, dtype=bool)
            mask = np.zeros(n, dtype=bool)
            for i, v in enumerate(col.values):
                if v is None:
                    continue
                mask[i] = True
                vals[i], _ = parse_phone(v, self.default_region)
            return NumericColumn(Binary, vals, mask)
        out = np.empty(n, dtype=object)
        for i, v in enumerate(col.values):
            ok, nat = parse_phone(v, self.default_region)
            out[i] = nat if ok else None
        return TextColumn(Text, out)
