"""Vector combination + scaling ops.

Parity: ``VectorsCombiner`` (``core/.../impl/feature/VectorsCombiner.scala``),
``OpScalarStandardScaler`` (``OpScalarStandardScaler.scala``).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columns import Column, ColumnStore, VectorColumn
from ..stages.base import (Estimator, FittedModel, InputSpec, Transformer,
                           VarArity, FixedArity, register_stage)
from ..types.feature_types import OPVector, Real, RealNN
from ..vector_metadata import VectorMetadata
from .vectorizer_base import VectorizerModel

__all__ = ["VectorsCombiner", "StandardScalerEstimator", "StandardScalerModel"]


@register_stage
class VectorsCombiner(Transformer):
    """Concatenate N OPVector features into one, merging metadata."""

    operation_name = "combineVec"
    output_type = OPVector

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    @property
    def input_spec(self) -> InputSpec:
        return VarArity(OPVector)

    def transform_columns(self, store: ColumnStore) -> Column:
        cols = [store[f.name] for f in self.input_features]
        mats, metas = [], []
        for f, c in zip(self.input_features, cols):
            assert isinstance(c, VectorColumn), f"{f.name} is not a vector"
            mats.append(c.values)
            if c.metadata is not None:
                metas.append(c.metadata)
            else:
                metas.append(VectorMetadata(f.name, []))
        mat = np.concatenate(mats, axis=1) if mats else np.zeros((store.n_rows, 0))
        meta = VectorMetadata.flatten(self.output_name, metas)
        if meta.size != mat.shape[1]:
            meta = None  # provenance lost for some inputs; keep data correct
        return VectorColumn(OPVector, mat, meta)


@register_stage
class StandardScalerModel(FittedModel):
    """(x - mean) / std per vector slot (OpScalarStandardScaler analog)."""

    operation_name = "zNormalize"
    output_type = OPVector

    def __init__(self, mean=None, std=None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.mean = np.asarray(mean) if mean is not None else None
        self.std = np.asarray(std) if std is not None else None

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(OPVector)

    def transform_columns(self, store: ColumnStore) -> Column:
        col = store[self.input_features[0].name]
        assert isinstance(col, VectorColumn)
        vals = (col.values - self.mean[None, :]) / self.std[None, :]
        return VectorColumn(OPVector, vals, col.metadata)

    def get_model_state(self):
        return {"mean": self.mean, "std": self.std}


@register_stage
class StandardScalerEstimator(Estimator):
    operation_name = "zNormalize"
    output_type = OPVector

    def __init__(self, uid: Optional[str] = None):
        super().__init__(uid=uid)

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(OPVector)

    def fit_columns(self, store: ColumnStore) -> StandardScalerModel:
        col = store[self.input_features[0].name]
        mean = col.values.mean(axis=0)
        std = col.values.std(axis=0)
        std = np.where(std < 1e-12, 1.0, std)
        return StandardScalerModel(mean=mean, std=std)
