from .vectorizer_base import TransmogrifierDefaults, VectorizerEstimator, VectorizerModel  # noqa: F401
from .numeric import RealVectorizer, IntegralVectorizer, BinaryVectorizer, NumericBucketizer  # noqa: F401
from .onehot import OneHotVectorizer, SetVectorizer, OneHotModel  # noqa: F401
from .hashing import HashingVectorizerModel, murmur3_32, hash_tokens  # noqa: F401
from .smart_text import SmartTextVectorizer, SmartTextVectorizerModel  # noqa: F401
from .text import TextTokenizer, tokenize_simple  # noqa: F401
from .dates import DateToUnitCircleVectorizer, TimePeriod  # noqa: F401
from .geo import GeolocationVectorizer  # noqa: F401
from .vectors import VectorsCombiner, StandardScalerEstimator  # noqa: F401
from .transmogrifier import Transmogrifier, transmogrify  # noqa: F401
from .indexers import (OpStringIndexerNoFilter, OpStringIndexerModel,  # noqa: F401
                       OpIndexToStringNoFilter, PredictionDeIndexer,
                       PredictionDeIndexerModel)
from .text_suite import (OpCountVectorizer, CountVectorizerModel,  # noqa: F401
                         NGramSimilarity, EmailParser, PhoneNumberParser,
                         UrlParser, MimeTypeDetector, NameEntityRecognizer,
                         OpSentenceSplitter, OpPOSTagger)
from .collections import (OPMapTransformer, OPListTransformer,  # noqa: F401
                          OPSetTransformer, lift_to_collection)
from .list_ops import (OpHashingTF, OpIDF, OpIDFModel, OpNGram,  # noqa: F401
                       OpStopWordsRemover, JaccardSimilarity)
