"""Workflow runtime — DAG fitting and scoring.

Re-designs ``OpWorkflow`` / ``OpWorkflowModel`` / ``FitStagesUtil``
(``core/.../OpWorkflow.scala:332-357``, ``core/.../OpWorkflowModel.scala``,
``core/.../utils/stages/FitStagesUtil.scala:173-293``) without Spark:

* ``Workflow.set_result_features(...)`` reconstructs the stage DAG from the
  requested outputs and validates it (distinct uids, max distances).
* ``train()`` folds over DAG layers deepest-first: fit each layer's
  estimators on the train split, evaluate ``has_test_eval`` models on the
  holdout, then transform train+test with the fitted layer
  (``FitStagesUtil.fitAndTransformLayer`` :254-293). Where the reference
  fuses a layer's row transformers into one RDD map (:96-119), here each
  stage's columnar transform is already one vectorized pass and any device
  work inside it is jit-compiled; layers share a single ColumnStore so XLA
  sees batched dense ops, not per-row UDFs.
* ``WorkflowModel`` holds fitted stages keyed by estimator uid and scores by
  replaying transform layers; ``save``/``load`` round-trip the whole model
  as ``model.json`` + ``weights.npz`` (the ``op-model.json`` analog,
  ``OpWorkflowModelWriter.scala:75-117``).
"""
from __future__ import annotations

import copy as _copy
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from . import resilience, telemetry
from .columns import Column, ColumnStore
from .features import Feature, copy_dag
from .graph import StagesDAG, compute_dag
from .stages.base import Estimator, FittedModel, OpPipelineStage, Transformer
from .stages.generator import FeatureGeneratorStage
from .utils import uid as uid_mod

__all__ = ["Workflow", "WorkflowModel", "WorkflowError"]


class WorkflowError(Exception):
    pass


def _raw_features_of(result_features: Sequence[Feature]) -> List[Feature]:
    seen: Dict[str, Feature] = {}
    for f in result_features:
        for raw in f.raw_features():
            seen.setdefault(raw.uid, raw)
    return sorted(seen.values(), key=lambda f: f.name)


def _generate_raw_store(data, raw_features: Sequence[Feature]) -> ColumnStore:
    """Materialize raw feature columns from input data.

    ``data`` is either a ColumnStore keyed by raw feature names, or a
    sequence of record dicts run through each feature's extract_fn
    (``DataReader.generateDataFrame``, readers/.../DataReader.scala:173-197).
    """
    if isinstance(data, ColumnStore):
        missing = [f.name for f in raw_features if f.name not in data]
        if missing:
            raise WorkflowError(f"Input store is missing raw features {missing}")
        return data.select([f.name for f in raw_features])
    # a columnar batch (avro.ColumnarRecords) already knows its length
    # and hands extract_column numpy columns directly — materializing
    # it into dicts here would undo the pipeline's vectorized decode
    records = data if hasattr(data, "columns") else list(data)
    cols = {}
    for f in raw_features:
        gen = f.origin_stage
        if not isinstance(gen, FeatureGeneratorStage):
            raise WorkflowError(f"Raw feature {f.name!r} has no generator stage")
        cols[f.name] = gen.extract_column(records)
    return ColumnStore(cols, len(records))


#: row count from which the layer's vectorizer transforms run as ONE jitted
#: XLA computation (below it, numpy wins: compile cost > compute)
FUSE_MIN_ROWS = 20_000

#: minimum measured host↔device round-trip bandwidth (MB/s) for layer
#: fusion to pay off. A transform layer's device work is memory-bound
#: (scatter/concat), so pushing the prepared blocks through a slow link —
#: e.g. a network-tunnelled TPU at ~10MB/s — costs far more than numpy
#: computes them. Local CPU backends (memcpy) and PCIe/ICI-attached chips
#: clear this easily; remote tunnels do not.
FUSE_MIN_BANDWIDTH_MBPS = 500.0

#: out-of-core streaming fit (run-scoped knobs — the runner installs
#: them via :func:`set_stream_fit` and restores in finally, the PR 13
#: discipline). ``STREAM_FIT`` is tri-state: None auto-engages when the
#: input is a directory stream reader (deferring to the planner's
#: measured stream-vs-materialize hint when one exists), True forces
#: streaming, False forces the materialized path.
STREAM_FIT: Optional[bool] = None

#: directory passes the streamed ingest makes: 1 folds fit statistics
#: and gathers the bounded subsample in ONE pass; 2 dedicates pass 1 to
#: the fitstats fold and pass 2 to the subsample gather (lower staging
#: pressure; identical results — the subsample is order-deterministic)
STREAM_FIT_PASSES = 2

#: bounded working set of the streamed fit: the seeded-permutation
#: subsample row budget (the quantile sketch's QUANTILE_SAMPLE_ROWS —
#: trees, quantiles and top-K stats see at most this many rows)
STREAM_SAMPLE_ROWS = int(os.environ.get("TMOG_STREAM_SAMPLE_ROWS",
                                        262_144))

#: the planner's measured ingest tier ("stream"/"materialize"/None) —
#: consulted only by the ``STREAM_FIT is None`` auto mode
_INGEST_TIER_HINT: Optional[str] = None

#: advisory host-memory budget (``customParams.rssCapMb``): a declared
#: cap makes the ``STREAM_FIT is None`` auto mode stream for directory
#: readers even against a "materialize is cheaper" tier hint — the hint
#: optimizes time, the cap protects the heap. Observability only
#: otherwise (bench's out_of_core config enforces it with setrlimit).
STREAM_RSS_CAP_MB: Optional[float] = None

_KEEP = object()


def set_stream_fit(stream=_KEEP, passes=_KEEP, sample_rows=_KEEP,
                   ingest_hint=_KEEP, rss_cap_mb=_KEEP) -> Dict[str, Any]:
    """Install run-scoped out-of-core knobs; returns the previous
    values (same keyword names) so the caller can restore them in a
    finally block — the runner's run-scoped discipline."""
    global STREAM_FIT, STREAM_FIT_PASSES, STREAM_SAMPLE_ROWS, \
        _INGEST_TIER_HINT, STREAM_RSS_CAP_MB
    prev: Dict[str, Any] = {
        "stream": STREAM_FIT, "passes": STREAM_FIT_PASSES,
        "sample_rows": STREAM_SAMPLE_ROWS,
        "ingest_hint": _INGEST_TIER_HINT,
        "rss_cap_mb": STREAM_RSS_CAP_MB}
    if stream is not _KEEP:
        STREAM_FIT = None if stream is None else bool(stream)
    if passes is not _KEEP and passes is not None:
        STREAM_FIT_PASSES = max(1, int(passes))
    if sample_rows is not _KEEP and sample_rows is not None:
        STREAM_SAMPLE_ROWS = max(1, int(sample_rows))
    if ingest_hint is not _KEEP:
        _INGEST_TIER_HINT = ingest_hint
    if rss_cap_mb is not _KEEP:
        STREAM_RSS_CAP_MB = (None if rss_cap_mb is None
                             else float(rss_cap_mb))
    return prev


_DEVICE_BW_MBPS: Optional[float] = None

#: the cold single-shot round-trip measurement (the number that used to
#: decide the gate alone — kept for the ``fusion_gate`` evidence blocks:
#: the probe/sustained split explains WHY the gate flipped)
_DEVICE_BW_PROBE_MBPS: Optional[float] = None

#: jitted per-layer programs keyed by (model ids, prepared shapes)
_LAYER_JIT_CACHE: Dict[Any, Any] = {}

# the XLA compile clock and its single jax.monitoring listener live in
# telemetry now (absorbed there along with the bandwidth probe); these
# re-exports keep the long-standing public/bench names working, sharing
# the SAME underlying clock object.
_COMPILE_CLOCK = telemetry._COMPILE_CLOCK
_ensure_compile_listener = telemetry._ensure_compile_listener
compile_clock_s = telemetry.compile_clock_s


def device_roundtrip_mbps() -> float:
    """The link bandwidth (MB/s) the fusion/engine gates decide on;
    measured once per process and cached here — tests pin
    ``_DEVICE_BW_MBPS`` to force the gate either way.

    Since the input-pipeline PR this is the SUSTAINED number: the
    better of the cold single-shot round-trip probe
    (telemetry.probe_device_roundtrip_mbps — dispatch latency dominates
    it on a warm link, the 23 MB/s that kept the gate OFF in BENCH_r05)
    and the pinned-buffer double-buffered measurement
    (pipeline.probe_sustained_mbps — the rate the staged pipeline's
    upload path actually achieves). Both raw numbers stay visible in
    :func:`fusion_state` / the cost db, so a gate decision is always
    explainable."""
    global _DEVICE_BW_MBPS, _DEVICE_BW_PROBE_MBPS
    if _DEVICE_BW_MBPS is None:
        _DEVICE_BW_PROBE_MBPS = telemetry.probe_device_roundtrip_mbps()
        from .pipeline import probe_sustained_mbps
        _DEVICE_BW_MBPS = max(_DEVICE_BW_PROBE_MBPS,
                              probe_sustained_mbps())
        logger.info(
            "layer fusion %s (gate %.0f MB/s; probe %.0f, "
            "sustained %.0f MB/s)",
            "ON" if _DEVICE_BW_MBPS >= FUSE_MIN_BANDWIDTH_MBPS else
            "OFF (tunnelled/slow link: transforms stay on host)",
            FUSE_MIN_BANDWIDTH_MBPS, _DEVICE_BW_PROBE_MBPS,
            _DEVICE_BW_MBPS)
    return _DEVICE_BW_MBPS


def fusion_state() -> Dict[str, Any]:
    """Layer-fusion gate state for benchmark recording: the measured
    link bandwidth and whether fused device transforms are ON — probed
    once per process (VERDICT r3: every benched number must say whether
    feature engineering ran fused-on-device or on host). ``mbps`` is
    the cold single-shot probe, ``sustained_mbps`` the pipeline's
    double-buffered measurement — the GATE number (the two together
    explain a gate flip)."""
    bw = device_roundtrip_mbps()
    probe = _DEVICE_BW_PROBE_MBPS if _DEVICE_BW_PROBE_MBPS is not None \
        else bw          # tests pin _DEVICE_BW_MBPS directly
    return {"fusion": "ON" if bw >= FUSE_MIN_BANDWIDTH_MBPS else "OFF",
            "mbps": round(probe, 1),
            "sustained_mbps": round(bw, 1),
            "gate_mbps": FUSE_MIN_BANDWIDTH_MBPS}


def _is_coordinator() -> bool:
    """Shared-filesystem writes (checkpoints) happen on one process only
    — multi-host runs compute identical state on every host."""
    from .parallel.multihost import is_coordinator
    return is_coordinator()


def _atomic_checkpoint(model: "WorkflowModel", directory: str) -> None:
    """Write a checkpoint crash-consistently: save into a sibling temp dir
    and swap it in (rename). A preemption at any point leaves a loadable
    checkpoint: mid-save the target dir is untouched; between the two
    renames the COMPLETE new save sits at ``<dir>.tmp`` and the previous
    good one at ``<dir>.old`` — ``model_io.load_workflow_model`` recovers
    from both (preferring ``.tmp``, which is always fully written before
    any rename starts). Names are pid-free so a resumed process cleans up
    a crashed predecessor's leftovers instead of leaking full-size copies
    (concurrent writers to one checkpoint dir are not supported).

    The write itself rides ``resilience.CHECKPOINT_RETRY`` (a transient
    shared-filesystem hiccup must not kill a multi-hour fit) and the
    swap carries the ``checkpoint.write``/``checkpoint.rename`` fault
    sites — the kill-and-resume chaos tests preempt exactly here."""
    import shutil

    from .model_io import _recover_checkpoint
    tmp = f"{directory}.tmp"
    old = f"{directory}.old"
    # adopt a predecessor's mid-swap save first (a complete .tmp/.old with
    # the target dir missing) so the cleanup below only ever deletes a
    # torn .tmp or a superseded .old — never the sole loadable save
    _recover_checkpoint(directory)

    def _save_tmp() -> None:
        resilience.inject("checkpoint.write", directory=directory)
        shutil.rmtree(tmp, ignore_errors=True)
        model.save(tmp, overwrite=True)

    resilience.CHECKPOINT_RETRY.call("checkpoint.write", _save_tmp)
    # the new save is complete on disk; stale .old is now safe to drop
    # (and must be, for the rename below to succeed)
    shutil.rmtree(old, ignore_errors=True)
    if os.path.exists(directory):
        os.rename(directory, old)
    resilience.inject("checkpoint.rename", directory=directory)
    os.rename(tmp, directory)
    shutil.rmtree(old, ignore_errors=True)


def apply_layer_vectorized(models: Sequence[Transformer], store: ColumnStore,
                           fuse_min_rows: Optional[int] = None,
                           fuse: Optional[bool] = None) -> ColumnStore:
    """Transform a DAG layer, fusing its vectorizers into one XLA program.

    The reference fuses a layer's row transformers into one RDD map
    (``FitStagesUtil.applyOpTransformations`` :96-119). Here every
    VectorizerModel in the layer contributes its ``device_compute`` to ONE
    jitted function: host_prepare runs per model on the host, then a single
    compiled XLA computation produces every output matrix — XLA fuses the
    elementwise work across stages and the data crosses host↔device once
    per layer. Non-vectorizer transformers apply as usual.

    The vector pipeline is f32-native: prepared blocks are canonicalized
    (``canonicalize_prepared``) to f32/i32 on BOTH paths, so the fused jit
    path (x64 off) and the numpy path compute on bit-identical inputs —
    no train/serve skew, no x64 requirement. Magnitudes that defeat f32
    are reduced on host first (see vectorizer_base docstring).

    The fused path engages only when BOTH of these hold; otherwise the
    numerically identical numpy path runs:

    * ``store.n_rows >= fuse_min_rows`` — below it, compile cost dominates;
    * measured host↔device bandwidth clears ``FUSE_MIN_BANDWIDTH_MBPS`` —
      a transform layer is memory-bound, so on a slow link (e.g. a
      network-tunnelled TPU) the round-trip costs more than the compute.
      Locally attached chips (PCIe/ICI) clear it easily.

    ``fuse`` overrides the BANDWIDTH half of the gate (the planner's
    measured per-phase tier decision, planner.py): ``True`` fuses even
    on a link below the prior, ``False`` keeps the layer on host. The
    row floor always holds — below it compile cost dominates whatever
    the link measures.
    """
    from .columns import VectorColumn
    from .ops.vectorizer_base import VectorizerModel, canonicalize_prepared
    from .types.feature_types import OPVector

    import jax

    threshold = FUSE_MIN_ROWS if fuse_min_rows is None else fuse_min_rows
    vecs = [m for m in models if isinstance(m, VectorizerModel)]
    rest = [m for m in models if not isinstance(m, VectorizerModel)]
    bandwidth_ok = (fuse if fuse is not None else
                    device_roundtrip_mbps() >= FUSE_MIN_BANDWIDTH_MBPS)
    fused_path = (len(vecs) >= 1 and store.n_rows >= threshold
                  and bandwidth_ok)
    t_layer = time.perf_counter()
    c_layer = _COMPILE_CLOCK["s"]
    if fused_path:
        import jax.numpy as jnp

        preps = [canonicalize_prepared(m.host_prepare(store)) for m in vecs]
        key = (tuple(id(m) for m in vecs),
               tuple((k, tuple(np.shape(v)), str(np.asarray(v).dtype))
                     for p in preps for k, v in sorted(p.items())))
        jitted = _LAYER_JIT_CACHE.pop(key, None)
        if jitted is None:
            telemetry.counter("fusion.cache_misses").inc()

            def layer_fn(prepared_list):
                return tuple(m.device_compute(jnp, p)
                             for m, p in zip(vecs, prepared_list))
            jitted = jax.jit(layer_fn)
        else:
            telemetry.counter("fusion.cache_hits").inc()
        # LRU: re-insert on use, evict oldest beyond cap (stale entries pin
        # their model objects + compiled executables otherwise)
        _LAYER_JIT_CACHE[key] = jitted
        while len(_LAYER_JIT_CACHE) > 32:
            _LAYER_JIT_CACHE.pop(next(iter(_LAYER_JIT_CACHE)))
        with telemetry.span("layer:fused_dispatch", rows=store.n_rows,
                            vectorizers=len(vecs)):
            outs = jax.device_get(jitted(preps))   # one batched pull
        for m, mat in zip(vecs, outs):
            mat = np.asarray(mat)              # already the pipeline f32
            meta = m.vector_metadata()
            assert mat.ndim == 2 and mat.shape[1] == meta.size, \
                (type(m).__name__, mat.shape, meta.size)
            store = store.with_column(m.output_name,
                                      VectorColumn(OPVector, mat, meta))
    else:
        rest = list(models)
    for m in rest:
        store = m.transform(store)
    # feed the planner's measured transform-phase tier costs — only
    # where the tier decision is contested (fusable layer at or above
    # the row floor), so host and device s/krow stay comparable. The
    # one-time XLA compile is subtracted (the _fit_layer clamp
    # discipline): folding ~seconds of compile into a steady-state
    # s/krow mean would poison the device tier against itself.
    if vecs and store.n_rows >= threshold:
        from . import planner
        elapsed = time.perf_counter() - t_layer
        compile_s = min(_COMPILE_CLOCK["s"] - c_layer, elapsed)
        planner.observe_phase(
            "transform", "device" if fused_path else "host",
            elapsed - compile_s, store.n_rows)
    return store


class Workflow:
    """Untrained pipeline: raw data + result features → fitted model."""

    def __init__(self):
        self.uid = uid_mod.make_uid("Workflow")
        self.result_features: Tuple[Feature, ...] = ()
        self._input_data = None
        self._reader = None
        self.splitter = None          # tuning.Splitter for holdout reservation
        self.raw_feature_filter = None
        self.parameters: Dict[str, Any] = {}
        self.blacklisted_features: List[Feature] = []
        #: explicit (data, grid) mesh; None resolves to the process
        #: default over all visible devices at train time (PR 6: the
        #: mesh is the mainline substrate, 1×1 degenerate on one device)
        self.mesh = None
        #: attached planner.ExecutionPlan (set_plan): its per-phase tier
        #: decisions steer the fused stats pass and layer fusion; None
        #: keeps the legacy gates (PR 7: the cost-based middle-end)
        self._exec_plan = None
        self._workflow_cv = False
        self._checkpoint_dir: Optional[str] = None
        self._warm_stages: Dict[str, FittedModel] = {}
        #: persisted train-time sufficient statistics from a PREVIOUS
        #: model ({"<layer>:<column>": fitstats.SufficientStats}) — the
        #: continual-learning warm start: moment-family fused stats
        #: Chan-merge [old window + fresh slice] instead of rescanning
        self._warm_fit_stats = None
        #: this train's collected sufficient statistics (same keying),
        #: persisted with the model so the NEXT retrain can warm-start
        self._fit_state: Dict[str, Any] = {}
        #: per-stage fit/transform wall-clock collected during train
        #: (OpSparkListener StageMetrics analog)
        self._stage_metrics: Dict[str, Dict[str, Any]] = {}

    # -- config ------------------------------------------------------------
    def set_result_features(self, *features: Feature) -> "Workflow":
        if not features:
            raise WorkflowError("Must provide at least one result feature")
        self.result_features = tuple(features)
        self._validate_dag()
        return self

    def set_input_store(self, store: ColumnStore) -> "Workflow":
        self._input_data = store
        return self

    def set_input_records(self, records: Sequence[Mapping[str, Any]]) -> "Workflow":
        self._input_data = list(records)
        return self

    def set_reader(self, reader) -> "Workflow":
        self._reader = reader
        return self

    def set_splitter(self, splitter) -> "Workflow":
        self.splitter = splitter
        return self

    def set_mesh(self, mesh) -> "Workflow":
        """Pin the (data, grid) device mesh for this workflow's heavy
        phases (CV sweep, fused fit-statistics, layer programs). The
        default — None — resolves to ``parallel.mesh.process_default_mesh``
        at train time, so multi-chip hosts shard by default and a single
        device takes the degenerate 1×1 path. ``mesh=False`` forces the
        unsharded single-device path on any host."""
        self.mesh = mesh
        return self

    def set_plan(self, plan) -> "Workflow":
        """Attach a :class:`~transmogrifai_tpu.planner.ExecutionPlan`
        whose per-phase tier decisions this fit follows: the fused
        fit-statistics pass and the transform-layer fusion consult its
        ``fitstats_tier``/``transform_tier`` instead of the global
        bandwidth gate (which stays as the cold-start prior when the
        plan defers). Tier choices change cost, never results — the
        planner only overrides the bandwidth half of each gate."""
        self._exec_plan = plan
        return self

    def with_raw_feature_filter(self, rff) -> "Workflow":
        """Attach a RawFeatureFilter data-quality gate
        (OpWorkflow.withRawFeatureFilter, OpWorkflow.scala:521-563)."""
        self.raw_feature_filter = rff
        return self

    def set_parameters(self, params: Dict[str, Any]) -> "Workflow":
        self.parameters = dict(params)
        return self

    def with_model_stages(self, model: "WorkflowModel") -> "Workflow":
        """Warm start (OpWorkflow.withModelStages :457-460): fitted stages
        from a previous model are substituted by uid during train, skipping
        their refit. Estimators not present in the model still fit."""
        self._warm_stages = dict(model.fitted_stages)
        return self

    def with_warm_fit_stats(self, stats) -> "Workflow":
        """Warm-start the fused fit-statistics pass from a previous
        model's persisted sufficient statistics (the continual-learning
        seam, continual.py / docs/lifecycle.md "Continuous training").

        ``stats`` maps ``"<layer>:<column>"`` to
        :class:`~transmogrifai_tpu.fitstats.SufficientStats` — the form
        :func:`fitstats.load_sufficient_stats` returns. During train,
        each fused layer's moment-family stats are Chan-merged with the
        matching warm entries, so opted-in estimators fit over
        [old train window + fresh slice] while the data scan covers only
        the fresh slice. ``None`` (or an empty dict) is a no-op — the
        train is a plain cold fit — and columns without a warm entry
        stay fresh-only, so a partially matching DAG degrades
        gracefully instead of failing."""
        self._warm_fit_stats = dict(stats) if stats else None
        return self

    def with_checkpointing(self, directory: str) -> "Workflow":
        """Layer-granular failure recovery: after every fitted DAG layer
        the partial model is persisted to ``directory``; a crashed train
        resumes via ``Workflow.with_model_stages(WorkflowModel.load(dir))``
        which skips the already-fitted estimators. The framework analog of
        the reference's persist-every-K robustness thinking
        (FitStagesUtil.scala:134-165) with actual resume."""
        self._checkpoint_dir = directory
        return self

    def with_workflow_cv(self, enabled: bool = True) -> "Workflow":
        """Leak-free workflow-level cross-validation
        (OpWorkflowCore.withWorkflowCV :104): the DAG's label-aware feature
        stages (cutDAG's *during* set) are re-fit inside every CV fold so
        validation metrics never see label leakage from feature
        engineering."""
        self._workflow_cv = enabled
        return self

    # -- validation (OpWorkflow.scala:265-323) -----------------------------
    def _validate_dag(self) -> None:
        from .models.selector import ModelSelector
        try:
            stages = [s for layer in compute_dag(self.result_features, True)
                      for s in layer]
        except ValueError as e:
            # compute_dag detects distinct stages sharing one uid (the
            # silent-collapse bug lint rule TMG102 also surfaces)
            raise WorkflowError(str(e)) from e
        selectors = [s for s in stages if isinstance(s, ModelSelector)]
        if len(selectors) > 1:
            raise WorkflowError(
                f"Workflow can contain at most 1 ModelSelector "
                f"(FitStagesUtil.scala:313), found {len(selectors)}")

    def validate(self, suppress=()) -> list:
        """Static pre-flight check (lint.py TMG1xx graph rules): returns
        structured :class:`~transmogrifai_tpu.lint.Finding` records for
        type-flow mismatches, duplicate uids, cycles, response leakage
        and estimator misuse — BEFORE any data is read. The runner calls
        this by default (``OpParams.customParams.validate``); callers
        gate on the result with ``lint.enforce(findings)``."""
        from . import lint
        return lint.check_workflow(self, suppress=suppress)

    # -- training ----------------------------------------------------------
    def train(self) -> "WorkflowModel":
        raw_features = _raw_features_of(self.result_features)
        # per-train sufficient-stats collection state (a reused
        # workflow must not carry a previous train's stats forward)
        self._fit_state = {}
        self._warm_matched = 0
        data = self._input_data
        store = None
        #: full-stream SufficientStats per raw column when the streamed
        #: ingest ran (injected into every fused stats pass); None on
        #: the materialized path — the exact current code path
        self._stream_state = None
        if data is None and self._reader is not None:
            if getattr(self._reader, "is_aggregating", False):
                # event-grouped readers OWN raw-store generation: the
                # group-by-key + cutoff/window monoid folds (and their
                # columnar fast path) live in the reader, not here —
                # read_records would hand us raw EVENTS, one row per
                # event instead of one per key
                store = self._reader.generate_store(raw_features)
            elif self._use_stream_fit():
                store = self._stream_raw_store(raw_features)
            else:
                t_ing = time.perf_counter()
                data = self._reader.read_records()
                self._observe_ingest("materialize",
                                     time.perf_counter() - t_ing,
                                     len(data))
        if store is None:
            if data is None:
                raise WorkflowError(
                    "No input data: call set_input_store/records/reader")
            store = _generate_raw_store(data, raw_features)

        result_features = self.result_features
        rff_results = None
        if self.raw_feature_filter is not None:
            filtered = self.raw_feature_filter.filter_raw(
                store, raw_features)
            store = filtered.clean_store
            self.blacklisted_features = filtered.blacklisted_features
            rff_results = filtered.results
            blacklisted = {f.uid for f in self.blacklisted_features}
            if blacklisted:
                # Rebuild the DAG without blacklisted raw features on a COPY
                # (copyWithNewStages) so the user-owned graph is untouched
                # (OpWorkflow.scala:112-154).
                for f in result_features:
                    if f.uid in blacklisted:
                        raise WorkflowError(
                            f"Result feature {f.name!r} was blacklisted by "
                            "the RawFeatureFilter")
                try:
                    result_features = tuple(copy_dag(
                        result_features, frozenset(blacklisted)))
                except TypeError as e:
                    raise WorkflowError(
                        "A fixed-arity stage depends on blacklisted "
                        f"feature(s): {e}") from e
            raw_features = [f for f in raw_features if f.uid not in blacklisted]

        train_store, test_store = store, None
        if self.splitter is not None:
            train_store, test_store = self.splitter.reserve_split(store)

        # the graph actually being fitted (RFF pruning may have copied it);
        # layer checkpoints must record THIS graph, not the original
        self._active_result_features = result_features
        dag = compute_dag(result_features)
        self._resolve_mesh(dag)
        logger.info(
            "train: %d rows (%d held out), %d DAG layers, %d stages%s",
            train_store.n_rows,
            test_store.n_rows if test_store is not None else 0,
            len(dag), sum(len(l) for l in dag),
            " [workflow-level CV]" if self._workflow_cv else "")
        with telemetry.span("workflow:train", layers=len(dag),
                            rows=train_store.n_rows,
                            workflow_cv=self._workflow_cv):
            if self._workflow_cv:
                fitted, train_time = self._fit_dag_workflow_cv(
                    result_features, dag, train_store, test_store)
            else:
                fitted, train_time, _, _ = self._fit_dag(
                    dag, train_store, test_store, transform_last=False)
        logger.info("train: done in %.2fs (%d fitted stages)",
                    train_time, len(fitted))
        if self._warm_fit_stats and not self._warm_matched:
            # warm start was requested but no persisted key matched any
            # fused layer (different DAG, fusion disabled, ...): the
            # refit silently became a full fresh-window fit — say so
            from . import lint
            f = lint.Finding(
                "TMG604", "warm-start sufficient statistics matched no "
                "fused layer of this DAG — the refit ran as a full "
                "fit over the fresh window")
            lint.emit_findings([f])
            logger.warning("train: %s", f.format())
        return WorkflowModel(
            result_features=result_features,
            fitted_stages=fitted,
            dag=dag,
            parameters=self.parameters,
            blacklisted_features=self.blacklisted_features,
            rff_results=rff_results,
            train_time_s=train_time,
            stage_metrics=self._stage_metrics,
            train_rows=train_store.n_rows,
            fit_stats=dict(self._fit_state),
        )

    def fit(self, resume_from: Optional[str] = None) -> "WorkflowModel":
        """:meth:`train` with preemption recovery.

        ``resume_from`` names a layer-checkpoint directory (the one a
        previous run's ``with_checkpointing`` wrote — including one left
        mid-swap by a kill, which ``model_io._recover_checkpoint``
        repairs on load): its fitted stages warm-start this fit, so
        every already-completed DAG layer is skipped and only the layers
        the preemption interrupted re-fit. A missing or empty checkpoint
        degrades to a fresh fit — ``fit(resume_from=d)`` is safe to use
        unconditionally as the restart entry point. Checkpointing
        continues into the same directory unless one was already
        configured."""
        if resume_from:
            from .model_io import MODEL_JSON
            if self._checkpoint_dir is None:
                self.with_checkpointing(resume_from)
            partial = None
            if any(os.path.exists(os.path.join(p, MODEL_JSON))
                   for p in (resume_from, f"{resume_from}.tmp",
                             f"{resume_from}.old")):
                try:
                    partial = WorkflowModel.load(resume_from)
                except Exception:  # lint: broad-except — unusable checkpoint degrades to a fresh fit
                    logger.exception(
                        "checkpoint at %s is unusable; fitting from "
                        "scratch", resume_from)
            if partial is not None and partial.fitted_stages:
                self.with_model_stages(partial)
                resilience.record_resumed_fit()
                logger.info(
                    "resuming fit from %s: %d fitted stage(s) warm-start",
                    resume_from, len(partial.fitted_stages))
        return self.train()

    # -- out-of-core ingest (streamFit) ------------------------------------
    def _use_stream_fit(self) -> bool:
        """Engage the streaming ingest? Explicit ``STREAM_FIT`` wins;
        auto (None) engages for directory stream readers unless the
        planner's measured ingest tier says materializing is cheaper."""
        from .readers.streaming import DirectoryStreamReader
        if not isinstance(self._reader, DirectoryStreamReader):
            return False
        if STREAM_FIT is not None:
            return bool(STREAM_FIT)
        if STREAM_RSS_CAP_MB is not None:
            # a declared memory budget outranks the time-optimizing
            # tier hint: streaming is the bounded-working-set route
            return True
        return _INGEST_TIER_HINT != "materialize"

    def _observe_ingest(self, tier: str, seconds: float,
                        rows: int) -> None:
        """Feed the planner's stream-vs-materialize cost observation —
        only for directory readers (the contested route) at row counts
        where the tier decision matters (the fitstats discipline)."""
        from .readers.streaming import DirectoryStreamReader
        if not isinstance(self._reader, DirectoryStreamReader):
            return
        if rows >= FUSE_MIN_ROWS:
            from . import planner
            planner.observe_phase("workflow.ingest", tier, seconds, rows)

    def _stream_raw_store(self, raw_features) -> ColumnStore:
        """Out-of-core ingest: fold full-stream fit statistics and
        gather the seeded bounded row subsample from the directory
        reader's columnar batches — the full store is NEVER
        materialized; host memory is bounded at one staging chunk plus
        ``STREAM_SAMPLE_ROWS`` buffered rows.

        Returns the subsample ColumnStore the rest of the fit runs on
        (for streams within the sample budget it is the whole stream,
        in order — identical to materializing). Side effect:
        ``self._stream_state`` carries each numeric raw column's
        full-stream :class:`~transmogrifai_tpu.fitstats.SufficientStats`
        (bit-identical to a materialized device fitstats pass), which
        every fused stats pass injects so moment stats reflect ALL
        rows, not the subsample. ``STREAM_FIT_PASSES`` >= 2 dedicates
        pass 1 to the fold and pass 2 (a reader ``rescan``) to the
        subsample gather; results are pass-count-invariant."""
        from . import fitstats, pipeline
        reader = self._reader
        passes = max(1, int(STREAM_FIT_PASSES))
        two_pass = passes >= 2
        sample = pipeline.SeededRowSample(STREAM_SAMPLE_ROWS)
        fold: Optional[fitstats.StreamingMomentFold] = None
        mesh = False if self.mesh is False else self.mesh
        t0 = time.perf_counter()
        n_batches = 0

        def batch_store(batch) -> ColumnStore:
            return _generate_raw_store(batch, raw_features)

        def fold_batch(bstore: ColumnStore) -> None:
            nonlocal fold
            if fold is None:
                numeric = [nm for nm in bstore.names()
                           if isinstance(getattr(bstore[nm], "values",
                                                 None), np.ndarray)
                           and (np.issubdtype(bstore[nm].values.dtype,
                                              np.number)
                                or bstore[nm].values.dtype == bool)]
                fold = fitstats.StreamingMomentFold(numeric, mesh=mesh)
            fold.update(bstore)

        def sample_batch(batch) -> None:
            loc = sample.offer(len(batch))
            sample.keep([batch[int(i)] for i in loc])

        with telemetry.span("workflow:stream_ingest",
                            passes=passes):
            for batch in reader.stream(passes=1):
                n_batches += 1
                fold_batch(batch_store(batch))
                if not two_pass:
                    sample_batch(batch)
            if two_pass:
                reader.rescan()
                for batch in reader.stream(passes=1):
                    sample_batch(batch)

        records = sample.result()
        n_total = sample.total_rows
        store = _generate_raw_store(records, raw_features)
        self._observe_ingest("stream", time.perf_counter() - t0,
                             n_total)
        if fold is not None and n_total >= FUSE_MIN_ROWS:
            self._stream_state = fold.finalize()
        else:
            # tiny streams: the subsample IS the data and the host
            # fitstats tier is bit-exact — behave exactly like the
            # materialized path
            self._stream_state = None
        logger.info(
            "train: streamed ingest %d row(s) in %d batch(es) "
            "(%d pass(es)); subsample %d row(s), %d streamed stat "
            "column(s)", n_total, n_batches, passes, store.n_rows,
            len(self._stream_state or ()))
        telemetry.emit("stream_ingest", rows=n_total,
                       batches=n_batches, passes=passes,
                       sample_rows=store.n_rows,
                       stream_stat_columns=len(self._stream_state
                                               or ()))
        return store

    def _resolve_mesh(self, dag: StagesDAG) -> None:
        """Resolve the mesh every heavy phase of this fit runs on and
        thread it to the consumers (PR 6: the process-wide mesh is the
        mainline substrate, not a dry-run opt-in).

        ``self.mesh`` wins when set (``False`` forces unsharded);
        otherwise the cached process-default mesh over all visible
        devices is used. The degenerate 1×1 mesh resolves to None —
        single-device runs take exactly the pre-mesh code path. Any
        ModelSelector in the DAG that was not handed an explicit mesh
        inherits the resolved one, so the CV sweep shards by default —
        and stays workflow-managed: a RE-train after ``set_mesh(...)``
        or under a different process mesh re-resolves it instead of
        keeping the first train's pin."""
        from .models.selector import ModelSelector
        from .parallel.mesh import (mesh_if_multi, mesh_topology,
                                    process_default_mesh)
        if self.mesh is False:
            active = None
        else:
            active = mesh_if_multi(
                self.mesh if self.mesh is not None
                else process_default_mesh())
        self._active_mesh = active
        if active is not None:
            topo = mesh_topology(active)
            telemetry.gauge("mesh.data_axis").set(topo["data"])
            telemetry.gauge("mesh.grid_axis").set(topo["grid"])
            telemetry.emit("mesh", devices=topo["devices"],
                           data=topo["data"], grid=topo["grid"],
                           platform=topo["platform"])
            logger.info("train: mesh %d device(s) (data=%d, grid=%d)",
                        topo["devices"], topo["data"], topo["grid"])
        # the auto-assignment marker lives on the STAGE (not a
        # per-workflow set): a selector one workflow auto-assigned must
        # stay workflow-managed when another workflow (or a retrain)
        # resolves a different mesh — only an explicit construction-time
        # mesh= is never overwritten. Tree estimator stages take the
        # mesh too: the sharded histogram build (shard_map + psum) makes
        # EVERY RF/GBT/XGB fit scale with devices, not just the CV fold
        # grid.
        from .models.trees import _TreeEstimatorBase
        for layer in dag:
            for stage in layer:
                if isinstance(stage, (ModelSelector, _TreeEstimatorBase)) \
                        and (stage.mesh is None
                             or getattr(stage, "_mesh_auto", False)):
                    stage.mesh = active
                    stage._mesh_auto = True
        # overlap the one-time Pallas kernel compile probe with the
        # phases between here and the first tree-family sweep (raw-store
        # prep, fitstats, vectorizers): only bench.py did this before —
        # a production Train paid the ~10-15 s probe compile inline
        # inside its first sweep
        self._warm_tree_probe(dag)

    @staticmethod
    def _warm_tree_probe(dag: StagesDAG) -> None:
        from .models.selector import ModelSelector
        from .models.trees import _TreeEstimatorBase, _TreeFamilyBase
        has_trees = any(
            isinstance(stage, _TreeEstimatorBase)
            or (isinstance(stage, ModelSelector)
                and any(isinstance(f, _TreeFamilyBase)
                        for f in stage.families))
            for layer in dag for stage in layer)
        if has_trees:
            from .models._pallas_hist import warm_probe_async
            warm_probe_async()

    def _fit_dag(self, dag: StagesDAG, train: ColumnStore,
                 test: Optional[ColumnStore],
                 fitted: Optional[Dict[str, FittedModel]] = None,
                 checkpoint: bool = True,
                 transform_last: bool = True
                 ) -> Tuple[Dict[str, FittedModel], float,
                            ColumnStore, Optional[ColumnStore]]:
        """Fold layers: fit estimators, holdout-eval, transform both splits
        (FitStagesUtil.fitAndTransformDAG/Layer).

        ``transform_last=False`` skips transforming the TERMINAL layer:
        callers that discard the returned stores (plain ``train()``) pay
        a full scoring pass — 97 s of pure upload at the 10M config —
        for predictions nothing consumes (scoring re-runs the DAG)."""
        t0 = time.perf_counter()
        _ensure_compile_listener()
        fitted = {} if fitted is None else fitted
        for li, layer in enumerate(dag):
            telemetry.emit("layer_start", index=li, n_stages=len(layer))
            with telemetry.span("fit:layer", layer=li, stages=len(layer),
                                rows=train.n_rows):
                train, test = self._fit_layer(
                    li, layer, dag, train, test, fitted, checkpoint,
                    transform_last)
        return fitted, time.perf_counter() - t0, train, test

    def _collect_layer_state(self, li: int, requests: Dict[str, list],
                             train: ColumnStore) -> None:
        """State-only sufficient-stats collection for a layer below the
        fusion threshold: one cheap host pass per requested moment
        column, keyed ``"<layer>:<column>"`` like the fused path, so
        single-estimator layers still leave a warm-start trail. Best
        effort — a failure costs the model its warm-start state, never
        the fit."""
        from . import fitstats
        try:
            cols = {r.column for reqs in requests.values() for r in reqs
                    if r.kind in fitstats._MOMENT_KINDS}
            for col in sorted(cols):
                self._fit_state[f"{li}:{col}"] = \
                    fitstats.collect_column_state(train[col])
        except Exception:  # lint: broad-except — state collection is an optimization for FUTURE retrains, never a fit dependency
            logger.exception("layer %d: sufficient-stats side "
                             "collection failed", li)

    def _layer_stats_pass(self, li: int, layer: Sequence[OpPipelineStage],
                          train: ColumnStore):
        """The fused fit-statistics pass (fitstats.py, the
        SequenceAggregators analog): collect every opted-in estimator's
        StatRequests for this layer and compute them in ONE pass over
        the train store, so each ``fit`` becomes a host-side finalize.
        Returns (StatResults | None, set of fused stage uids). Any
        failure degrades to the sequential per-stage fits — the fused
        pass is an optimization, never a correctness dependency."""
        from . import fitstats
        if not fitstats.FITSTATS_ENABLED:
            return None, set()
        requests: Dict[str, list] = {}
        for stage in layer:
            if not isinstance(stage, Estimator) \
                    or self._warm_stages.get(stage.uid) is not None:
                continue
            try:
                reqs = stage.stat_requests(train)
            except Exception:  # lint: broad-except — a failing opt-in degrades to the sequential fit
                logger.exception(
                    "stat_requests failed for %s; it fits sequentially",
                    stage.stage_name())
                reqs = None
            if reqs is not None:
                requests[stage.uid] = list(reqs)
        # only stages whose requests actually SCAN data count toward the
        # pass math — an empty opt-in (constant-fill vectorizers) never
        # scanned sequentially either, so it saves nothing and must not
        # inflate the passes_saved/layers_fused tallies
        n_scanning = sum(1 for reqs in requests.values() if reqs)
        # the continual seam, part 1: the warm stats for THIS layer's
        # columns (a warm match forces the stats path even below the
        # fusion threshold — the merge needs it)
        warm = None
        if self._warm_fit_stats and n_scanning:
            prefix = f"{li}:"
            warm = {k[len(prefix):]: v
                    for k, v in self._warm_fit_stats.items()
                    if k.startswith(prefix)} or None
            if warm:
                self._warm_matched += len(warm)
        if n_scanning < fitstats.FITSTATS_MIN_STAGES and warm is None:
            # below the fusion threshold there is no pass to save, but
            # the moment sufficient stats still persist with the model
            # (state-only side collection, no fused-pass tallies) so a
            # FUTURE drift-triggered retrain can warm-start from it
            if n_scanning:
                self._collect_layer_state(li, requests, train)
            return None, set()
        try:
            plan = fitstats.LayerStatsPlan(
                [r for reqs in requests.values() for r in reqs],
                n_stages=n_scanning)
            # the continual seam, part 2: collect this layer's
            # sufficient stats (persisted with the model for the NEXT
            # warm retrain) alongside the fused pass itself
            state_out: Dict[str, Any] = {}
            tp = time.perf_counter()
            with telemetry.span("fit:stats_pass", layer=li,
                                stages=n_scanning,
                                requests=plan.n_requests,
                                rows=train.n_rows):
                stats = plan.run(
                    train,
                    mesh=(False if self.mesh is False
                          else getattr(self, "_active_mesh", None)),
                    tier_hint=(self._exec_plan.fitstats_tier
                               if self._exec_plan is not None else None),
                    state_out=state_out, warm_state=warm,
                    stream_state=getattr(self, "_stream_state", None))
            for col, st in state_out.items():
                self._fit_state[f"{li}:{col}"] = st
            telemetry.emit("stats_pass", layer=li,
                           n_stages=n_scanning,
                           n_requests=plan.n_requests,
                           passes_saved=n_scanning - 1,
                           seconds=time.perf_counter() - tp)
            logger.info(
                "layer %d: fused stats pass fed %d estimator(s) "
                "(%d request(s)) in %.2fs",
                li, len(requests), plan.n_requests,
                time.perf_counter() - tp)
            return stats, set(requests)
        except Exception:  # lint: broad-except — fused pass is an optimization, never a dependency
            logger.exception(
                "layer %d: fused fit-stats pass failed; estimators fit "
                "sequentially", li)
            return None, set()

    def _fit_layer(self, li: int, layer: Sequence[OpPipelineStage],
                   dag: StagesDAG, train: ColumnStore,
                   test: Optional[ColumnStore],
                   fitted: Dict[str, FittedModel], checkpoint: bool,
                   transform_last: bool
                   ) -> Tuple[ColumnStore, Optional[ColumnStore]]:
        """One layer of :meth:`_fit_dag`: fit/warm-start its estimators,
        transform both splits, checkpoint. Mutates ``fitted`` in place and
        returns the transformed (train, test) stores."""
        models: List[Transformer] = []
        n_fitted_before = len(fitted)
        layer_stats, fused_uids = self._layer_stats_pass(li, layer, train)
        for stage in layer:
            metrics = self._stage_metrics.setdefault(
                stage.uid, {"stageName": stage.stage_name()})
            if isinstance(stage, Estimator):
                warm = self._warm_stages.get(stage.uid)
                if warm is not None:
                    # warm start: substitute the previously fitted model
                    # by uid. Shallow-copy before rebinding wiring so
                    # the donor WorkflowModel's stages stay intact
                    # (fitted state/arrays are shared read-only).
                    model = _copy.copy(warm)
                    model.input_features = stage.input_features
                    model._output_feature = stage.get_output()
                    metrics["warmStarted"] = True
                    metrics["fitSeconds"] = 0.0
                    telemetry.emit(
                        "stage_fit", uid=stage.uid,
                        stage_name=stage.stage_name(), fit_s=0.0,
                        warm_started=True)
                    logger.info("layer %d: %s [%s] warm-started",
                                li, stage.stage_name(), stage.uid)
                else:
                    logger.info("layer %d: fitting %s [%s] on %d rows",
                                li, stage.stage_name(), stage.uid,
                                train.n_rows)
                    tf = time.perf_counter()
                    c0 = _COMPILE_CLOCK["s"]
                    fused = layer_stats is not None \
                        and stage.uid in fused_uids
                    with telemetry.span("fit:stage", uid=stage.uid,
                                        stage=stage.stage_name(),
                                        layer=li, fused=fused):
                        # positional call when not fused: stages that
                        # override fit(store) (dt_bucketizer) never see
                        # the stats kwarg
                        model = (stage.fit(train, stats=layer_stats)
                                 if fused else stage.fit(train))
                    if fused:
                        metrics["fusedStats"] = True
                    fit_s = time.perf_counter() - tf
                    # clamp: concurrent compiles sum WORK > wall-clock
                    compile_s = min(_COMPILE_CLOCK["s"] - c0, fit_s)
                    metrics["fitSeconds"] = round(fit_s, 4)
                    metrics["compileSeconds"] = round(compile_s, 4)
                    metrics["executeSeconds"] = round(
                        max(fit_s - compile_s, 0.0), 4)
                    telemetry.emit(
                        "stage_fit", uid=stage.uid,
                        stage_name=stage.stage_name(), fit_s=fit_s,
                        compile_s=compile_s,
                        execute_s=max(fit_s - compile_s, 0.0))
                    logger.info(
                        "layer %d: %s fit in %.2fs "
                        "(compile %.2fs, execute %.2fs)",
                        li, stage.stage_name(), fit_s, compile_s,
                        max(fit_s - compile_s, 0.0))
                fitted[stage.uid] = model
                if model.has_test_eval() and test is not None:
                    model.evaluate_model(test)
                models.append(model)
            elif isinstance(stage, Transformer):
                models.append(stage)
            else:
                raise WorkflowError(f"Unfittable stage {stage!r}")
        # transform both splits with the fully fitted layer — the
        # layer's vectorizers fuse into one XLA program per split
        if not transform_last and li == len(dag) - 1:
            if models:
                logger.info("layer %d: transform skipped "
                            "(terminal layer, outputs unconsumed)", li)
        else:
            tt = time.perf_counter()
            # the planner's measured transform tier overrides the
            # bandwidth prior (omitted entirely when the plan defers,
            # so the gate — and any test double of this function —
            # sees the pre-planner call shape; the row floor inside
            # apply_layer_vectorized always holds)
            fuse_kw = {}
            if self._exec_plan is not None \
                    and self._exec_plan.transform_tier is not None:
                fuse_kw = {"fuse":
                           self._exec_plan.transform_tier == "device"}
            with telemetry.span("fit:transform_layer", layer=li,
                                stages=len(models)):
                train = apply_layer_vectorized(models, train, **fuse_kw)
                if test is not None:
                    test = apply_layer_vectorized(models, test,
                                                  **fuse_kw)
            layer_transform_s = time.perf_counter() - tt
            if models:
                logger.info("layer %d: transformed %d stage(s) in "
                            "%.2fs", li, len(models), layer_transform_s)
            for m in models:
                self._stage_metrics.setdefault(
                    m.uid, {"stageName": m.stage_name()})[
                    "layerTransformSeconds"] = round(layer_transform_s, 4)
        if checkpoint and self._checkpoint_dir \
                and len(fitted) > n_fitted_before \
                and _is_coordinator():
            # the ACTIVE graph (post-RawFeatureFilter pruning), written
            # crash-consistently: a preemption mid-save must not
            # destroy the previous good checkpoint. Transformer-only
            # layers add no fitted state, so they skip the write.
            feats = getattr(self, "_active_result_features",
                            self.result_features)
            if feats:
                _atomic_checkpoint(WorkflowModel(
                    result_features=feats, fitted_stages=fitted),
                    self._checkpoint_dir)
                logger.info(
                    "layer %d: checkpointed %d fitted stage(s) to %s",
                    li, len(fitted), self._checkpoint_dir)
        return train, test

    def _fit_dag_workflow_cv(self, result_features, dag: StagesDAG,
                             train: ColumnStore,
                             test: Optional[ColumnStore]
                             ) -> Tuple[Dict[str, FittedModel], float]:
        """Leak-free workflow CV (OpWorkflow.scala:388-443 + cutDAG).

        1. Fit the *before* DAG once on the training split.
        2. Per CV fold: re-fit the *during* (label-aware) stages on in-fold
           training rows only, transform the full split, and score the
           (family × grid) sweep on that fold's matrix
           (OpCrossValidation.scala:89-116 dagCopy semantics).
        3. Hand the winner to the ModelSelector, then fit during + selector
           + after layers normally on the full training split.
        """
        from .graph import cut_dag

        t0 = time.perf_counter()
        ms, before, during, after = cut_dag(result_features)
        if ms is None or not during:
            fitted, _, _, _ = self._fit_dag(dag, train, test,
                                            transform_last=False)
            return fitted, time.perf_counter() - t0

        fitted: Dict[str, FittedModel] = {}
        _, _, train_b, test_b = self._fit_dag(before, train, test, fitted)

        label_name = ms.input_features[0].name
        feats_f = ms.input_features[1]
        y = np.asarray(train_b[label_name].values, dtype=np.float64)
        if ms.splitter is not None:
            ms.splitter.pre_validation_prepare(y)
            keep = ms.splitter.keep_mask(y)
        else:
            keep = np.ones_like(y, dtype=bool)
        store_kept = train_b.take(np.nonzero(keep)[0]) if not keep.all() \
            else train_b
        y_kept = y[keep]
        if ms.splitter is not None:
            y_kept = ms.splitter.relabel(y_kept)
            base_w = ms.splitter.sample_weights(y_kept)
        else:
            base_w = np.ones_like(y_kept)
        ms._maybe_set_classes(y_kept)

        from .models.trees import detect_binary_columns

        fold_data = []
        for train_mask, val_mask in ms.validator._splits(y_kept):
            tr_idx = np.nonzero(train_mask > 0)[0]
            fold_fit: Dict[str, FittedModel] = {}
            _, _, _, _ = self._fit_dag(during, store_kept.take(tr_idx),
                                       None, fold_fit, checkpoint=False,
                                       transform_last=False)
            # transform the FULL kept split with fold-fitted during stages
            fold_store = store_kept
            for layer in during:
                fold_models = [fold_fit.get(s.uid, s) for s in layer]
                fold_store = apply_layer_vectorized(fold_models, fold_store)
            X_f = np.asarray(fold_store[feats_f.name].values,
                             dtype=np.float64)
            fold_data.append((X_f, y_kept, train_mask * base_w, val_mask,
                              detect_binary_columns(X_f)))

        best_family, best_hparams, vsummary = \
            ms.validator.validate_per_fold(ms.families, fold_data,
                                           mesh=ms.mesh)
        ms.best_estimator_ = (best_family, best_hparams)
        ms.precomputed_summary_ = vsummary

        # final fit: during + selector layer + after on the full split
        remaining: StagesDAG = []
        done = {s.uid for layer in before for s in layer}
        for layer in dag:
            rest = [s for s in layer if s.uid not in done]
            if rest:
                remaining.append(rest)
        fitted, _, _, _ = self._fit_dag(remaining, train_b, test_b, fitted,
                                        transform_last=False)
        return fitted, time.perf_counter() - t0


class WorkflowModel:
    """Fitted pipeline (OpWorkflowModel): score / evaluate / save."""

    def __init__(self, result_features: Sequence[Feature],
                 fitted_stages: Dict[str, FittedModel],
                 dag: Optional[StagesDAG] = None,
                 parameters: Optional[Dict[str, Any]] = None,
                 blacklisted_features: Sequence[Feature] = (),
                 rff_results=None,
                 train_time_s: float = 0.0,
                 stage_metrics: Optional[Dict[str, Dict[str, Any]]] = None,
                 train_rows: int = 0,
                 fit_stats: Optional[Dict[str, Any]] = None):
        self.uid = uid_mod.make_uid("WorkflowModel")
        self.result_features = tuple(result_features)
        self.fitted_stages = dict(fitted_stages)
        self.dag = dag if dag is not None else compute_dag(result_features)
        self.parameters = parameters or {}
        self.blacklisted_features = list(blacklisted_features)
        self.rff_results = rff_results
        self.train_time_s = train_time_s
        #: per-stage fit/transform timings (OpSparkListener analog)
        self.stage_metrics = stage_metrics or {}
        #: rows of the training split (the cost database's denominator;
        #: 0 on loaded models — only fresh fits record costs)
        self.train_rows = int(train_rows)
        #: train-time sufficient statistics per fused moment column
        #: ({"<layer>:<column>": fitstats.SufficientStats}) — persisted
        #: with the model so a drift-triggered retrain can warm-start
        #: by monoid merge instead of rescanning (continual.py)
        self.fit_stats = dict(fit_stats) if fit_stats else {}
        #: lazily built compiled scoring engine (scoring.ScoringEngine);
        #: False = not yet attempted, None = attempted and unusable
        self._scoring_engine: Any = False
        #: attached planner.ExecutionPlan the scoring engine follows
        self._execution_plan: Any = None

    # -- stage access (OpWorkflowModel.getOriginStageOf analog) ------------
    def _resolved_dag(self) -> List[List[Transformer]]:
        out: List[List[Transformer]] = []
        for layer in self.dag:
            row: List[Transformer] = []
            for stage in layer:
                model = self.fitted_stages.get(stage.uid)
                if model is not None:
                    row.append(model)
                elif isinstance(stage, Transformer):
                    row.append(stage)
                else:
                    raise WorkflowError(
                        f"Estimator {stage.uid} has no fitted model")
            out.append(row)
        return out

    def stage_of(self, feature: Feature) -> Transformer:
        st = feature.origin_stage
        if st is None:
            raise WorkflowError(f"{feature.name!r} is a raw feature")
        return self.fitted_stages.get(st.uid, st)

    def validate(self, device: bool = True, suppress=()) -> list:
        """Static pre-flight check over the fitted model (lint.py):
        TMG1xx graph rules (incl. unfitted-estimator / dead-stage
        checks) plus — with ``device`` — the TMG2xx eval_shape
        pre-flight, which propagates ``jax.ShapeDtypeStruct``s through
        every layer's device computes without reading data or touching a
        device. Returns :class:`~transmogrifai_tpu.lint.Finding`
        records; the runner calls this before score-type runs."""
        from . import lint
        return lint.check_model(self, device=device, suppress=suppress)

    # -- planning (planner.py, the cost-based middle-end) ------------------
    def plan(self, cost_db=None, attach: bool = True):
        """Build this model's :class:`~transmogrifai_tpu.planner
        .ExecutionPlan` (dead-column liveness, CSE merges, per-stage
        tier assignment from ``cost_db``'s measured costs with static
        fallbacks) and — with ``attach`` — install it so the scoring
        engine follows it. Purely static: no data read, no device
        dispatched (lint.py's synthetic-store discipline)."""
        from . import planner
        p = planner.plan_model(self, cost_db=cost_db)
        if attach:
            self.attach_plan(p)
        return p

    def attach_plan(self, plan) -> "WorkflowModel":
        """Install an ExecutionPlan: the next ``scoring_engine()`` build
        applies its CSE aliases, dead-column pruning and measured tier
        decision (a memoized engine is invalidated so the plan takes
        effect). ``attach_plan(None)`` reverts to unplanned behavior."""
        self._execution_plan = plan
        self._scoring_engine = False          # rebuild under the plan
        return self

    def execution_plan(self):
        return self._execution_plan

    # -- scoring -----------------------------------------------------------

    def _engine_breaker(self):
        """THIS model's device-tier circuit breaker, shared by its
        engine routes (scoring_engine build, transform, score): one
        policy object instead of three independent ``except Exception``
        fallbacks. Per-model and held ON the instance (not the process
        registry): a broken plan or compile is a property of one model,
        must not downgrade other models served by the same process, and
        the breaker should die with its model rather than accumulate in
        a registry a long-lived server never empties. After
        ``failure_threshold`` consecutive device failures the per-layer
        host path serves WITHOUT re-attempting the failing engine each
        call, until the reset timeout lets a probe through."""
        brk = getattr(self, "_engine_breaker_obj", None)
        if brk is None:
            brk = self._engine_breaker_obj = resilience.CircuitBreaker(
                f"scoring.engine[{self.uid}]", failure_threshold=3,
                reset_timeout_s=60.0)
        return brk

    def scoring_engine(self, rebuild: bool = False, **engine_kw):
        """The compiled batched scoring engine for this model
        (scoring.ScoringEngine), built once and memoized. Returns None
        when the plan cannot be built (nothing fusable is not an error —
        the engine still runs, it just reports ``enabled() == False``)."""
        if rebuild or self._scoring_engine is False or engine_kw:
            from .scoring import ScoringEngine
            kw = dict(engine_kw)
            # the attached ExecutionPlan rides into every build unless
            # the caller pins plan= explicitly (plan=None opts out)
            kw.setdefault("plan", getattr(self, "_execution_plan", None))
            try:
                eng = ScoringEngine(self, **kw)
            except Exception:  # lint: broad-except — engine build failure falls back to the per-layer path
                logger.exception("scoring engine build failed; "
                                 "per-layer path stays active")
                self._engine_breaker().record_failure()
                eng = None
            if engine_kw and not rebuild:
                return eng          # custom engines aren't memoized
            self._scoring_engine = eng
        return self._scoring_engine

    def _use_engine(self, n_rows: int, engine) -> bool:
        """Routing decision for score/transform: ``engine=True`` forces,
        ``False`` forbids, ``"auto"`` requires a worthwhile batch (same
        reasoning as FUSE_MIN_ROWS) plus the bandwidth gate — and a
        closed (or probing) device-tier breaker either way. The breaker
        ``allow()`` may consume the open breaker's single half-open
        probe, so it only runs once every cheap gate has said yes and an
        engine ATTEMPT (build or dispatch, both of which report back via
        record_success/failure) follows. A failed build is such an
        attempt: it is retried under the same probe discipline rather
        than memoized as dead forever, so a transient build failure
        heals after the reset timeout."""
        if engine is False:
            return False
        from .scoring import SCORING_MIN_ROWS
        brk = self._engine_breaker()
        eng = self._scoring_engine
        if eng is not False and eng is not None:
            # engine already built: cheap gates first, breaker last —
            # a score/transform dispatch attempt follows a True
            if not eng.enabled():
                return False
            if engine is not True and n_rows < SCORING_MIN_ROWS:
                return False
            return brk.allow()
        # unbuilt (False) or a previously failed build (None): the
        # build itself is the breaker-governed attempt
        if engine is not True and n_rows < SCORING_MIN_ROWS:
            return False
        if not brk.allow():
            return False
        eng = self.scoring_engine(rebuild=(eng is None))
        if eng is None:
            return False        # build failed; record_failure already ran
        if not eng.enabled():
            # the probe (the build) succeeded but no dispatch follows —
            # report it so the breaker doesn't idle in half-open
            brk.record_success()
            return False
        return True

    def _transform_layers(self, data,
                          up_to: Optional[Feature] = None) -> ColumnStore:
        """The per-layer reference path (one host↔device crossing per
        DAG layer) — the engine's fallback and parity oracle."""
        targets = (up_to,) if up_to is not None else self.result_features
        raw_features = _raw_features_of(targets)
        store = _generate_raw_store(data, raw_features)
        needed = (None if up_to is None else
                  {s.uid for s in up_to.parent_stages()})
        for layer in self._resolved_dag():
            wanted = [m for m in layer
                      if needed is None or m.uid in needed]
            store = apply_layer_vectorized(wanted, store)
        return store

    def transform(self, data, up_to: Optional[Feature] = None,
                  engine: Any = "auto") -> ColumnStore:
        """Apply the fitted DAG (optionally only ancestors of ``up_to`` —
        computeDataUpTo, OpWorkflowModel.scala:106).

        With ``up_to=None`` big batches route through the compiled
        scoring engine (scoring.py): the whole device-capable chain runs
        as ONE jitted program instead of one crossing per layer.
        ``engine=True/False`` force/forbid the engine path (force is
        still subject to this model's device-tier circuit breaker —
        a known-bad engine serves from the host path, docs/robustness.md)."""
        if up_to is None:
            n = (data.n_rows if isinstance(data, ColumnStore)
                 else len(data) if hasattr(data, "__len__") else 0)
            if self._use_engine(n, engine):
                try:
                    out = self.scoring_engine().transform_store(data)
                    self._engine_breaker().record_success()
                    return out
                except Exception:  # lint: broad-except — breaker-governed device-tier fallback
                    self._engine_breaker().record_failure()
                    logger.exception(
                        "scoring engine transform failed; falling back "
                        "to the per-layer path")
        return self._transform_layers(data, up_to)

    def score(self, data, keep_intermediate: bool = False,
              engine: Any = "auto") -> ColumnStore:
        """Score: returns result feature columns (+ key columns)
        (OpWorkflowModel.score, :254-268). Routes through the compiled
        scoring engine for worthwhile batches (see ``transform``); the
        engine path pulls ONLY the result columns off the device."""
        if not keep_intermediate:
            n = (data.n_rows if isinstance(data, ColumnStore)
                 else len(data) if hasattr(data, "__len__") else 0)
            if self._use_engine(n, engine):
                try:
                    out = self.scoring_engine().score_store(data)
                    self._engine_breaker().record_success()
                    return out
                except Exception:  # lint: broad-except — breaker-governed device-tier fallback
                    self._engine_breaker().record_failure()
                    logger.exception(
                        "scoring engine score failed; falling back to "
                        "the per-layer path")
                    engine = False      # don't re-attempt via transform
        store = self.transform(data, engine=engine)
        if keep_intermediate:
            return store
        return store.select([f.name for f in self.result_features
                             if f.name in store])

    def score_and_evaluate(self, data, evaluator) -> Tuple[ColumnStore, Dict[str, Any]]:
        store = self.transform(data)
        metrics = evaluator.evaluate_all(store)
        return store.select(
            [f.name for f in self.result_features if f.name in store]), metrics

    def evaluate(self, data, evaluator) -> Dict[str, Any]:
        return self.score_and_evaluate(data, evaluator)[1]

    def score_fn(self) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        """Row-level scoring closure for serving (scoreFn / local module):
        Map[name, raw value] → Map[result name, raw value]. No bulk data."""
        layers = self._resolved_dag()
        result_names = [f.name for f in self.result_features]

        def score_row(row: Dict[str, Any]) -> Dict[str, Any]:
            acc = dict(row)
            for layer in layers:
                for m in layer:
                    acc[m.output_name] = m.transform_row(acc)
            return {n: acc[n] for n in result_names if n in acc}

        return score_row

    def model_insights(self, pred_feature: Optional[Feature] = None,
                       store: Optional[ColumnStore] = None):
        """Interpretability report (OpWorkflowModel.modelInsights :163-176)."""
        from .insights import ModelInsights
        return ModelInsights.extract(self, pred_feature, store)

    # -- persistence (OpWorkflowModelWriter/Reader) ------------------------
    def save(self, path: str, overwrite: bool = False) -> None:
        from .model_io import save_workflow_model
        save_workflow_model(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "WorkflowModel":
        from .model_io import load_workflow_model
        return load_workflow_model(path)

    # -- summaries ---------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"uid": self.uid,
                               "trainTimeSeconds": self.train_time_s,
                               "stageMetrics": self.stage_metrics,
                               "stages": {}}
        for uid, model in self.fitted_stages.items():
            s = getattr(model, "summary", None)
            if s is not None:
                out["stages"][uid] = s() if callable(s) else s
        return out

    def summary_pretty(self) -> str:
        """Human-readable summary: per-stage timing table
        (OpSparkListener / Table.scala pretty rendering) + stage JSON."""
        from .utils.table import Table
        parts = []
        if self.stage_metrics:
            rows = [[m.get("stageName", uid), uid,
                     m.get("fitSeconds"), m.get("compileSeconds"),
                     m.get("executeSeconds"),
                     m.get("layerTransformSeconds"),
                     "yes" if m.get("warmStarted") else ""]
                    for uid, m in sorted(self.stage_metrics.items())]
            parts.append(Table(
                ["stage", "uid", "fit s", "compile s", "execute s",
                 "layer transform s", "warm"],
                rows, name="Stage metrics").render())
        doc = self.summary()
        doc.pop("stageMetrics", None)
        parts.append(json.dumps(doc, indent=2, default=str))
        return "\n\n".join(parts)
