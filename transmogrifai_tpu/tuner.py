"""Offline autotuner — whole-program measurement over the declared
knob space (docs/tuning.md).

``python -m transmogrifai_tpu tune params.json --workload <dir>``
searches the registry-declared tunable knobs (:func:`config
.tunable_knobs`) by coordinate descent: per candidate config it boots
a REAL server (or fleet, when the params ask for workers) from that
config, re-drives the merged recorded workload through the PR 17
replay harness at recorded arrival offsets, and scores the leg on the
decomposed-latency objective (client e2e p99, or replayed rows/s).
Flare-style whole-program measurement, not microbenchmarks: the leg
pays queueing, coalescing, dispatch and scatter exactly as production
would.

Correctness is a GATE, not a score component: a candidate whose
replayed outputs drift from the recording past ``parity_tol`` (or
that fails requests) is rejected outright — a config that changes
numerics is never ranked. The search is seeded by the persisted
CostDatabase's measured phase costs where it has them (priors from
real runs beat cold defaults), bounded by each knob's declared
``tune_lo``/``tune_hi``, and stops when the wall-clock budget
expires — the incumbent-so-far wins, so the emitted config never
regresses the baseline on the measured objective.

Outputs: a validated ``params.tuned.json`` (the baseline params with
the winning knob values overlaid) plus a byte-stable tuning report
(winner, per-knob sensitivity, every leg measured; sorted keys, fixed
rounding, content digest — the plan-report stamping discipline).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import config

logger = logging.getLogger(__name__)

__all__ = ["run_tune", "tune", "tuner_stats", "reset_tuner_stats",
           "TunerError"]

#: probe multipliers one coordinate-descent pass tries around the
#: incumbent value of a float knob (int knobs use +/- steps too)
_PROBE_FACTORS = (0.25, 0.5, 2.0, 4.0)

#: coordinate-descent passes over the knob list before the search
#: declares convergence (a pass with zero improvement stops earlier)
_MAX_PASSES = 3

#: relative objective improvement a candidate must show to replace the
#: incumbent — measurement noise must not masquerade as a win
_MIN_IMPROVEMENT = 0.02


# ---------------------------------------------------------------------------
# always-on tallies (the engine_cache_stats discipline)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY = {"searches": 0, "legs_replayed": 0, "legs_failed_boot": 0,
          "candidates_evaluated": 0, "candidates_rejected_parity": 0,
          "candidates_improved": 0, "knobs_searched": 0,
          "budget_expirations": 0, "prior_seeds": 0}


def tuner_stats() -> Dict[str, Any]:
    """Process-wide tuner tallies (always on): searches run, replay
    legs measured, parity rejections, incumbent improvements."""
    with _TALLY_LOCK:
        return dict(_TALLY)


def reset_tuner_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


class TunerError(Exception):
    """Tuner misuse: no tunable knobs, unusable workload, bad params."""


# ---------------------------------------------------------------------------
# candidate legs — boot, replay, score
# ---------------------------------------------------------------------------

def _objective_score(replay: Dict[str, Any],
                     objective: str) -> Optional[float]:
    """One leg's scalar score — LOWER is better for both objectives
    (throughput negates), so the search minimizes uniformly. None when
    the leg measured nothing."""
    if objective == "throughput":
        rows = sum(int(m.get("rows", 0))
                   for m in (replay.get("models") or {}).values())
        dur = float(replay.get("durationS") or 0.0)
        return -(rows / dur) if rows and dur > 0 else None
    e2e = (replay.get("client") or {}).get("e2e") or {}
    p99 = e2e.get("p99Ms")
    return float(p99) if p99 is not None else None


def _apply_candidate(base_doc: Dict[str, Any],
                     values: Dict[str, Any]) -> Dict[str, Any]:
    doc = json.loads(json.dumps(base_doc))    # deep copy, JSON-safe
    cp = dict(doc.get("customParams") or {})
    cp.update(values)
    doc["customParams"] = cp
    return doc


def _boot_and_replay(params_doc: Dict[str, Any],
                     workload_doc: Dict[str, Any], *, speed: float,
                     parity_tol: float, timeout_s: float,
                     duration_s: Optional[float],
                     max_requests: Optional[int],
                     use_fleet: bool) -> Dict[str, Any]:
    """One measured leg: boot a server (or fleet) from the candidate
    params, replay the recorded workload against it, shut it down.
    Raises on boot failure; replay errors surface in the result."""
    from . import workload as workload_mod
    from .runner import OpParams

    with tempfile.TemporaryDirectory(prefix="tmog_tune_") as tmp:
        cand_path = os.path.join(tmp, "candidate.params.json")
        with open(cand_path, "w") as fh:
            json.dump(params_doc, fh, indent=1, sort_keys=True)
        if use_fleet:
            from . import fleet as fleet_mod
            from .runner import _numeric_custom_param
            params = OpParams.from_file(cand_path)
            n = _numeric_custom_param(params, "fleetWorkers", int,
                                      default=2, minimum=1)
            sup = fleet_mod.FleetSupervisor(cand_path, workers=n,
                                            probe_interval_s=0.1)
            sup.start()
            httpd = fleet_mod.serve_fleet_http(sup, port=0)
            try:
                host, port = httpd.server_address[:2]
                return workload_mod.replay_workload(
                    workload_doc, f"http://{host}:{port}", speed=speed,
                    timeout_s=timeout_s, parity_tol=parity_tol,
                    duration_s=duration_s, max_requests=max_requests)
            finally:
                httpd.shutdown()
                sup.stop(drain=True)
        from . import server as server_mod
        from .cli import build_server_from_params
        params = OpParams.from_file(cand_path)
        srv = build_server_from_params(params)
        httpd = server_mod.serve_http(srv, port=0)
        try:
            host, port = httpd.server_address[:2]
            return workload_mod.replay_workload(
                workload_doc, f"http://{host}:{port}", speed=speed,
                timeout_s=timeout_s, parity_tol=parity_tol,
                duration_s=duration_s, max_requests=max_requests)
        finally:
            srv.shutdown(drain=True)
            httpd.shutdown()


# ---------------------------------------------------------------------------
# priors — seed the search from the persisted CostDatabase
# ---------------------------------------------------------------------------

def _prior_seeds(params, knob_names: List[str]) -> Dict[str, Any]:
    """Measured-cost seeds for the first incumbent: where the persisted
    CostDatabase has scored-transform phase costs, start
    ``serveBatchDeadlineMs`` near the measured per-request transform
    cost (a hold much longer than the work it amortizes only adds
    latency; much shorter coalesces nothing). Knobs without a usable
    prior keep their baseline/default value."""
    from . import planner
    from .runner import OpWorkflowRunner
    seeds: Dict[str, Any] = {}
    try:
        db_path = OpWorkflowRunner._cost_db_path(params)
        if not db_path:
            return seeds
        db = planner.CostDatabase.load(db_path)
    except Exception:  # lint: broad-except — priors are an optimization, never a dependency
        return seeds
    if "serveBatchDeadlineMs" in knob_names:
        per_krow = (db.stage_cost("phase:transform", "device")
                    or db.stage_cost("phase:transform", "host"))
        if per_krow:
            lo, hi = config.knob_bounds("serveBatchDeadlineMs")
            # s/krow -> ms for a ~32-row micro-batch worth of work
            seed = per_krow * 1e3 * 32 / 1000.0
            seeds["serveBatchDeadlineMs"] = round(
                min(max(seed, lo), hi if hi != float("inf") else seed),
                4)
            _tally("prior_seeds")
    return seeds


# ---------------------------------------------------------------------------
# coordinate descent
# ---------------------------------------------------------------------------

def _probe_values(k: config.Knob, cur: Any) -> List[Any]:
    lo, hi = config.knob_bounds(k.name)
    if cur is None:
        cur = k.default
    if cur is None:
        cur = lo if lo != float("-inf") else 1.0
    cur = float(cur)
    vals: List[float] = []
    for f in _PROBE_FACTORS:
        v = cur * f if cur > 0 else (f - 1.0)
        v = min(max(v, lo), hi if hi != float("inf") else v)
        vals.append(v)
    # always probe the declared edges of the space too
    if lo != float("-inf"):
        vals.append(lo)
    if hi != float("inf"):
        vals.append(hi)
    out: List[Any] = []
    for v in vals:
        v = int(round(v)) if k.type == "int" else round(float(v), 4)
        if v != (int(cur) if k.type == "int" else round(cur, 4)) \
                and v not in out:
            out.append(v)
    return out


def tune(params_path: str, workload_doc: Dict[str, Any], *,
         objective: str = "p99", budget_s: float = 120.0,
         knobs: Optional[List[str]] = None, speed: float = 1.0,
         parity_tol: float = 1e-4, timeout_s: float = 30.0,
         duration_s: Optional[float] = None,
         max_requests: Optional[int] = None,
         use_fleet: Optional[bool] = None) -> Dict[str, Any]:
    """Run the search; returns ``{"tunedParams", "report"}``.

    The baseline config is ALWAYS the first measured leg and the first
    incumbent: the winner can only replace it by beating it on the
    replayed objective (by at least the noise floor), with score
    parity asserted — so the emitted config beats or matches the
    default by construction."""
    from .runner import OpParams

    if objective not in ("p99", "throughput"):
        raise TunerError(f"objective must be 'p99' or 'throughput', "
                         f"got {objective!r}")
    with open(params_path) as fh:
        base_doc = json.load(fh)
    params = OpParams.from_file(params_path)
    errors = config.check_custom_params(params.custom_params)
    if errors:
        raise TunerError(
            "baseline params invalid: "
            + "; ".join(msg for _k, msg in errors))

    tunable = {k.name: k for k in config.tunable_knobs()}
    if knobs:
        unknown = [n for n in knobs if n not in tunable]
        if unknown:
            raise TunerError(
                f"not tunable (declared tunable knobs: "
                f"{sorted(tunable)}): {unknown}")
        search = [tunable[n] for n in knobs]
    else:
        search = list(tunable.values())
    if not search:
        raise TunerError("no tunable knobs declared in the registry")
    if use_fleet is None:
        use_fleet = bool(
            (params.custom_params.get("fleetWorkers") or 0))  # lint: knob — presence probe decides boot topology
    _tally("searches")
    _tally("knobs_searched", len(search))

    t0 = time.monotonic()
    deadline = t0 + float(budget_s)
    legs: List[Dict[str, Any]] = []
    sensitivity: Dict[str, Dict[str, Any]] = {}

    def _leg(values: Dict[str, Any], label: str) -> Optional[float]:
        """Measure one candidate; returns its score or None when the
        leg was rejected (parity/failures) or could not boot."""
        doc = _apply_candidate(base_doc, values)
        cp = doc["customParams"]
        bad = config.check_custom_params(cp)
        if bad:   # a candidate off the declared surface is a bug
            raise TunerError(f"candidate invalid: {bad}")
        _tally("candidates_evaluated")
        try:
            replay = _boot_and_replay(
                doc, workload_doc, speed=speed, parity_tol=parity_tol,
                timeout_s=timeout_s, duration_s=duration_s,
                max_requests=max_requests, use_fleet=use_fleet)
        except Exception as e:  # lint: broad-except — a candidate that cannot boot is rejected, not fatal
            logger.warning("tune: leg %s failed to boot/replay: %r",
                           label, e)
            _tally("legs_failed_boot")
            legs.append({"label": label, "values": values,
                         "rejected": "boot/replay error",
                         "error": repr(e)[:200]})
            return None
        _tally("legs_replayed")
        score = _objective_score(replay, objective)
        rejected = None
        if replay.get("parityFailures"):
            rejected = "score parity"
            _tally("candidates_rejected_parity")
        elif replay.get("failed"):
            rejected = "failed requests"
        elif score is None:
            rejected = "nothing measured"
        legs.append({
            "label": label, "values": values,
            "score": None if score is None else round(score, 4),
            "rejected": rejected,
            "sent": replay.get("sent"),
            "failed": replay.get("failed"),
            "lateSends": replay.get("lateSends"),
            "parityChecked": replay.get("parityChecked"),
            "parityFailures": replay.get("parityFailures"),
            "p99Ms": ((replay.get("client") or {}).get("e2e") or {})
            .get("p99Ms")})
        return None if rejected else score

    # -- leg 0: the baseline is the first incumbent ------------------------
    incumbent: Dict[str, Any] = {}
    base_score = _leg({}, "baseline")
    if base_score is None:
        raise TunerError(
            "baseline config failed its replay leg (parity/failures) "
            "— fix the recording or the params before tuning")
    best_score = base_score

    # -- priors seed one candidate before the descent ----------------------
    seeds = _prior_seeds(params, [k.name for k in search])
    seeds = {n: v for n, v in seeds.items()
             if v != (base_doc.get("customParams") or {}).get(n)}
    if seeds and time.monotonic() < deadline:
        s = _leg(dict(seeds), "prior-seed")
        if s is not None and s < best_score * (1 - _MIN_IMPROVEMENT):
            incumbent, best_score = dict(seeds), s
            _tally("candidates_improved")

    # -- coordinate descent over the declared bounds -----------------------
    expired = False
    for pass_i in range(_MAX_PASSES):
        improved = False
        for k in search:
            cur = incumbent.get(
                k.name,
                (base_doc.get("customParams") or {}).get(k.name,
                                                         k.default))
            scores_this_knob: List[float] = []
            for v in _probe_values(k, cur):
                if time.monotonic() >= deadline:
                    expired = True
                    break
                cand = dict(incumbent)
                cand[k.name] = v
                s = _leg(cand, f"pass{pass_i}:{k.name}={v}")
                if s is None:
                    continue
                scores_this_knob.append(s)
                if s < best_score * (1 - _MIN_IMPROVEMENT):
                    incumbent, best_score = cand, s
                    improved = True
                    _tally("candidates_improved")
            sens = sensitivity.setdefault(
                k.name, {"legs": 0, "bestScore": None,
                         "worstScore": None})
            sens["legs"] += len(scores_this_knob)
            if scores_this_knob:
                lo_s = min(scores_this_knob + (
                    [sens["bestScore"]] if sens["bestScore"] is not None
                    else []))
                hi_s = max(scores_this_knob + (
                    [sens["worstScore"]]
                    if sens["worstScore"] is not None else []))
                sens["bestScore"] = round(lo_s, 4)
                sens["worstScore"] = round(hi_s, 4)
                sens["spread"] = round(hi_s - lo_s, 4)
            if expired:
                break
        if expired or not improved:
            break
    if expired:
        _tally("budget_expirations")

    tuned_doc = _apply_candidate(base_doc, incumbent)
    bad = config.check_custom_params(tuned_doc["customParams"])
    if bad:
        raise TunerError(f"tuned params failed validation: {bad}")

    report = {
        "objective": objective,
        "baselineScore": round(base_score, 4),
        "winnerScore": round(best_score, 4),
        "improvement": round(
            (base_score - best_score) / base_score, 4) if base_score
        else 0.0,
        "winner": {n: incumbent[n] for n in sorted(incumbent)},
        "searchedKnobs": sorted(k.name for k in search),
        "bounds": {k.name: [
            None if b in (float("inf"), float("-inf")) else b
            for b in config.knob_bounds(k.name)] for k in search},
        "sensitivity": {n: sensitivity[n]
                        for n in sorted(sensitivity)},
        "legs": legs,
        "legsMeasured": len(legs),
        "parityTol": parity_tol,
        "budgetExpired": expired,
        "fleet": bool(use_fleet),
    }
    # the plan-report stamping discipline: canonical serialization +
    # content digest, so identical measurements yield identical bytes
    canonical = json.dumps(report, sort_keys=True,
                           separators=(",", ":"), default=str)
    report["digest"] = "blake2b:" + hashlib.blake2b(
        canonical.encode(), digest_size=16).hexdigest()
    return {"tunedParams": tuned_doc, "report": report}


# ---------------------------------------------------------------------------
# CLI entry (``python -m transmogrifai_tpu tune``)
# ---------------------------------------------------------------------------

def run_tune(params_path: str, workload: str,
             out: Optional[str] = None, budget_s: float = 120.0,
             objective: str = "p99", knobs: Optional[str] = None,
             report: Optional[str] = None, speed: float = 1.0,
             parity_tol: float = 1e-4,
             duration_s: Optional[float] = None,
             max_requests: Optional[int] = None) -> int:
    """The ``tune`` subcommand: load/merge the recorded workload, run
    the search, write ``params.tuned.json`` + the tuning report."""
    import sys

    from . import workload as workload_mod

    try:
        if os.path.isdir(workload):
            doc = workload_mod.merge_workload_shards(workload)
        else:
            doc = workload_mod.load_workload(workload)
    except (OSError, ValueError) as e:
        print(f"tune: cannot load workload {workload!r}: {e}")
        return 1
    knob_list = ([n.strip() for n in knobs.split(",") if n.strip()]
                 if knobs else None)
    try:
        result = tune(params_path, doc, objective=objective,
                      budget_s=budget_s, knobs=knob_list, speed=speed,
                      parity_tol=parity_tol, duration_s=duration_s,
                      max_requests=max_requests)
    except (TunerError, OSError, ValueError) as e:
        print(f"tune: {e}", file=sys.stderr)
        return 1
    rep = result["report"]
    out = out or (os.path.splitext(params_path)[0] + ".tuned.json")
    report_path = report or (os.path.splitext(out)[0]
                             + ".tuning-report.json")
    for path, doc_out in ((out, result["tunedParams"]),
                          (report_path, rep)):
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc_out, fh, indent=1, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)
    better = rep["winnerScore"] <= rep["baselineScore"]
    print(f"tune: {rep['legsMeasured']} leg(s) measured over "
          f"{len(rep['searchedKnobs'])} knob(s), objective "
          f"{objective}: baseline {rep['baselineScore']} -> winner "
          f"{rep['winnerScore']} "
          f"({rep['improvement'] * 100:.1f}% better)"
          + (" [budget expired]" if rep["budgetExpired"] else ""))
    if rep["winner"]:
        for n, v in rep["winner"].items():
            print(f"  {n} = {v}")
    else:
        print("  baseline config already optimal over the searched "
              "space — tuned file keeps it")
    print(f"tune: tuned params -> {out}")
    print(f"tune: report -> {report_path}")
    return 0 if better else 1
