"""TransmogrifAI-TPU: a TPU-native AutoML framework for structured data.

A ground-up re-design of Salesforce TransmogrifAI's capabilities
(type-safe feature graph, automated feature engineering, sanity checking,
k-fold × grid model selection, model insights, portable serving) on
JAX/XLA: feature pipelines compile layer-by-layer into fused XLA
computations over sharded device arrays, and the model-selection grid
``vmap``s/``shard_map``s across the TPU mesh.
"""

__version__ = "0.2.0"

import logging as _logging

_logging.getLogger(__name__).addHandler(_logging.NullHandler())


def enable_logging(level: int = _logging.INFO) -> None:
    """Turn on human-readable progress logging for the package.

    The library itself only emits records (stage fit/transform timings,
    chunk-plan decisions, Pallas gate/fallback events, runner phases) —
    this attaches a stderr handler so a long run narrates itself, the
    OpSparkListener-console analog. The runner CLI calls it by default."""
    root = _logging.getLogger(__name__)
    root.setLevel(level)
    if not any(isinstance(h, _logging.StreamHandler)
               for h in root.handlers):
        h = _logging.StreamHandler()
        h.setFormatter(_logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s", "%H:%M:%S"))
        root.addHandler(h)


from . import lint  # noqa: F401  (pre-flight static checks, rule catalog)
from . import resilience  # noqa: F401  (faults/retries/breakers/quarantine)
from . import telemetry  # noqa: F401  (run tracing/metrics/listeners)
from . import types  # noqa: F401
from .columns import Column, ColumnStore, column_from_values  # noqa: F401
from .features import Feature, FeatureBuilder  # noqa: F401
from .vector_metadata import VectorColumnMetadata, VectorMetadata  # noqa: F401
from . import dsl  # noqa: F401  (attaches Feature operators)
from .workflow import Workflow, WorkflowModel  # noqa: F401
