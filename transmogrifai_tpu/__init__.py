"""TransmogrifAI-TPU: a TPU-native AutoML framework for structured data.

A ground-up re-design of Salesforce TransmogrifAI's capabilities
(type-safe feature graph, automated feature engineering, sanity checking,
k-fold × grid model selection, model insights, portable serving) on
JAX/XLA: feature pipelines compile layer-by-layer into fused XLA
computations over sharded device arrays, and the model-selection grid
``vmap``s/``shard_map``s across the TPU mesh.
"""

__version__ = "0.2.0"

from . import types  # noqa: F401
from .columns import Column, ColumnStore, column_from_values  # noqa: F401
from .features import Feature, FeatureBuilder  # noqa: F401
from .vector_metadata import VectorColumnMetadata, VectorMetadata  # noqa: F401
from . import dsl  # noqa: F401  (attaches Feature operators)
from .workflow import Workflow, WorkflowModel  # noqa: F401
