"""Model interpretability — workflow-level and per-record insights.

Parity targets: ``core/.../ModelInsights.scala`` and
``core/.../impl/insights/RecordInsightsLOCO.scala``.
"""
from .loco import RecordInsightsLOCO, parse_insights  # noqa: F401
from .model_insights import (DerivedFeatureInsight, FeatureInsights,  # noqa: F401
                             LabelSummary, ModelInsights)
from .corr import RecordInsightsCorr, RecordInsightsCorrModel  # noqa: F401
