"""ModelInsights — a fitted workflow's interpretability report.

Parity: ``core/.../ModelInsights.scala:72-110`` (``LabelSummary`` :291,
``FeatureInsights`` :336, ``Insights`` :372): merges the label summary,
per-derived-column insights (correlation, Cramér's V, model contribution,
SanityChecker drop reasons, RawFeatureFilter metrics), the selected model's
validation results, and stage lineage into one JSON-able report.

The heavy statistics are not recomputed here — they are harvested from the
fitted stages (SanityCheckerModel summary, ModelSelectorSummary, RFF
results), exactly as the reference reads stage metadata rather than data.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..columns import ColumnStore, NumericColumn, VectorColumn
from ..features import Feature
from ..types.feature_types import Prediction
from ..vector_metadata import VectorMetadata

__all__ = ["LabelSummary", "DerivedFeatureInsight", "FeatureInsights",
           "ModelInsights"]


@dataclass
class LabelSummary:
    """Label name + distribution (ModelInsights.LabelSummary :291)."""

    name: str
    is_categorical: bool = False
    distribution: Dict[str, float] = field(default_factory=dict)
    sample_size: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {"labelName": self.name, "categorical": self.is_categorical,
                "distribution": self.distribution,
                "sampleSize": self.sample_size}


@dataclass
class DerivedFeatureInsight:
    """One derived vector slot's insight row (FeatureInsights derived)."""

    column_name: str
    parent_feature: Optional[str] = None
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    corr_with_label: Optional[float] = None
    mean: Optional[float] = None
    variance: Optional[float] = None
    cramers_v: Optional[float] = None
    contribution: Optional[float] = None
    dropped: bool = False
    drop_reasons: List[str] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {"derivedFeatureName": self.column_name,
                "parentFeatureOrigins": self.parent_feature,
                "grouping": self.grouping,
                "indicatorValue": self.indicator_value,
                "corr": self.corr_with_label, "mean": self.mean,
                "variance": self.variance, "cramersV": self.cramers_v,
                "contribution": self.contribution,
                "dropped": self.dropped, "dropReasons": self.drop_reasons}


@dataclass
class FeatureInsights:
    """Per raw feature: its derived columns + RFF metrics."""

    feature_name: str
    feature_type: str = ""
    derived: List[DerivedFeatureInsight] = field(default_factory=list)
    rff_metrics: Optional[Dict[str, Any]] = None
    rff_exclusion: Optional[Dict[str, Any]] = None

    def to_json(self) -> Dict[str, Any]:
        return {"featureName": self.feature_name,
                "featureType": self.feature_type,
                "derivedFeatures": [d.to_json() for d in self.derived],
                "rawFeatureFilterMetrics": self.rff_metrics,
                "rawFeatureFilterExclusion": self.rff_exclusion}


class ModelInsights:
    """The merged report (ModelInsights.scala:72-110)."""

    def __init__(self, label: LabelSummary,
                 features: List[FeatureInsights],
                 selected_model_info: Dict[str, Any],
                 problem_type: str = "",
                 stage_info: Optional[Dict[str, Any]] = None):
        self.label = label
        self.features = features
        self.selected_model_info = selected_model_info
        self.problem_type = problem_type
        self.stage_info = stage_info or {}

    # -- extraction --------------------------------------------------------
    @staticmethod
    def extract(workflow_model, pred_feature: Optional[Feature] = None,
                store: Optional[ColumnStore] = None) -> "ModelInsights":
        """Harvest insights from a fitted WorkflowModel.

        ``store``: optional training/scoring data — supplies the label
        distribution and the final vector metadata when given.
        """
        pred_feature = pred_feature or next(
            (f for f in workflow_model.result_features
             if issubclass(f.ftype, Prediction)), None)
        if pred_feature is None:
            raise ValueError("No Prediction result feature in this workflow")

        selected = workflow_model.stage_of(pred_feature)
        label_f = selected.input_features[0]
        vector_f = selected.input_features[1]

        # sanity checker (walk up from the model's vector input)
        sanity = None
        st = vector_f.origin_stage
        if st is not None:
            cand = workflow_model.fitted_stages.get(st.uid)
            if cand is not None and hasattr(cand, "summary_") \
                    and cand.summary_ is not None:
                sanity = cand

        meta = ModelInsights._vector_metadata(workflow_model, vector_f, store)
        contributions = ModelInsights._contributions(selected)

        label = ModelInsights._label_summary(label_f, store)
        features = ModelInsights._feature_insights(
            vector_f, sanity, meta, contributions, workflow_model)

        sel_info: Dict[str, Any] = {}
        summ = getattr(selected, "selector_summary", None)
        if summ is not None:
            sel_info = summ.to_json()
        else:
            s = getattr(selected, "summary", None)
            if callable(s):
                sel_info = s()

        task = getattr(selected, "task", "")
        stage_info = {
            uid: type(m).__name__
            for uid, m in workflow_model.fitted_stages.items()}
        return ModelInsights(label, features, sel_info, task, stage_info)

    @staticmethod
    def _vector_metadata(workflow_model, vector_f: Feature,
                         store: Optional[ColumnStore]
                         ) -> Optional[VectorMetadata]:
        if store is None:
            return None
        out = workflow_model.transform(store, up_to=vector_f)
        col = out.get(vector_f.name)
        if isinstance(col, VectorColumn):
            return col.metadata
        return None

    @staticmethod
    def _label_summary(label_f: Feature,
                       store: Optional[ColumnStore]) -> LabelSummary:
        summary = LabelSummary(name=label_f.name)
        if store is not None and label_f.name in store:
            col = store[label_f.name]
            if isinstance(col, NumericColumn):
                y = col.values.astype(np.float64)
                summary.sample_size = int(y.size)
                uniq, counts = np.unique(y, return_counts=True)
                if uniq.size <= 30:
                    summary.is_categorical = True
                    summary.distribution = {
                        str(u): int(c) for u, c in zip(uniq, counts)}
                else:
                    summary.distribution = {
                        "min": float(y.min()), "max": float(y.max()),
                        "mean": float(y.mean()), "variance": float(y.var())}
        return summary

    @staticmethod
    def _contributions(selected) -> Optional[np.ndarray]:
        """Per-slot importance from the winning model: |coef| for linear
        heads, split-frequency importance for tree ensembles."""
        inner = getattr(selected, "inner", selected)
        coef = getattr(inner, "coefficients", None)
        if coef is not None:
            c = np.abs(np.asarray(coef, dtype=np.float64))
            return c.mean(axis=0) if c.ndim == 2 else c
        trees = getattr(inner, "trees", None)
        if trees and "feat" in trees and "thr" in trees:
            feat = np.asarray(trees["feat"])      # [n_trees, n_nodes]
            thr = np.asarray(trees["thr"])
            mask = np.isfinite(thr)               # real splits only
            used = feat[mask].astype(np.int64)
            if used.size:
                gain = trees.get("gain")
                if gain is not None:
                    # gain-weighted impurity reduction — the reference's
                    # featureImportances semantics (treeinterpreter style);
                    # XGB gains can be negative under its -inf split floor
                    w = np.maximum(
                        np.asarray(gain, dtype=np.float64)[mask], 0.0)
                else:  # older saved models: split-frequency fallback
                    w = np.ones(used.shape, dtype=np.float64)
                d = int(used.max()) + 1
                imp = np.bincount(used, weights=w, minlength=d)
                tot = imp.sum()
                if tot <= 0:   # e.g. XGB where every gain clipped to 0
                    imp = np.bincount(used, minlength=d).astype(np.float64)
                    tot = imp.sum()
                return imp / tot if tot > 0 else imp
        return None

    @staticmethod
    def _feature_insights(vector_f: Feature, sanity, meta, contributions,
                          workflow_model) -> List[FeatureInsights]:
        derived: List[DerivedFeatureInsight] = []
        stats_by_name: Dict[str, Dict[str, Any]] = {}
        dropped_by_name: Dict[str, List[str]] = {}
        cramers_by_group: Dict[str, float] = {}
        if sanity is not None:
            s = sanity.summary_
            for cs in s.column_stats:
                stats_by_name[cs["name"]] = cs
            for dr in s.dropped:
                dropped_by_name[dr["name"]] = dr["reasons"]
            for cs in s.categorical_stats:
                cramers_by_group[cs["group"]] = cs["cramersV"]

        if meta is not None and meta.size:
            kept_names = meta.column_names()
            for i, cm in enumerate(meta.columns):
                st = stats_by_name.get(cm.column_name(), {})
                group = (f"{cm.parent_feature_name}_{cm.grouping}"
                         if cm.grouping else None)
                derived.append(DerivedFeatureInsight(
                    column_name=kept_names[i],
                    parent_feature=cm.parent_feature_name,
                    grouping=cm.grouping,
                    indicator_value=cm.indicator_value,
                    corr_with_label=st.get("corrWithLabel"),
                    mean=st.get("mean"), variance=st.get("variance"),
                    cramers_v=cramers_by_group.get(group) if group else None,
                    contribution=(float(contributions[i])
                                  if contributions is not None
                                  and i < len(contributions) else None)))
            # dropped columns are absent from the kept metadata — surface
            # them from the sanity summary so drop reasons aren't lost
            present = set(kept_names)
            for name, rs in dropped_by_name.items():
                if name not in present:
                    st = stats_by_name.get(name, {})
                    derived.append(DerivedFeatureInsight(
                        column_name=name,
                        corr_with_label=st.get("corrWithLabel"),
                        mean=st.get("mean"), variance=st.get("variance"),
                        dropped=True, drop_reasons=rs))
        elif stats_by_name:
            kept = set()
            if sanity is not None and getattr(sanity, "keep_indices", None):
                kept = {sanity.summary_.names[i] for i in sanity.keep_indices}
            j = 0
            for name, st in stats_by_name.items():
                contrib = None
                if name in kept and contributions is not None \
                        and j < len(contributions):
                    contrib = float(contributions[j])
                if name in kept:
                    j += 1
                derived.append(DerivedFeatureInsight(
                    column_name=name,
                    corr_with_label=st.get("corrWithLabel"),
                    mean=st.get("mean"), variance=st.get("variance"),
                    contribution=contrib,
                    dropped=name in dropped_by_name,
                    drop_reasons=dropped_by_name.get(name, [])))

        for d in derived:
            if d.column_name in dropped_by_name:
                d.dropped = True
                d.drop_reasons = dropped_by_name[d.column_name]

        # group by parent raw feature; RFF metrics attach per raw feature
        rff = workflow_model.rff_results
        rff_metrics: Dict[str, Dict[str, Any]] = {}
        rff_excl: Dict[str, Dict[str, Any]] = {}
        if rff is not None:
            for m in rff.metrics:
                if m.key is None:
                    rff_metrics[m.name] = m.to_json()
            for r in rff.exclusion_reasons:
                if r.key is None:
                    rff_excl[r.name] = r.to_json()

        by_parent: Dict[str, FeatureInsights] = {}
        raws = vector_f.raw_features()
        raw_types = {f.name: f.ftype.__name__ for f in raws}
        for d in derived:
            parent = d.parent_feature or vector_f.name
            fi = by_parent.setdefault(parent, FeatureInsights(
                feature_name=parent,
                feature_type=raw_types.get(parent, ""),
                rff_metrics=rff_metrics.get(parent),
                rff_exclusion=rff_excl.get(parent)))
            fi.derived.append(d)
        for f in workflow_model.blacklisted_features:
            by_parent.setdefault(f.name, FeatureInsights(
                feature_name=f.name, feature_type=f.ftype.__name__,
                rff_metrics=rff_metrics.get(f.name),
                rff_exclusion=rff_excl.get(f.name)))
        return [by_parent[k] for k in sorted(by_parent)]

    # -- output ------------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        return {"label": self.label.to_json(),
                "features": [f.to_json() for f in self.features],
                "selectedModelInfo": self.selected_model_info,
                "problemType": self.problem_type,
                "stageInfo": self.stage_info}

    def pretty(self) -> str:
        """Human-readable summary (summaryPretty analog)."""
        lines = [f"Model insights — problem type: {self.problem_type}",
                 f"Label: {self.label.name} "
                 f"(n={self.label.sample_size})", ""]
        best = self.selected_model_info.get("bestModelName")
        if best:
            lines.append(f"Best model: {best} "
                         f"{self.selected_model_info.get('bestModelParams')}")
        ev = self.selected_model_info.get("holdoutEvaluation")
        if ev:
            lines.append("Holdout: " + ", ".join(
                f"{k}={v:.4f}" for k, v in ev.items()
                if isinstance(v, (int, float))))
        lines.append("")
        rows = []
        for fi in self.features:
            for d in fi.derived:
                rows.append((d.column_name,
                             d.corr_with_label, d.contribution, d.dropped))
        rows.sort(key=lambda r: (r[2] is None,
                                 -(abs(r[2]) if r[2] is not None else 0.0)))
        lines.append(f"{'derived feature':<40} {'corr':>8} "
                     f"{'contrib':>10} dropped")
        for name, corr, contrib, dropped in rows[:40]:
            c = f"{corr:+.3f}" if corr is not None else "-"
            t = f"{contrib:.4f}" if contrib is not None else "-"
            lines.append(f"{name:<40} {c:>8} {t:>10} "
                         f"{'yes' if dropped else ''}")
        return "\n".join(lines)
