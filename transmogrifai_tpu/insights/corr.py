"""RecordInsightsCorr — correlation-based per-record feature attributions.

Parity: ``core/.../impl/insights/RecordInsightsCorr.scala:55-165``: fit
computes the correlation of every feature column with every prediction
score column plus a feature normalizer (MinMax by default); transform
scores each row as ``importance[k, j] = corr[k, j] * normalized_x[j]`` and
keeps the top-K absolute contributors per prediction column.

TPU re-design: correlations come from ONE fused gram matmul over the
[features | scores] matrix (the SanityChecker moments kernel pattern), and
the per-row importances are one [n, p, d] broadcast multiply — no per-row
loop.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..columns import Column, ColumnStore, PredictionColumn, TextColumn, VectorColumn
from ..stages.base import (AllowLabelAsInput, Estimator, FittedModel,
                           FixedArity, InputSpec, register_stage)
from ..types.feature_types import OPVector, Prediction, TextMap

__all__ = ["RecordInsightsCorr", "RecordInsightsCorrModel"]


def _scores_of(col: PredictionColumn) -> np.ndarray:
    """[n, p] score matrix: probabilities when present, else prediction."""
    if col.probability.shape[1] > 0:
        return np.asarray(col.probability, dtype=np.float64)
    return np.asarray(col.prediction, dtype=np.float64)[:, None]


@register_stage
class RecordInsightsCorrModel(FittedModel, AllowLabelAsInput):
    """Fitted: corr [p, d] + MinMax normalizer stats."""

    operation_name = "recordInsightsCorr"
    output_type = TextMap

    def __init__(self, corr: Optional[np.ndarray] = None,
                 x_min: Optional[np.ndarray] = None,
                 x_max: Optional[np.ndarray] = None,
                 top_k: int = 20,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.corr = np.asarray(corr) if corr is not None else None
        self.x_min = np.asarray(x_min) if x_min is not None else None
        self.x_max = np.asarray(x_max) if x_max is not None else None
        self.top_k = top_k

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Prediction, OPVector)

    def transform_columns(self, store: ColumnStore) -> Column:
        xcol = store[self.input_features[1].name]
        assert isinstance(xcol, VectorColumn)
        X = np.asarray(xcol.values, dtype=np.float64)
        n, d = X.shape
        meta = xcol.metadata
        names = (meta.column_names() if meta is not None and meta.size == d
                 else [f"f_{i}" for i in range(d)])

        span = np.maximum(self.x_max - self.x_min, 1e-12)
        Xn = (X - self.x_min[None, :]) / span[None, :]       # MinMax norm
        corr = np.nan_to_num(self.corr, nan=0.0)             # [p, d]
        imp = corr[None, :, :] * Xn[:, None, :]              # [n, p, d]

        k = min(self.top_k, d)
        out = np.empty((n,), dtype=object)
        # rank per (row, pred col) by |importance|
        order = np.argsort(-np.abs(imp), axis=2, kind="stable")[:, :, :k]
        p = corr.shape[0]
        for i in range(n):
            row: Dict[str, List[List[float]]] = {}
            for kk in range(p):
                for j in order[i, kk]:
                    v = float(imp[i, kk, j])
                    if v != 0.0:
                        row.setdefault(names[j], []).append(
                            [int(kk), round(v, 10)])
            out[i] = json.dumps(row)
        return TextColumn(TextMap, out)

    def get_model_state(self) -> Dict[str, Any]:
        return {"corr": self.corr, "x_min": self.x_min, "x_max": self.x_max}


@register_stage
class RecordInsightsCorr(Estimator, AllowLabelAsInput):
    """Estimator(Prediction, OPVector) → TextMap of per-record insights."""

    operation_name = "recordInsightsCorr"
    output_type = TextMap

    def __init__(self, top_k: int = 20, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.top_k = top_k

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(Prediction, OPVector)

    def fit_columns(self, store: ColumnStore) -> RecordInsightsCorrModel:
        pcol = store[self.input_features[0].name]
        xcol = store[self.input_features[1].name]
        assert isinstance(pcol, PredictionColumn)
        assert isinstance(xcol, VectorColumn)
        P = _scores_of(pcol)                       # [n, p]
        X = np.asarray(xcol.values, dtype=np.float64)
        Z = np.concatenate([X, P], axis=1)
        Zc = Z - Z.mean(axis=0)
        cov = Zc.T @ Zc / max(len(Z) - 1, 1)
        std = np.sqrt(np.maximum(np.diagonal(cov), 0.0))
        with np.errstate(divide="ignore", invalid="ignore"):
            corr_full = cov / np.maximum(np.outer(std, std), 1e-30)
        d = X.shape[1]
        corr = corr_full[d:, :d]                   # [p, d]
        return RecordInsightsCorrModel(
            corr=corr, x_min=X.min(axis=0), x_max=X.max(axis=0),
            top_k=self.top_k)
