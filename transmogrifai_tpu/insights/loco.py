"""RecordInsightsLOCO — per-row leave-one-column-out feature attributions.

Parity: ``core/.../impl/insights/RecordInsightsLOCO.scala:99-170`` — for each
row, zero each vector slot, re-score, record the score diff, and keep the
top-K positive and negative contributors.

TPU re-design: the reference loops columns sequentially per row inside a
UDF. Here the whole thing is one batched computation: for a chunk of C
columns we materialize the (C, n, d) zeroed tensor, flatten to (C·n, d), and
run a single model forward — XLA sees one big matmul-shaped batch instead of
n·d scalar re-scores. Chunking bounds peak memory at roughly
``chunk · n · d`` floats.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..columns import Column, ColumnStore, TextColumn, VectorColumn
from ..stages.base import FixedArity, InputSpec, Transformer, register_stage
from ..types.feature_types import OPVector, TextMap
from ..vector_metadata import VectorMetadata

__all__ = ["RecordInsightsLOCO", "parse_insights"]


@register_stage
class RecordInsightsLOCO(Transformer):
    """Transformer(OPVector) → Text (JSON per row of top-K LOCO diffs).

    ``model`` is the fitted :class:`PredictorModel` whose score is being
    explained (the reference takes the model as a constructor argument the
    same way, RecordInsightsLOCO.scala:60).
    """

    operation_name = "recordInsightsLOCO"
    output_type = TextMap

    def __init__(self, model: Optional[Any] = None, top_k: int = 20,
                 column_chunk: int = 128, model_uid: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.model = model
        self.model_uid = model_uid or getattr(model, "uid", None)
        self.top_k = top_k
        self.column_chunk = column_chunk

    @property
    def input_spec(self) -> InputSpec:
        return FixedArity(OPVector)

    # -- scoring helpers ---------------------------------------------------
    def _strength(self, pred: np.ndarray, prob: np.ndarray) -> np.ndarray:
        """Scalar score to diff: P(class 1) for binary, max-prob for
        multiclass, the prediction for regression."""
        prob = np.asarray(prob)
        if prob.ndim == 2 and prob.shape[1] == 2:
            return prob[:, 1]
        if prob.ndim == 2 and prob.shape[1] > 2:
            return prob.max(axis=1)
        return np.asarray(pred, dtype=np.float64)

    def loco_diffs(self, X: np.ndarray) -> np.ndarray:
        """[d, n] score diffs: base − score-with-column-zeroed."""
        n, d = X.shape
        pred0, _raw0, prob0 = self.model.predict_arrays(X)
        base = self._strength(pred0, prob0)              # [n]
        diffs = np.zeros((d, n), dtype=np.float64)
        for start in range(0, d, self.column_chunk):
            cols = np.arange(start, min(start + self.column_chunk, d))
            C = cols.shape[0]
            Xz = np.broadcast_to(X, (C, n, d)).copy()    # [C, n, d]
            Xz[np.arange(C), :, cols] = 0.0
            pred, _raw, prob = self.model.predict_arrays(
                Xz.reshape(C * n, d))
            s = self._strength(pred, prob).reshape(C, n)
            diffs[cols] = base[None, :] - s
        return diffs

    # -- stage API ---------------------------------------------------------
    def transform_columns(self, store: ColumnStore) -> Column:
        if self.model is None:
            raise RuntimeError(
                f"{self.stage_name()}: model is unbound. The model reference "
                "is serialized by uid (model_uid="
                f"{self.model_uid!r}); load via WorkflowModel (which rebinds "
                "it) or pass model= explicitly.")
        col = store[self.input_features[0].name]
        assert isinstance(col, VectorColumn)
        X = np.asarray(col.values, dtype=np.float64)
        n, d = X.shape
        meta: Optional[VectorMetadata] = col.metadata
        names = (meta.column_names() if meta is not None and meta.size == d
                 else [f"f_{i}" for i in range(d)])

        diffs = self.loco_diffs(X)                       # [d, n]
        k = min(self.top_k, d)
        out = np.empty((n,), dtype=object)
        # [d, n] per-row rank; stable so tied |diffs| keep feature order
        order = np.argsort(-np.abs(diffs), axis=0, kind="stable")
        for i in range(n):
            top = order[:k, i]
            row = {names[j]: round(float(diffs[j, i]), 10)
                   for j in top if diffs[j, i] != 0.0}
            out[i] = json.dumps(row)
        return TextColumn(TextMap, out)

    def get_params(self) -> Dict[str, Any]:
        p = super().get_params()
        p.pop("model", None)  # re-bound by uid: see rebind_stages
        p["model_uid"] = self.model_uid
        return p

    def copy(self):
        new = super().copy()
        new.model = self.model  # carry the live reference through copy_dag
        return new

    def rebind_stages(self, stage_by_uid: Dict[str, Any]) -> None:
        """Re-attach the scored model after load (called by model_io)."""
        if self.model is None and self.model_uid:
            self.model = stage_by_uid.get(self.model_uid)


def parse_insights(value: str) -> Dict[str, float]:
    """Parse one LOCO output cell (RecordInsightsParser analog)."""
    return {} if value is None else json.loads(value)
