"""Temporal workload tier — columnar point-in-time aggregation and a
streaming hash join over the staged input pipeline.

TransmogrifAI's reader layer is half the paper: ``AggregatedReader``
computes leakage-safe point-in-time feature aggregates against an
event-time cutoff and ``JoinedDataReader`` joins multiple keyed sources
(``DataReader.scala:206-230``, ``JoinedDataReader.scala:54-418``,
PAPER.md L3). The seed-era analogs in ``readers/data_readers.py`` are
row-wise Python loops over ``List[Dict]`` — per-record ``extract_fn``
frames, per-record dict probes — that never touch the PR 9 columnar
pipeline. This module is their native execution tier (the Flare framing:
compile the relational join/aggregate down to vectorized kernels instead
of interpreted per-record dispatch; the tf.data framing: run it inside
the input pipeline's map stages so it overlaps IO — PAPERS.md):

* **Columnar aggregation engine** — group-by-key + per-key cutoff +
  time-windowed monoid folds computed vectorized over columnar batches
  (``avro.ColumnarRecords``, the :class:`Table` facade, joined tables):
  one ``np.argsort(kind="stable")`` groups every key, ``np.searchsorted``
  (explicit ``side=``) finds segment bounds, boolean masks apply the
  cutoff/window discipline, and each key's surviving values fold through
  the SAME ``utils/aggregators`` monoid object the row-wise reader uses
  — so the output is **bit-identical** to the row-wise fold (asserted in
  tests across monoid families, cutoff shapes and join types).
  ``AggregateReader``/``ConditionalReader`` auto-route here when their
  source yields a columnar batch (:func:`route_aggregate`); a columnar
  failure trips the ``temporal.columnar`` breaker and degrades to the
  row-wise fold, never a crash.
* **Parallel partial aggregation** — :func:`aggregate_tables` /
  :func:`aggregate_directory` / :func:`join_aggregate_directory` run
  decode → (join) → filter/group inside the PR 9 ``map_ordered`` worker
  pool, so aggregation overlaps file IO; per-key value segments merge in
  submission order and fold ONCE per key, which keeps the float fold
  order — and therefore the bits — identical to the serial pass.
* **Streaming hash join** — :class:`~transmogrifai_tpu.readers.
  data_readers.TemporalJoinReader` consistent-hash partitions the build
  side into bounded per-partition hash tables (overflow rows spill to
  the dead-letter quarantine instead of eating the heap), probes the
  left stream in order, and takes a fully vectorized path when both
  sides are columnar. ``JoinedAggregateDataReader`` reroutes on top, so
  the joined-then-aggregate composition is columnar end-to-end.
* **Cutoff leakage linting** — :func:`check_temporal` (rules TMG7xx,
  extending TMG105's graph-taint story to event time): a predictor
  aggregated with NO cutoff while a response exists is a *static* error
  (TMG701), a response-side event window is an error (TMG702 — the
  response fold is strictly-after-cutoff, a window reaches back across
  it into the predictor window), a join key derived from a
  response-side field is a warning (TMG703). Findings flow through the
  existing failOn / lintSuppress / telemetry machinery and the runner
  blocks them BEFORE any reader I/O.

Cutoff semantics (pinned; docs/readers.md has the table): with a cutoff
``c``, predictors fold events with ``ts < c`` (within
``[c - window, c)`` when a window is declared) and responses fold events
with ``ts > c`` — strictly after, so the cutoff event itself (a
conditional reader's triggering event) lands in NEITHER fold.

Knobs ride in the runner as ``customParams.aggregateColumnar`` (tri-state
auto) / ``joinPartitions`` / ``joinTableMaxRows``; ``TMOG_TEMPORAL=0`` is
the kill switch. Always-on :func:`temporal_stats` tallies are stamped on
every runner metrics doc and every bench doc.
"""
from __future__ import annotations

import glob as _glob
import hashlib
import logging
import os
import threading
from typing import (Any, Callable, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Tuple)

import numpy as np

from . import resilience, telemetry

logger = logging.getLogger(__name__)

__all__ = [
    "TemporalError", "field", "column_key_of",
    "Table", "table_from_records", "concat_tables",
    "route_aggregate", "aggregate_tables", "aggregate_directory",
    "join_aggregate_directory",
    "check_temporal",
    "set_run_defaults", "columnar_mode", "join_partitions",
    "join_table_max_rows", "set_aggregate_tier_hint",
    "aggregate_tier_hint", "last_route_contested",
    "temporal_stats", "reset_temporal_stats",
    "DEFAULT_JOIN_PARTITIONS", "DEFAULT_JOIN_TABLE_MAX_ROWS",
]

#: default consistent-hash partition count for the streaming join's
#: build-side tables (``customParams.joinPartitions``)
DEFAULT_JOIN_PARTITIONS = 8

#: default per-partition build-table bound (unique keys); overflow rows
#: spill to the quarantine sink (``customParams.joinTableMaxRows``)
DEFAULT_JOIN_TABLE_MAX_ROWS = 1_000_000

#: ``TMOG_TEMPORAL=0`` forces every aggregate/join back to the row-wise
#: path (kill switch, the TMOG_PIPELINE discipline)
TEMPORAL_ENABLED = os.environ.get("TMOG_TEMPORAL", "1") != "0"


class TemporalError(ValueError):
    """Configuration error in the temporal tier (bad knob, unroutable
    columnar request)."""


# ---------------------------------------------------------------------------
# run-scoped configuration (the runner installs customParams here)
# ---------------------------------------------------------------------------

_RUN_LOCK = threading.Lock()
_RUN: Dict[str, Any] = {"columnar": None, "join_partitions": None,
                        "join_table_max_rows": None,
                        "aggregate_hint": None}


def set_run_defaults(columnar: Any = None,
                     join_partitions: Optional[int] = None,
                     join_table_max_rows: Optional[int] = None,
                     aggregate_hint: Optional[str] = None
                     ) -> Dict[str, Any]:
    """Install run-scoped temporal defaults (the runner's
    ``aggregateColumnar`` / ``joinPartitions`` / ``joinTableMaxRows``
    knobs, plus the planner's measured aggregation-tier hint); returns
    the PREVIOUS dict so the runner can restore it in its finally
    block. ``None`` means "module default"."""
    with _RUN_LOCK:
        prev = dict(_RUN)
        _RUN.update(columnar=columnar, join_partitions=join_partitions,
                    join_table_max_rows=join_table_max_rows,
                    aggregate_hint=aggregate_hint)
    return prev


def columnar_mode() -> Any:
    """The effective columnar-aggregation mode: ``False`` (forced off —
    the ``TMOG_TEMPORAL=0`` kill switch wins over everything), ``True``
    (forced on: a non-columnar source still falls back, tallied), or
    ``"auto"`` (columnar when the source yields a columnar batch)."""
    if not TEMPORAL_ENABLED:
        return False
    v = _RUN["columnar"]
    if v is None or v == "auto":
        return "auto"
    return bool(v)


def set_aggregate_tier_hint(hint: Optional[str]) -> Optional[str]:
    """Install the planner's MEASURED columnar-vs-rowwise aggregation
    tier (``"columnar"`` / ``"rowwise"`` / None = no evidence): the
    runner computes it from the cost database's
    ``phase:temporal.route_aggregate`` observations and installs it
    run-scoped (restored in its finally). Returns the previous hint.
    The hint steers the ``"auto"`` route ONLY — an explicit
    ``aggregateColumnar`` knob always wins (contradictions surface as a
    TMG405 advisory instead)."""
    with _RUN_LOCK:
        prev = _RUN["aggregate_hint"]
        _RUN["aggregate_hint"] = hint
    return prev


def aggregate_tier_hint() -> Optional[str]:
    return _RUN["aggregate_hint"]


#: every Nth auto-routed aggregate under a "rowwise" hint still runs
#: the columnar engine (the breaker's half-open idea): without the
#: probe the hint is a one-way ratchet — once the db says rowwise the
#: columnar tier is never re-measured, so a decision made on one
#: unrepresentative workload (tiny folds where columnar's fixed setup
#: dominates) could never flip back as rowwise observations keep
#: refreshing and columnar's s/krow freezes forever
HINT_PROBE_EVERY = 16
_HINT_COUNT = [0]


def _hint_stand_down() -> bool:
    """True when a "rowwise" hint should actually suppress the columnar
    route for THIS pass (every HINT_PROBE_EVERY-th pass probes)."""
    with _RUN_LOCK:
        _HINT_COUNT[0] += 1
        return _HINT_COUNT[0] % HINT_PROBE_EVERY != 0


def join_partitions(explicit: Optional[int] = None) -> int:
    v = explicit if explicit is not None else _RUN["join_partitions"]
    return int(v) if v is not None else DEFAULT_JOIN_PARTITIONS


def join_table_max_rows(explicit: Optional[int] = None) -> Optional[int]:
    v = explicit if explicit is not None else _RUN["join_table_max_rows"]
    return int(v) if v is not None else DEFAULT_JOIN_TABLE_MAX_ROWS


# ---------------------------------------------------------------------------
# always-on tallies (runner/bench stamp these on every doc)
# ---------------------------------------------------------------------------

_TALLY_LOCK = threading.Lock()
_TALLY: Dict[str, int] = {
    "columnar_aggregates": 0, "rowwise_aggregates": 0,
    "parallel_aggregates": 0, "columnar_fallbacks": 0,
    "hint_fallbacks": 0,
    "aggregate_rows": 0, "aggregate_keys": 0,
    "joins": 0, "columnar_joins": 0, "join_rows": 0,
    "join_matched": 0, "join_unmatched": 0, "join_spilled_rows": 0,
}


def temporal_stats() -> Dict[str, int]:
    """Snapshot of the process-wide temporal-tier tallies — always on
    (the ``fitstats_stats`` discipline), stamped on every runner metrics
    doc and every bench doc. ``columnar_fallbacks`` counts aggregates
    that ASKED for the columnar tier but degraded to row-wise (source
    not columnar under forced-on, breaker open, or a columnar failure);
    ``join_spilled_rows`` counts build-side rows quarantined by the
    bounded hash tables."""
    with _TALLY_LOCK:
        return dict(_TALLY)


def reset_temporal_stats() -> None:
    with _TALLY_LOCK:
        for k in _TALLY:
            _TALLY[k] = 0


def _tally(key: str, n: int = 1) -> None:
    with _TALLY_LOCK:
        _TALLY[key] += n


# ---------------------------------------------------------------------------
# field helpers
# ---------------------------------------------------------------------------


def field(name: str) -> Callable[[Mapping], Any]:
    """A record → value extractor by field name, carrying the
    ``_column_key`` marker the columnar fast paths key on (the same
    marker ``FeatureBuilder.from_column`` sets). Use it for the
    ``key_fn`` / ``timestamp_fn`` / ``condition_fn`` of temporal readers
    so they can route columnar::

        AggregateReader(base, timestamp_fn=temporal.field("ts"),
                        key_fn=temporal.field("user"), ...)
    """
    def fn(rec):
        return rec.get(name)
    fn._column_key = name
    return fn


def column_key_of(fn: Any) -> Optional[str]:
    """The column name a callable extracts, when statically known
    (``_column_key`` marker), else None — the columnar router's
    resolvability test."""
    return getattr(fn, "_column_key", None)


# ---------------------------------------------------------------------------
# Table — columnar batch with per-column validity (the joined shape)
# ---------------------------------------------------------------------------


class Table:
    """Columnar record batch with optional per-column validity masks.

    The temporal tier's working shape: ``columns`` holds fully-valid
    numpy columns (safe for the bulk extract lane), ``masked_columns``
    holds ``(values, valid_mask)`` pairs for columns with per-row
    missingness (a left-outer join's unmatched right side), and
    ``null_fields`` names all-None columns. Iterating yields the same
    dicts a row-wise reader would build (None where masked/null), so
    every non-columnar consumer keeps working; columnar consumers read
    the arrays and never materialize a dict."""

    __slots__ = ("columns", "masked_columns", "null_fields", "_names",
                 "n_rows", "_dicts")

    def __init__(self, columns: Dict[str, np.ndarray],
                 masked_columns: Optional[
                     Dict[str, Tuple[np.ndarray, np.ndarray]]] = None,
                 null_fields: Sequence[str] = (),
                 names: Optional[Sequence[str]] = None,
                 n_rows: Optional[int] = None):
        self.columns = dict(columns)
        self.masked_columns = dict(masked_columns or {})
        self.null_fields = frozenset(null_fields)
        self._names = list(names) if names is not None else (
            list(self.columns) + list(self.masked_columns)
            + [f for f in self.null_fields
               if f not in self.columns and f not in self.masked_columns])
        if n_rows is not None:
            self.n_rows = int(n_rows)
        elif self.columns:
            self.n_rows = int(next(iter(self.columns.values())).shape[0])
        elif self.masked_columns:
            self.n_rows = int(
                next(iter(self.masked_columns.values()))[0].shape[0])
        else:
            self.n_rows = 0
        self._dicts: Optional[List[Dict[str, Any]]] = None

    def __len__(self) -> int:
        return self.n_rows

    def __bool__(self) -> bool:
        return self.n_rows > 0

    @staticmethod
    def _pyval(arr: np.ndarray, i: int) -> Any:
        v = arr[i]
        return v.item() if isinstance(v, np.generic) else v

    def _row(self, i: int) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for nm in self._names:
            if nm in self.null_fields:
                out[nm] = None
            elif nm in self.columns:
                out[nm] = self._pyval(self.columns[nm], i)
            else:
                vals, mask = self.masked_columns[nm]
                out[nm] = self._pyval(vals, i) if mask[i] else None
        return out

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._row(j) for j in range(*i.indices(self.n_rows))]
        n = self.n_rows
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self._row(i)

    def _materialize(self) -> List[Dict[str, Any]]:
        if self._dicts is None:
            lists = []
            for nm in self._names:
                if nm in self.null_fields:
                    lists.append([None] * self.n_rows)
                elif nm in self.columns:
                    lists.append(self.columns[nm].tolist())
                else:
                    vals, mask = self.masked_columns[nm]
                    lists.append([v if m else None for v, m
                                  in zip(vals.tolist(), mask.tolist())])
            names = self._names
            self._dicts = [dict(zip(names, row)) for row in zip(*lists)]
            if not lists:
                self._dicts = [{} for _ in range(self.n_rows)]
        return self._dicts

    def __iter__(self):
        return iter(self._materialize())

    def __repr__(self) -> str:
        return f"Table({self.n_rows} rows × {len(self._names)} cols)"


def table_from_records(records: Sequence[Mapping[str, Any]],
                       fields: Optional[Sequence[str]] = None) -> Table:
    """Build a :class:`Table` from dict records (first-seen field order):
    all-bool columns become bool, all-int int64, all-numeric float64,
    anything else an object column; ``None`` values become validity
    masks (all-None fields become ``null_fields``). The row-wise view of
    the result iterates as the same dicts that went in."""
    if fields is None:
        fields = []
        for r in records:
            for k in r:
                if k not in fields:
                    fields.append(k)
    n = len(records)
    cols: Dict[str, np.ndarray] = {}
    masked: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    nulls: List[str] = []
    for f in fields:
        vals = [r.get(f) for r in records]
        present = [v for v in vals if v is not None]
        if not present:
            nulls.append(f)
            continue
        if all(isinstance(v, bool) for v in present):
            arr = np.array([bool(v) if v is not None else False
                            for v in vals], dtype=bool)
        elif all(isinstance(v, int) and not isinstance(v, bool)
                 for v in present):
            arr = np.array([int(v) if v is not None else 0 for v in vals],
                           dtype=np.int64)
        elif all(isinstance(v, (int, float)) and not isinstance(v, bool)
                 for v in present):
            arr = np.array([float(v) if v is not None else np.nan
                            for v in vals], dtype=np.float64)
        else:
            arr = np.empty(n, dtype=object)
            arr[:] = vals
        if len(present) == n:
            cols[f] = arr
        else:
            masked[f] = (arr, np.array([v is not None for v in vals],
                                       dtype=bool))
    return Table(cols, masked, nulls, names=fields, n_rows=n)


def _is_table(records: Any) -> bool:
    """Anything exposing numpy ``columns`` (avro.ColumnarRecords, Table)
    takes the columnar lanes."""
    return getattr(records, "columns", None) is not None


def concat_tables(tables: Sequence[Any]) -> Table:
    """Row-concatenate columnar batches (same column names required).
    Columns that are masked/null in ANY part become masked in the result
    — validity is per part, never forgotten."""
    tables = list(tables)
    if not tables:
        return Table({})
    names = _names_of(tables[0])
    for t in tables[1:]:
        if _names_of(t) != names:
            raise TemporalError(
                "concat_tables: column names differ between parts")
    cols: Dict[str, np.ndarray] = {}
    masked: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    nulls: List[str] = []
    n = sum(len(t) for t in tables)
    for nm in names:
        parts = [_column_of(t, nm, len(t)) for t in tables]
        if all(p[0] is None for p in parts):
            nulls.append(nm)
            continue
        vals = np.concatenate([
            p[0] if p[0] is not None
            else np.zeros(len(t), dtype=next(
                q[0].dtype for q in parts if q[0] is not None))
            for p, t in zip(parts, tables)])
        if all(p[0] is not None and p[1] is None for p in parts):
            cols[nm] = vals
        else:
            mask = np.concatenate([
                (p[1] if p[1] is not None
                 else np.ones(len(t), bool) if p[0] is not None
                 else np.zeros(len(t), bool))
                for p, t in zip(parts, tables)])
            masked[nm] = (vals, mask)
    return Table(cols, masked, nulls, names=names, n_rows=n)


def _names_of(table: Any) -> List[str]:
    names = getattr(table, "_names", None)
    if names is not None:
        return list(names)
    return list(table.columns)


def _column_of(table: Any, name: str, n: int
               ) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """(values, validity) of one column — ``(None, None)`` means
    all-None (null field or absent from the batch; a row-wise
    ``rec.get`` would see None everywhere too)."""
    nulls = getattr(table, "null_fields", frozenset())
    if name in nulls:
        return None, None
    masked = getattr(table, "masked_columns", None) or {}
    if name in masked:
        vals, mask = masked[name]
        return vals, mask
    cols = table.columns
    if name in cols:
        return cols[name], None
    return None, None


# ---------------------------------------------------------------------------
# the columnar aggregation engine
# ---------------------------------------------------------------------------


class _FeatureSpec:
    """One raw feature's columnar fold plan (resolved identically to the
    row-wise reader: explicit aggregator, else the feature type's
    default monoid, else last-value)."""

    __slots__ = ("name", "ftype", "column", "aggregator", "window_ms",
                 "is_response")

    def __init__(self, name, ftype, column, aggregator, window_ms,
                 is_response):
        self.name = name
        self.ftype = ftype
        self.column = column
        self.aggregator = aggregator
        self.window_ms = window_ms
        self.is_response = is_response


def _resolve_specs(raw_features) -> List[_FeatureSpec]:
    """Per-feature fold plan, or raise :class:`TemporalError` when any
    feature's extractor is not statically column-keyed (a custom lambda
    the columnar tier cannot vectorize → the caller falls back
    row-wise)."""
    from .stages.generator import FeatureGeneratorStage
    from .utils.aggregators import aggregator_of
    specs = []
    for f in raw_features:
        gen = f.origin_stage
        if not isinstance(gen, FeatureGeneratorStage):
            raise TemporalError(f"{f.name!r} has no generator stage")
        col = column_key_of(gen.extract_fn)
        if col is None:
            raise TemporalError(
                f"{f.name!r} extracts via an opaque callable — the "
                "columnar engine needs a column-keyed extractor "
                "(from_column / temporal.field)")
        agg = gen.aggregator
        if agg is None:
            try:
                agg = aggregator_of(f.ftype)
            except ValueError:
                agg = None       # last-value, the row-wise default
        specs.append(_FeatureSpec(f.name, f.ftype, col, agg,
                                  gen.window_ms, f.is_response))
    return specs


def _group_keys(keys: np.ndarray):
    """Stable group-by: unique keys in ascending order (the row-wise
    reader's ``sorted(groups)``) plus, per key, the segment bounds into
    a stably key-sorted row order — original record order WITHIN each
    key is preserved, which is what keeps float fold order (and
    therefore bits) identical to the row-wise loop."""
    uniques, codes = np.unique(keys, return_inverse=True)
    order = np.argsort(codes, kind="stable")
    sorted_codes = codes[order]
    idx = np.arange(len(uniques))
    starts = np.searchsorted(sorted_codes, idx, side="left")
    ends = np.searchsorted(sorted_codes, idx, side="right")
    return uniques, codes, order, starts, ends


def _time_masks(ts_sorted: np.ndarray, cutoff_sorted: np.ndarray):
    """(predictor base mask, response mask, keep-always mask) over the
    key-sorted rows. ``cutoff_sorted`` is float with NaN meaning "no
    cutoff for this key" (everything folds on both sides — the row-wise
    contract). The pinned boundary: predictors ``ts < c``, responses
    ``ts > c`` — strictly after, the cutoff row lands in neither fold.
    A NaN EVENT TIME folds into BOTH sides: the row-wise loop's
    ``ts <= c`` / ``ts >= c`` guards are all False for NaN so no
    ``continue`` ever fires — parity is the contract, so the columnar
    masks keep those rows too (the returned keep-always mask also
    bypasses per-feature window filters, as row-wise NaN comparisons
    do)."""
    no_cut = np.isnan(cutoff_sorted) | np.isnan(ts_sorted)
    with np.errstate(invalid="ignore"):
        pred = no_cut | (ts_sorted < cutoff_sorted)
        resp = no_cut | (ts_sorted > cutoff_sorted)
    return pred, resp, no_cut


def _fold_segments(vals_sorted: Optional[np.ndarray],
                   valid_sorted: Optional[np.ndarray],
                   time_mask: np.ndarray,
                   key_index: Sequence[int],
                   starts: np.ndarray, ends: np.ndarray,
                   agg) -> List[Any]:
    """Fold one feature for every (kept) key: slice the key's segment,
    apply the time/validity mask, hand the surviving values — as the
    same Python list the row-wise loop builds — to the SAME monoid
    ``fold`` (or take the last value when the feature has no
    aggregator). Bit parity is by construction: same values, same
    order, same fold expression."""
    out: List[Any] = []
    for ki in key_index:
        s, e = int(starts[ki]), int(ends[ki])
        if vals_sorted is None:
            out.append(None)
            continue
        m = time_mask[s:e]
        if valid_sorted is not None:
            m = m & valid_sorted[s:e]
        vals = vals_sorted[s:e][m].tolist()
        if agg is None:
            out.append(vals[-1] if vals else None)
        else:
            out.append(agg.fold(vals))
    return out


def _per_key_cutoffs_conditional(reader, records, codes: np.ndarray,
                                 ts: np.ndarray, n_keys: int
                                 ) -> np.ndarray:
    """Per-key cutoff = min event time where the condition holds (NaN =
    no conditioning event). The predicate is an arbitrary callable, so
    it runs once over the (memoized) dict view; the min-merge is exact,
    so the vectorized reduction matches ``min(times)`` bit-for-bit."""
    cond = np.fromiter((bool(reader.condition_fn(r)) for r in records),
                       dtype=bool, count=len(records))
    cut = np.full(n_keys, np.inf)
    if cond.any():
        np.minimum.at(cut, codes[cond], ts[cond].astype(np.float64))
    cut[np.isinf(cut)] = np.nan
    return cut


def _columnar_aggregate(reader, records, raw_features) -> Any:
    """The engine: one grouping pass, shared masks, per-feature folds.
    Returns a ColumnStore bit-identical to the row-wise
    ``generate_store`` on the same records."""
    from .columns import ColumnStore, column_from_values
    from .readers.data_readers import ConditionalReader

    key_key = column_key_of(reader.key_fn)
    ts_key = column_key_of(reader.timestamp_fn)
    if key_key is None or ts_key is None:
        raise TemporalError(
            "key_fn/timestamp_fn are opaque callables — use "
            "temporal.field()/from_column-style extractors for the "
            "columnar path")
    specs = _resolve_specs(raw_features)
    n = len(records)
    keys, kmask = _column_of(records, key_key, n)
    ts, tmask = _column_of(records, ts_key, n)
    if keys is None or ts is None or kmask is not None or tmask is not None:
        raise TemporalError(
            f"key column {key_key!r} / timestamp column {ts_key!r} must "
            "be present and fully valid in the columnar batch")

    uniques, codes, order, starts, ends = _group_keys(keys)
    ts_sorted = np.asarray(ts, dtype=np.float64)[order]

    conditional = isinstance(reader, ConditionalReader)
    if conditional:
        cut = _per_key_cutoffs_conditional(reader, records, codes, ts,
                                           len(uniques))
        if reader.drop_if_no_condition:
            key_index = [int(i) for i in
                         np.flatnonzero(~np.isnan(cut))]
        else:
            key_index = list(range(len(uniques)))
    else:
        c = reader.cutoff.timestamp_ms
        cut = np.full(len(uniques), np.nan if c is None else float(c))
        key_index = list(range(len(uniques)))

    cutoff_sorted = cut[codes[order]]
    pred_base, resp_mask, no_cut = _time_masks(ts_sorted, cutoff_sorted)

    cols: Dict[str, Any] = {}
    window_masks: Dict[Any, np.ndarray] = {}
    for spec in specs:
        if spec.is_response:
            mask = resp_mask
        elif spec.window_ms is not None:
            wm = window_masks.get(spec.window_ms)
            if wm is None:
                with np.errstate(invalid="ignore"):
                    wm = pred_base & (
                        no_cut
                        | (ts_sorted >= cutoff_sorted - spec.window_ms))
                window_masks[spec.window_ms] = wm
            mask = wm
        else:
            mask = pred_base
        vals, valid = _column_of(records, spec.column, n)
        vals_sorted = vals[order] if vals is not None else None
        valid_sorted = valid[order] if valid is not None else None
        values = _fold_segments(vals_sorted, valid_sorted, mask,
                                key_index, starts, ends, spec.aggregator)
        cols[spec.name] = column_from_values(spec.ftype, values)
    _tally("aggregate_rows", n)
    _tally("aggregate_keys", len(key_index))
    return ColumnStore(cols, len(key_index))


def route_aggregate(reader, records, raw_features):
    """The auto-routing seam ``AggregateReader.generate_store`` calls:
    returns the columnar store, or None to fall back to the row-wise
    fold. Routing: the ``aggregateColumnar`` tri-state (off → None;
    auto → only columnar batches; forced on → a non-columnar source
    still returns None, tallied as a fallback). A columnar FAILURE
    (``temporal.aggregate`` fault site included) trips the
    ``temporal.columnar`` breaker and degrades row-wise — once the tier
    is known-bad the failing pass is not re-paid per read."""
    _ROUTE_STATE.contested = False
    mode = columnar_mode()
    if mode is False:
        return None
    if not _is_table(records):
        if mode is True:
            _tally("columnar_fallbacks")
            logger.warning(
                "aggregateColumnar=true but the source yields %s — "
                "row-wise fold serves", type(records).__name__)
        return None
    # a columnar batch with the engine available: from here on the
    # tier decision is real, whichever path serves
    _ROUTE_STATE.contested = True
    if mode == "auto" and aggregate_tier_hint() == "rowwise" \
            and _hint_stand_down():
        # the cost database measured the row-wise fold faster for this
        # workload shape (planner.aggregate_route_tier): the auto-route
        # defers to the measurement; an explicit aggregateColumnar=true
        # still forces columnar (the knob wins, TMG405 says so). Every
        # HINT_PROBE_EVERY-th pass still runs columnar so the
        # measurement stays live and the tier can flip back.
        _tally("hint_fallbacks")
        return None
    br = resilience.breaker("temporal.columnar")
    if not br.allow():
        _tally("columnar_fallbacks")
        return None
    import time as _time
    t0 = _time.perf_counter()
    try:
        resilience.inject("temporal.aggregate",
                          reader=type(reader).__name__,
                          rows=len(records))
        with telemetry.span("temporal:aggregate", rows=len(records)):
            store = _columnar_aggregate(reader, records, raw_features)
    except TemporalError:
        # structurally unroutable (opaque extractors): not a tier
        # failure AND not a tier success — record NEITHER, or an
        # unroutable reader interleaved with a failing one would keep
        # resetting the failure count (and a half-open probe handed to
        # an unroutable pass would falsely close the breaker; an
        # unreported probe re-arms after the reset timeout by design).
        # Also NOT a contested tier decision: the caller's row-wise
        # timing must not feed the cost db's rowwise slot — this reader
        # never had a columnar option, whatever its record type.
        _ROUTE_STATE.contested = False
        if mode is True:
            _tally("columnar_fallbacks")
        return None
    except Exception:  # lint: broad-except — columnar tier failure degrades to the row-wise fold, breaker-reported
        br.record_failure()
        _tally("columnar_fallbacks")
        telemetry.counter("temporal.columnar_fallbacks").inc()
        logger.exception("columnar aggregation failed; row-wise fold "
                         "serves (breaker %s)", br.state)
        return None
    br.record_success()
    _tally("columnar_aggregates")
    telemetry.counter("temporal.columnar_aggregates").inc()
    # feed the planner's cost database: the measured columnar tier cost
    # rides the SAME observe_phase → drain pipeline the fitstats/
    # transform tiers use, keyed phase:temporal.route_aggregate with
    # tier "columnar" (planner.aggregate_route_tier reads it back)
    from . import planner
    planner.observe_phase("temporal.route_aggregate", "columnar",
                          _time.perf_counter() - t0, len(records))
    return store


#: per-thread disposition of the LAST route_aggregate call (readers may
#: run concurrently on pipeline workers): ``contested`` is True only
#: when the columnar tier was a REAL option for that pass — rowwise
#: timings from passes with no columnar alternative (row-list sources,
#: forced-off mode, structurally unroutable extractors) must not reach
#: the cost database, or they poison the pooled per-tier s/krow the
#: auto-route hint compares (observe_phase's contract: report only
#: where the tier decision is contested)
_ROUTE_STATE = threading.local()


def last_route_contested() -> bool:
    """Whether this thread's last :func:`route_aggregate` call was a
    genuine columnar-vs-rowwise tier decision — the gate readers apply
    before feeding a row-wise fold timing to the cost database."""
    return bool(getattr(_ROUTE_STATE, "contested", False))


def tally_rowwise(n_rows: int, seconds: Optional[float] = None) -> None:
    """Count one row-wise aggregation pass (the fallback/legacy path),
    so the columnar-vs-rowwise split shows in every stamped doc.
    ``seconds`` (when the caller timed the fold AND the pass was a
    contested tier decision — see :func:`columnar_candidate`) feeds the
    planner's cost database as the ``rowwise`` half of the
    ``phase:temporal.route_aggregate`` tier decision."""
    _tally("rowwise_aggregates")
    _tally("aggregate_rows", n_rows)
    if seconds is not None:
        from . import planner
        planner.observe_phase("temporal.route_aggregate", "rowwise",
                              seconds, n_rows)


# ---------------------------------------------------------------------------
# parallel partial aggregation (inside the PR 9 decode workers)
# ---------------------------------------------------------------------------


class _Partial:
    """One table's partial aggregate: the file's key universe plus, per
    feature, the FILTERED (key, value) arrays in original record order —
    no folding, no per-key Python loop. Folding happens once after the
    ordered merge, so the float fold order (and the bits) match the
    serial pass; keeping the worker side purely vectorized is what lets
    N decode workers actually scale (numpy releases the GIL, per-key
    Python loops do not)."""

    __slots__ = ("keys", "filtered", "n_rows")

    def __init__(self, keys: np.ndarray,
                 filtered: List[Tuple[Optional[np.ndarray],
                                      Optional[np.ndarray]]],
                 n_rows: int):
        self.keys = keys            # unique keys present in this table
        self.filtered = filtered    # [per spec] -> (keys, values) arrays
        self.n_rows = n_rows


def _partial_aggregate(records, specs: List[_FeatureSpec], key_key: str,
                       ts_key: str, cutoff_ms: Optional[float]) -> _Partial:
    """Filter ONE table (runs inside a worker): vectorized cutoff /
    window / validity masks over the original row order — the grouping
    happens once, later, over the merged survivors."""
    n = len(records)
    keys, kmask = _column_of(records, key_key, n)
    ts, tmask = _column_of(records, ts_key, n)
    if keys is None or ts is None or kmask is not None or tmask is not None:
        raise TemporalError(
            f"key column {key_key!r} / timestamp column {ts_key!r} must "
            "be present and fully valid in the columnar batch")
    tsf = np.asarray(ts, dtype=np.float64)
    if cutoff_ms is None:
        pred_base = resp_mask = np.ones(n, dtype=bool)
        nan_ts = None
    else:
        c = float(cutoff_ms)
        # NaN event times fold into BOTH sides and bypass windows — the
        # row-wise loop's guards are all False for NaN (see _time_masks)
        nan_ts = np.isnan(tsf)
        pred_base = nan_ts | (tsf < c)
        resp_mask = nan_ts | (tsf > c)
    filtered: List[Tuple[Optional[np.ndarray], Optional[np.ndarray]]] = []
    for spec in specs:
        if spec.is_response:
            mask = resp_mask
        elif spec.window_ms is not None and cutoff_ms is not None:
            mask = pred_base & (
                nan_ts | (tsf >= float(cutoff_ms) - spec.window_ms))
        else:
            mask = pred_base
        vals, valid = _column_of(records, spec.column, n)
        if vals is None:
            filtered.append((None, None))
            continue
        if valid is not None:
            mask = mask & valid
        filtered.append((keys[mask], vals[mask]))
    return _Partial(np.unique(keys), filtered, n)


def _concat_parts(parts: List[Optional[np.ndarray]]) -> Optional[np.ndarray]:
    real = [p for p in parts if p is not None]
    if not real:
        return None
    return np.concatenate(real) if len(real) > 1 else real[0]


def _finalize_partials(partials: List[_Partial],
                       specs: List[_FeatureSpec]):
    """Ordered monoid merge: per feature, concatenate the survivors'
    (key, value) arrays in submission order (= record order of the
    serial pass), group with ONE stable argsort — within a key the
    concat order survives, so each key's value sequence is exactly what
    the serial fold sees — and fold once per key."""
    from .columns import ColumnStore, column_from_values
    all_keys = np.unique(_concat_parts([p.keys for p in partials])
                         if partials else np.zeros(0))
    n_keys = len(all_keys)
    cols: Dict[str, Any] = {}
    for j, spec in enumerate(specs):
        fk = _concat_parts([p.filtered[j][0] for p in partials])
        fv = _concat_parts([p.filtered[j][1] for p in partials])
        if fk is None or fv is None or not len(fk):
            if spec.aggregator is None:
                values = [None] * n_keys
            else:
                values = [spec.aggregator.fold([]) for _ in range(n_keys)]
            cols[spec.name] = column_from_values(spec.ftype, values)
            continue
        order = np.argsort(fk, kind="stable")
        sk = fk[order]
        sv = fv[order]
        starts = np.searchsorted(sk, all_keys, side="left")
        ends = np.searchsorted(sk, all_keys, side="right")
        values = []
        for ki in range(n_keys):
            vals = sv[int(starts[ki]):int(ends[ki])].tolist()
            if spec.aggregator is None:
                values.append(vals[-1] if vals else None)
            else:
                values.append(spec.aggregator.fold(vals))
        cols[spec.name] = column_from_values(spec.ftype, values)
    rows = sum(p.n_rows for p in partials)
    _tally("aggregate_rows", rows)
    _tally("aggregate_keys", n_keys)
    _tally("parallel_aggregates")
    telemetry.counter("temporal.parallel_aggregates").inc()
    return ColumnStore(cols, n_keys)


def aggregate_tables(tables: Sequence[Any], raw_features,
                     timestamp_fn, key_fn,
                     cutoff_ms: Optional[float] = None,
                     workers: Optional[int] = None):
    """Aggregate a sequence of columnar tables with a global cutoff,
    partial-aggregating each table on the pipeline's ordered worker
    pool (:func:`pipeline.map_ordered`) — filtering/grouping overlaps
    across tables while the consumer merges partials in submission
    order. Bit-identical to aggregating the concatenated table (and to
    the row-wise reader) by the ordered-merge construction."""
    from . import pipeline
    key_key = column_key_of(key_fn) if not isinstance(key_fn, str) \
        else key_fn
    ts_key = column_key_of(timestamp_fn) \
        if not isinstance(timestamp_fn, str) else timestamp_fn
    if key_key is None or ts_key is None:
        raise TemporalError("aggregate_tables needs column-keyed key/"
                            "timestamp extractors (temporal.field)")
    specs = _resolve_specs(raw_features)
    tables = list(tables)

    def work(t):
        resilience.inject("temporal.aggregate", rows=len(t))
        return _partial_aggregate(t, specs, key_key, ts_key, cutoff_ms)

    partials: List[_Partial] = []
    with telemetry.span("temporal:aggregate_tables", tables=len(tables)):
        for _t, part, exc in pipeline.map_ordered(
                work, tables, workers=workers, name="temporal-agg"):
            if exc is not None:
                raise exc
            partials.append(part)
    return _finalize_partials(partials, specs)


def aggregate_directory(path: str, raw_features, timestamp_fn, key_fn,
                        cutoff_ms: Optional[float] = None,
                        pattern: str = "*.avro",
                        workers: Optional[int] = None):
    """Decode + partial-aggregate every event file of a directory INSIDE
    the ordered worker pool (decode and aggregation overlap file IO —
    the tf.data map-stage shape), then merge/fold. Files are processed
    in sorted order, matching a serial read of the same directory."""
    from . import pipeline
    from .readers.avro import read_avro_table
    key_key = column_key_of(key_fn) if not isinstance(key_fn, str) \
        else key_fn
    ts_key = column_key_of(timestamp_fn) \
        if not isinstance(timestamp_fn, str) else timestamp_fn
    if key_key is None or ts_key is None:
        raise TemporalError("aggregate_directory needs column-keyed "
                            "key/timestamp extractors (temporal.field)")
    specs = _resolve_specs(raw_features)
    files = sorted(_glob.glob(os.path.join(path, pattern)))

    def work(fp):
        resilience.inject("temporal.aggregate", path=fp)
        return _partial_aggregate(read_avro_table(fp), specs, key_key,
                                  ts_key, cutoff_ms)

    partials: List[_Partial] = []
    with telemetry.span("temporal:aggregate_directory", files=len(files)):
        for _fp, part, exc in pipeline.map_ordered(
                work, files, workers=workers, name="temporal-agg"):
            if exc is not None:
                raise exc
            partials.append(part)
    return _finalize_partials(partials, specs)


def join_aggregate_directory(path: str, raw_features, right_records,
                             timestamp_fn, key_fn,
                             cutoff_ms: Optional[float] = None,
                             join_type: str = "left_outer",
                             pattern: str = "*.avro",
                             workers: Optional[int] = None,
                             right_key_fn=None):
    """The joined-then-aggregate composition on the worker pool: each
    event file decodes, hash-joins against the (small, broadcast) right
    table and partial-aggregates — all inside ``map_ordered`` workers —
    then partials merge/fold once. The per-file join is the same probe
    the whole-dataset join runs, so the result is bit-identical to
    joining the concatenated left table first."""
    from . import pipeline
    from .readers.avro import read_avro_table
    key_key = column_key_of(key_fn) if not isinstance(key_fn, str) \
        else key_fn
    ts_key = column_key_of(timestamp_fn) \
        if not isinstance(timestamp_fn, str) else timestamp_fn
    if key_key is None or ts_key is None:
        raise TemporalError("join_aggregate_directory needs column-keyed "
                            "key/timestamp extractors (temporal.field)")
    rk = right_key_fn or key_fn
    rkey = column_key_of(rk) if not isinstance(rk, str) else rk
    specs = _resolve_specs(raw_features)
    if not _is_table(right_records):
        # a plain list of dicts (the usual small dimension table) lifts
        # to a columnar Table so the vectorized probe works
        right_records = table_from_records(list(right_records))
    build = build_join_table(right_records, rkey or key_key)
    if not isinstance(build, _ColumnarBuildTable):
        # over the partition bound, masked key column, or columnar mode
        # forced off: the workers' vectorized probe/partial cannot run —
        # say so instead of crashing inside a worker; the bounded
        # spill-to-quarantine path lives in TemporalJoinReader
        raise TemporalError(
            "join_aggregate_directory needs a vectorizable build side "
            "(fully valid key column, unique keys within joinPartitions "
            "× joinTableMaxRows, columnar mode not forced off) — use "
            "TemporalJoinReader + AggregateReader for the bounded/spill "
            "path")
    files = sorted(_glob.glob(os.path.join(path, pattern)))

    def work(fp):
        # per-file decode → join → partial is idempotent pure compute
        # over one file: a transient failure rides READER_RETRY (the
        # documented temporal.join contract) instead of killing the
        # whole directory aggregate
        def attempt():
            resilience.inject("temporal.join", path=fp)
            joined = build.probe(read_avro_table(fp), key_key, join_type)
            return _partial_aggregate(joined, specs, key_key, ts_key,
                                      cutoff_ms)
        return resilience.READER_RETRY.call("temporal.join", attempt)

    partials: List[_Partial] = []
    with telemetry.span("temporal:join_aggregate", files=len(files)):
        for _fp, part, exc in pipeline.map_ordered(
                work, files, workers=workers, name="temporal-join"):
            if exc is not None:
                raise exc
            partials.append(part)
    return _finalize_partials(partials, specs)


# ---------------------------------------------------------------------------
# streaming hash join internals (TemporalJoinReader rides on these)
# ---------------------------------------------------------------------------


def _canonical_key(key: Any) -> str:
    """Canonical hash text of a join key, matching PYTHON DICT equality:
    ``1``, ``1.0``, ``True`` and ``np.float64(1.0)`` are the same dict
    key, so they must land in the same partition — hashing ``repr``
    directly would split a float-keyed probe side (avro doubles) from an
    int-keyed build side (JSON records) and silently unmatch every
    row."""
    if isinstance(key, (bool, np.bool_)):
        key = int(key)
    if isinstance(key, np.generic):
        key = key.item()
    if isinstance(key, float) and key.is_integer():
        key = int(key)
    return repr(key)


def partition_of(key: Any, n_partitions: int) -> int:
    """Consistent-hash partition of a join key (the fleet/canary blake2b
    routing discipline — stable across processes and runs, unlike
    ``hash()`` under PYTHONHASHSEED). Keys are canonicalized first so
    dict-equal keys of different numeric types share a partition."""
    h = hashlib.blake2b(_canonical_key(key).encode("utf-8", "replace"),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") % max(1, int(n_partitions))


class _ColumnarBuildTable:
    """Vectorized build side: sorted unique keys + the ORIGINAL row
    index of each key's last occurrence (the dict path's
    last-update-wins), probed via ``np.searchsorted``."""

    def __init__(self, table: Any, key_field: str):
        n = len(table)
        keys, kmask = _column_of(table, key_field, n)
        if keys is None or kmask is not None:
            raise TemporalError(
                f"join key column {key_field!r} must be present and "
                "fully valid on the build side")
        order = np.argsort(keys, kind="stable")
        sorted_keys = keys[order]
        uniq, counts = np.unique(sorted_keys, return_counts=True)
        last_sorted = np.cumsum(counts) - 1
        self.table = table
        self.key_field = key_field
        self.uniq = uniq
        self.last_row = order[last_sorted]
        self.n_keys = len(uniq)

    def probe(self, left: Any, key_field: str, join_type: str) -> Table:
        n = len(left)
        lk, lmask = _column_of(left, key_field, n)
        if lk is None or lmask is not None:
            raise TemporalError(
                f"join key column {key_field!r} must be present and "
                "fully valid on the probe side")
        if self.n_keys:
            pos = np.searchsorted(self.uniq, lk, side="left")
            posc = np.clip(pos, 0, self.n_keys - 1)
            matched = self.uniq[posc] == lk
            ridx = self.last_row[posc]
        else:
            matched = np.zeros(n, dtype=bool)
            ridx = np.zeros(n, dtype=np.int64)
        _tally("join_rows", n)
        _tally("join_matched", int(matched.sum()))
        _tally("join_unmatched", int(n - matched.sum()))

        left_names = _names_of(left)
        right_names = [nm for nm in _names_of(self.table)
                       if nm not in left_names]
        sel = np.flatnonzero(matched) if join_type == "inner" else None
        out_n = len(sel) if sel is not None else n

        cols: Dict[str, np.ndarray] = {}
        masked: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        nulls: List[str] = []
        for nm in left_names:                       # left wins shared names
            vals, valid = _column_of(left, nm, n)
            if vals is None:
                nulls.append(nm)
                continue
            v = vals[sel] if sel is not None else vals
            if valid is None:
                cols[nm] = v
            else:
                masked[nm] = (v, valid[sel] if sel is not None else valid)
        rn = len(self.table)
        for nm in right_names:
            vals, valid = _column_of(self.table, nm, rn)
            if vals is None or self.n_keys == 0:
                nulls.append(nm)
                continue
            take = ridx[sel] if sel is not None else ridx
            v = vals[take]
            ok = np.ones(out_n, dtype=bool) if sel is not None \
                else matched.copy()
            if valid is not None:
                ok &= valid[take]
            if ok.all():
                cols[nm] = v
            else:
                masked[nm] = (v, ok)
        return Table(cols, masked, nulls, names=left_names + right_names,
                     n_rows=out_n)


class _DictBuildTable:
    """Streaming build side: consistent-hash partitioned, BOUNDED
    per-partition hash tables; a NEW key arriving at a full partition
    spills its row to the dead-letter quarantine (kind ``records``,
    site ``temporal.join``) instead of growing the heap — the join
    stays memory-bounded and the loss is loud and replayable."""

    def __init__(self, records: Iterable[Mapping[str, Any]], key_fn,
                 partitions: int, max_rows: Optional[int]):
        self.partitions = max(1, int(partitions))
        self.tables: List[Dict[Any, Dict[str, Any]]] = [
            {} for _ in range(self.partitions)]
        spilled = 0
        for r in records:
            k = key_fn(r)
            t = self.tables[partition_of(k, self.partitions)]
            if k not in t and max_rows is not None \
                    and len(t) >= max_rows:
                spilled += 1
                resilience.quarantine(
                    "temporal.join",
                    f"join build table overflow (partition bound "
                    f"{max_rows})", kind="records", key=repr(k),
                    records=[dict(r)])
                continue
            t.setdefault(k, {}).update(r)
        if spilled:
            _tally("join_spilled_rows", spilled)
            telemetry.counter("temporal.join_spilled_rows").inc(spilled)

    def n_keys(self) -> int:
        return sum(len(t) for t in self.tables)

    def get(self, key: Any) -> Optional[Dict[str, Any]]:
        return self.tables[partition_of(key, self.partitions)].get(key)

    def probe(self, left_records, left_key_fn,
              join_type: str) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        matched = unmatched = 0
        for rec in left_records:
            k = left_key_fn(rec)
            r = self.get(k)
            if r is None:
                unmatched += 1
                if join_type == "inner":
                    continue
                out.append(dict(rec))
            else:
                matched += 1
                merged = dict(r)
                merged.update(rec)
                out.append(merged)
        _tally("join_rows", matched + unmatched)
        _tally("join_matched", matched)
        _tally("join_unmatched", unmatched)
        return out


def build_join_table(right_records, key_field_or_fn,
                     partitions: Optional[int] = None,
                     table_max_rows: Optional[int] = None):
    """Build the join's build-side table: vectorized when the right
    source is a columnar batch with a statically known key column (and
    within the partition bound), else the partitioned bounded dict
    tables. Both probe to the same output values."""
    kf = key_field_or_fn
    key_field = kf if isinstance(kf, str) else column_key_of(kf)
    p = join_partitions(partitions)
    cap = join_table_max_rows(table_max_rows)
    if _is_table(right_records) and key_field is not None \
            and columnar_mode() is not False:
        try:
            built = _ColumnarBuildTable(right_records, key_field)
        except TemporalError:
            built = None
        if built is not None:
            if cap is None or built.n_keys <= p * cap:
                _tally("columnar_joins")
                return built
            # over the bound: the dict path's per-partition spill is the
            # sanctioned memory-bounded behavior
    key_fn = kf if callable(kf) else field(key_field)
    return _DictBuildTable(right_records, key_fn, p, cap)


# ---------------------------------------------------------------------------
# cutoff leakage linting — TMG7xx
# ---------------------------------------------------------------------------


def _reader_chain(reader) -> List[Any]:
    """Every reader reachable through base/left/right wrappers (the
    aggregate-over-filtered-join compositions), root first."""
    out: List[Any] = []
    seen = set()
    stack = [reader]
    while stack:
        r = stack.pop()
        if r is None or id(r) in seen:
            continue
        seen.add(id(r))
        out.append(r)
        for attr in ("base", "left", "right"):
            stack.append(getattr(r, attr, None))
    return out


def _response_sources(responses) -> Dict[str, str]:
    """{source column: feature name} for response raw features whose
    extraction is statically column-keyed."""
    from .stages.generator import FeatureGeneratorStage
    out: Dict[str, str] = {}
    for r in responses:
        gen = r.origin_stage
        if not isinstance(gen, FeatureGeneratorStage):
            continue
        src = column_key_of(gen.extract_fn) or gen.extract_source
        if src:
            out.setdefault(str(src), r.name)
    return out


def check_temporal(reader, result_features) -> List[Any]:
    """Static cutoff-leakage rules (TMG7xx) over a workflow's reader +
    raw features — no data read, no reader I/O (the reader OBJECT is
    inspected, never polled). Returns lint ``Finding`` records; the
    graph checker folds them into the normal failOn/lintSuppress flow.
    See the module docstring for the pinned cutoff semantics."""
    from .lint import Finding
    from .readers.data_readers import (AggregateReader, ConditionalReader,
                                       JoinedDataReader, TemporalJoinReader)
    from .stages.generator import FeatureGeneratorStage

    findings: List[Any] = []
    raws: List[Any] = []
    seen = set()
    for f in result_features:
        for raw in f.raw_features():
            if id(raw) not in seen:
                seen.add(id(raw))
                raws.append(raw)
    responses = [f for f in raws if f.is_response]
    predictors = [f for f in raws if not f.is_response]
    chain = _reader_chain(reader)
    agg = next((r for r in chain if isinstance(r, AggregateReader)), None)
    joins = [r for r in chain
             if isinstance(r, (JoinedDataReader, TemporalJoinReader))]

    if agg is not None:
        conditional = isinstance(agg, ConditionalReader)
        if not conditional and agg.cutoff.timestamp_ms is None \
                and responses and predictors:
            # TMG701 — every predictor fold would see post-outcome rows:
            # the point-in-time discipline is the whole reason the
            # aggregating reader exists
            pnames = ", ".join(p.name for p in predictors)
            rnames = ", ".join(r.name for r in responses)
            findings.append(Finding(
                "TMG701",
                f"point-in-time aggregation with NO cutoff while "
                f"response(s) [{rnames}] fold from the same events: "
                f"predictor fold(s) [{pnames}] would see post-outcome "
                "rows — set CutOffTime.at(...) or use a conditional "
                "reader", feature=responses[0].name))
        for r in responses:
            gen = r.origin_stage
            if isinstance(gen, FeatureGeneratorStage) \
                    and gen.window_ms is not None:
                findings.append(Finding(
                    "TMG702",
                    f"response {r.name!r} declares an event-time window "
                    f"({gen.window_ms} ms): responses fold strictly "
                    "AFTER the cutoff, so a window reaches back across "
                    "it into the predictor window [cutoff - w, cutoff) "
                    "— drop the window or make the feature a predictor",
                    feature=r.name))

    if joins and responses:
        resp_srcs = _response_sources(responses)
        for j in joins:
            jkeys = set()
            kfield = getattr(j, "key_field", None)
            if kfield:
                jkeys.add(str(kfield))
            for side in ("left", "right"):
                side_reader = getattr(j, side, None)
                if side_reader is not None:
                    k = column_key_of(getattr(side_reader, "key_fn", None))
                    if k:
                        jkeys.add(str(k))
            for hit in sorted(jkeys & set(resp_srcs)):
                findings.append(Finding(
                    "TMG703",
                    f"join key {hit!r} is also the source field of "
                    f"response {resp_srcs[hit]!r}: a key derived from a "
                    "post-cutoff field routes outcome information into "
                    "the joined predictors", feature=resp_srcs[hit]))
    return findings
